"""DeepWalk graph embeddings.

Reference: deeplearning4j-graph graph/models/deepwalk/DeepWalk.java:31 —
random walks fed to a skip-gram trainer with hierarchical softmax over a
GraphHuffman tree (InMemoryGraphLookupTable). Here the walks ride the
SequenceVectors engine; the default objective is the reference's
hierarchical softmax, batched over padded Huffman paths (the tree is coded
by vertex occurrence frequency in the walks — proportional to the stationary
visit distribution, where the reference's GraphHuffman codes by degree; same
objective family, similarity behavior validated instead of bitwise parity).
``use_hierarchical_softmax=False`` selects batched negative sampling instead.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..nlp.sequence_vectors import SequenceVectors
from .graph import Graph, RandomWalkIterator


class DeepWalk:
    """API mirror of reference DeepWalk.Builder: vectorSize, windowSize,
    walkLength, learningRate; fit(graph) / fit(walk_iterator);
    vertex_vector / similarity."""

    def __init__(self, *, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 1,
                 learning_rate: float = 0.025, negative: int = 5,
                 epochs: int = 1, seed: int = 123,
                 use_hierarchical_softmax: bool = True):
        self.vector_size = vector_size
        self.window_size = window_size
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.learning_rate = learning_rate
        self.negative = negative
        self.epochs = epochs
        self.seed = seed
        self.use_hierarchical_softmax = use_hierarchical_softmax
        self._sv: Optional[SequenceVectors] = None
        self._n_vertices = 0

    def fit(self, graph_or_walks):
        """Train from a Graph (walks generated internally, reference
        DeepWalk.fit(IGraph)) or any iterable of vertex-id walks
        (reference fit(GraphWalkIterator))."""
        if isinstance(graph_or_walks, Graph):
            g = graph_or_walks
            self._n_vertices = g.num_vertices()
            walks: List[List[int]] = []
            for rep in range(self.walks_per_vertex):
                it = RandomWalkIterator(g, self.walk_length,
                                        seed=self.seed + rep)
                walks.extend(it)
        else:
            walks = [list(w) for w in graph_or_walks]
            self._n_vertices = 1 + max((max(w) for w in walks if w), default=-1)
        token_seqs = [[str(v) for v in w] for w in walks]
        self._sv = SequenceVectors(
            layer_size=self.vector_size, window=self.window_size,
            min_word_frequency=1, negative=self.negative,
            learning_rate=self.learning_rate, epochs=self.epochs,
            seed=self.seed,
            use_hierarchical_softmax=self.use_hierarchical_softmax)
        self._sv.fit(token_seqs)
        return self

    # ---- queries (reference getVertexVector / similarity) ----
    def vertex_vector(self, v: int) -> Optional[np.ndarray]:
        return self._sv.get_word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity(str(a), str(b))

    def verts_nearest(self, v: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self._sv.words_nearest(str(v), top_n)]

    @property
    def lookup_table(self) -> np.ndarray:
        """[n_vertices, vector_size] embedding matrix in vertex order
        (reference InMemoryGraphLookupTable.getVertexVectors)."""
        out = np.zeros((self._n_vertices, self.vector_size), np.float32)
        for v in range(self._n_vertices):
            vec = self.vertex_vector(v)
            if vec is not None:
                out[v] = vec
        return out
