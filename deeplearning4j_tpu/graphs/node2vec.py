"""node2vec: biased second-order random-walk embeddings.

Reference: models/node2vec/ — a stub in the reference snapshot (SURVEY.md
§2.3 notes "Stub/partial"); completed here per the published algorithm
(Grover & Leskovec 2016): return parameter ``p`` and in-out parameter ``q``
bias the walk between BFS-like (community) and DFS-like (structural)
exploration. Training rides DeepWalk's SequenceVectors path.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .deepwalk import DeepWalk
from .graph import Graph


class Node2VecWalkIterator:
    """Second-order biased walks: transition weight from (prev -> cur -> nxt)
    is 1/p when nxt == prev, 1 when nxt neighbors prev, 1/q otherwise."""

    def __init__(self, graph: Graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0, seed: int = 123):
        self.graph = graph
        self.walk_length = walk_length
        self.p = p
        self.q = q
        self.seed = seed
        self._epoch = 0
        self._nbr_sets = [set(graph._adj[v]) for v in range(graph.n)]

    def __iter__(self):
        rng = np.random.default_rng(self.seed + self._epoch)
        self._epoch += 1
        for start in rng.permutation(self.graph.n):
            walk = [int(start)]
            for _ in range(self.walk_length):
                cur = walk[-1]
                nbrs = self.graph._adj[cur]
                if not nbrs:
                    walk.append(cur)
                    continue
                if len(walk) == 1:
                    walk.append(int(nbrs[rng.integers(0, len(nbrs))]))
                    continue
                prev = walk[-2]
                prev_nbrs = self._nbr_sets[prev]
                w = np.asarray([1.0 / self.p if x == prev
                                else (1.0 if x in prev_nbrs else 1.0 / self.q)
                                for x in nbrs])
                walk.append(int(rng.choice(nbrs, p=w / w.sum())))
            yield walk

    def reset(self):
        pass


class Node2Vec(DeepWalk):
    """DeepWalk with node2vec's biased walk generator."""

    def __init__(self, *, p: float = 1.0, q: float = 1.0, **kwargs):
        super().__init__(**kwargs)
        self.p = p
        self.q = q

    def fit(self, graph_or_walks):
        if isinstance(graph_or_walks, Graph):
            walks: List[List[int]] = []
            self._n_vertices = graph_or_walks.num_vertices()
            for rep in range(self.walks_per_vertex):
                it = Node2VecWalkIterator(graph_or_walks, self.walk_length,
                                          self.p, self.q, seed=self.seed + rep)
                walks.extend(it)
            return super().fit(walks)
        return super().fit(graph_or_walks)
