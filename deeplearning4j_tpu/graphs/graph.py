"""Graph API: adjacency-list graph + random-walk iterators.

Reference: deeplearning4j-graph — api/IGraph.java SPI, graph/Graph.java
(adjacency-list impl), iterator/RandomWalkIterator.java (uniform walks with
restart-on-end), iterator/WeightedRandomWalkIterator.java (edge-weight
proportional transitions), NoEdgeHandling modes.
"""
from __future__ import annotations

from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class Graph:
    """Directed or undirected adjacency-list graph with optional edge
    weights (reference graph/Graph.java). Vertices are 0..n-1."""

    def __init__(self, n_vertices: int, directed: bool = False):
        self.n = n_vertices
        self.directed = directed
        self._adj: List[List[int]] = [[] for _ in range(n_vertices)]
        self._w: List[List[float]] = [[] for _ in range(n_vertices)]

    def add_edge(self, a: int, b: int, weight: float = 1.0):
        self._adj[a].append(b)
        self._w[a].append(weight)
        if not self.directed:
            self._adj[b].append(a)
            self._w[b].append(weight)
        return self

    def add_edges(self, edges: Iterable[Tuple[int, int]]):
        for e in edges:
            self.add_edge(*e)
        return self

    def num_vertices(self) -> int:
        return self.n

    def degree(self, v: int) -> int:
        return len(self._adj[v])

    def neighbors(self, v: int) -> List[int]:
        return list(self._adj[v])

    def weights(self, v: int) -> List[float]:
        return list(self._w[v])


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex (reference
    iterator/RandomWalkIterator.java). ``no_edge_handling``:
    'self_loop' (stay put, the reference's SELF_LOOP_ON_DISCONNECTED) or
    'cutoff' (truncate the walk, EXCEPTION_ON_DISCONNECTED is not useful
    here)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 123,
                 no_edge_handling: str = "self_loop",
                 weighted: bool = False):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self.weighted = weighted
        self._epoch = 0

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed + self._epoch)
        self._epoch += 1
        order = rng.permutation(self.graph.n)
        for start in order:
            walk = [int(start)]
            cur = int(start)
            for _ in range(self.walk_length):
                nbrs = self.graph._adj[cur]
                if not nbrs:
                    if self.no_edge_handling == "self_loop":
                        walk.append(cur)
                        continue
                    break   # cutoff
                if self.weighted:
                    w = np.asarray(self.graph._w[cur], np.float64)
                    cur = int(rng.choice(nbrs, p=w / w.sum()))
                else:
                    cur = int(nbrs[rng.integers(0, len(nbrs))])
                walk.append(cur)
            yield walk

    def reset(self):
        pass


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional walks (reference
    WeightedRandomWalkIterator.java)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 123,
                 no_edge_handling: str = "self_loop"):
        super().__init__(graph, walk_length, seed, no_edge_handling,
                         weighted=True)
