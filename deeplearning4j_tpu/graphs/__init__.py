from .deepwalk import DeepWalk
from .graph import Graph, RandomWalkIterator, WeightedRandomWalkIterator

__all__ = ["DeepWalk", "Graph", "RandomWalkIterator",
           "WeightedRandomWalkIterator"]
