from .deepwalk import DeepWalk
from .graph import Graph, RandomWalkIterator, WeightedRandomWalkIterator
from .node2vec import Node2Vec, Node2VecWalkIterator

__all__ = ["DeepWalk", "Graph", "Node2Vec", "Node2VecWalkIterator",
           "RandomWalkIterator", "WeightedRandomWalkIterator"]
