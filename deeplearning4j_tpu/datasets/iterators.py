"""Dataset iterator adapter family.

Reference: datasets/iterator/ — ExistingDataSetIterator,
MultipleEpochsIterator, EarlyTerminationDataSetIterator,
SamplingDataSetIterator, IteratorDataSetIterator, and the MultiDataSet
iterator family (AsyncMultiDataSetIterator etc.) used by multi-input
ComputationGraphs. The TPU build's iterator protocol is "iterable of
DataSet + reset()" (datasets/dataset.py); these adapters compose it the same
way the reference's 20+ wrappers compose DataSetIterator.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from .dataset import DataSet, DataSetIterator


@dataclass
class MultiDataSet:
    """Multi-input/multi-output sample batch (reference ND4J MultiDataSet):
    features/labels are LISTS of arrays, one per network input/output.
    Shares the DataSet attribute surface so solvers/iterators are agnostic."""
    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_mask: Optional[List[Optional[np.ndarray]]] = None
    labels_mask: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])


class ExistingDataSetIterator(DataSetIterator):
    """Wraps any re-iterable of DataSet/MultiDataSet (reference
    ExistingDataSetIterator)."""

    def __init__(self, iterable: Iterable):
        self.iterable = iterable

    def __iter__(self):
        return iter(self.iterable)

    def reset(self):
        if hasattr(self.iterable, "reset"):
            self.iterable.reset()


class MultipleEpochsIterator(DataSetIterator):
    """Repeats the base iterator n times as ONE epoch (reference
    MultipleEpochsIterator — used to stretch small datasets)."""

    def __init__(self, n_epochs: int, base: DataSetIterator):
        self.n = n_epochs
        self.base = base

    def __iter__(self):
        for i in range(self.n):
            yield from self.base
            if hasattr(self.base, "reset"):
                self.base.reset()

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of minibatches per epoch (reference
    EarlyTerminationDataSetIterator)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        if max_batches <= 0:
            raise ValueError("max_batches must be positive")
        self.base = base
        self.max_batches = max_batches

    def __iter__(self):
        for i, ds in enumerate(self.base):
            if i >= self.max_batches:
                break
            yield ds

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()


class SamplingDataSetIterator(DataSetIterator):
    """Draws ``n_batches`` random with-replacement minibatches from an
    in-memory dataset (reference SamplingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch_size: int, n_batches: int,
                 seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.n_batches = n_batches
        self.seed = seed
        self._epoch = 0

    def __iter__(self):
        rng = np.random.default_rng(self.seed + self._epoch)
        self._epoch += 1
        n = self.dataset.num_examples()
        for _ in range(self.n_batches):
            idx = rng.integers(0, n, self.batch_size)
            yield DataSet(
                self.dataset.features[idx], self.dataset.labels[idx],
                None if self.dataset.features_mask is None
                else self.dataset.features_mask[idx],
                None if self.dataset.labels_mask is None
                else self.dataset.labels_mask[idx])


class IteratorDataSetIterator(DataSetIterator):
    """Re-batches a stream of single examples (or small DataSets) into
    minibatches of ``batch_size`` (reference IteratorDataSetIterator)."""

    def __init__(self, make_iterator, batch_size: int):
        """``make_iterator``: zero-arg callable returning a fresh iterator of
        DataSet (so reset() can re-create it)."""
        self.make_iterator = make_iterator
        self.batch_size = batch_size

    def __iter__(self):
        buf: List[DataSet] = []
        count = 0
        for ds in self.make_iterator():
            buf.append(ds)
            count += ds.num_examples()
            if count >= self.batch_size:
                yield _concat(buf)
                buf, count = [], 0
        if buf:
            yield _concat(buf)


class ListMultiDataSetIterator(DataSetIterator):
    """Batches an in-memory MultiDataSet (the multi-input analogue of
    ListDataSetIterator; reference iterator/impl MultiDataSet iterators)."""

    def __init__(self, mds: MultiDataSet, batch_size: int):
        self.mds = mds
        self.batch_size = batch_size

    def __iter__(self):
        n = self.mds.num_examples()
        for s in range(0, n, self.batch_size):
            sl = slice(s, s + self.batch_size)

            def cut(arrs):
                if arrs is None:
                    return None
                return [None if a is None else a[sl] for a in arrs]

            yield MultiDataSet(cut(self.mds.features), cut(self.mds.labels),
                               cut(self.mds.features_mask),
                               cut(self.mds.labels_mask))


def _concat(batch: Sequence[DataSet]) -> DataSet:
    def cat(get):
        vals = [get(d) for d in batch]
        if any(v is None for v in vals):
            return None
        return np.concatenate(vals, axis=0)

    return DataSet(cat(lambda d: d.features), cat(lambda d: d.labels),
                   cat(lambda d: d.features_mask), cat(lambda d: d.labels_mask))
