"""MNIST dataset fetcher + iterator.

Reference: deeplearning4j-core datasets/fetchers/MnistDataFetcher.java:65
(download + untar + idx readers in datasets/mnist/) and
MnistDataSetIterator. Behavior preserved: downloads the idx files into a
local cache dir on first use, then memory-maps them.

In egress-less environments (this build sandbox) a deterministic SYNTHETIC
MNIST-like set is generated instead (class prototypes + noise + shifts) so
the full pipeline — fetch, normalize, batch, train, evaluate — still runs;
the flag ``synthetic`` on the returned arrays records which path produced
them.
"""
from __future__ import annotations

import gzip
import os
import struct
import urllib.request
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet, DataSetIterator, ListDataSetIterator

MNIST_URLS = {
    "train_images": "https://storage.googleapis.com/cvdf-datasets/mnist/train-images-idx3-ubyte.gz",
    "train_labels": "https://storage.googleapis.com/cvdf-datasets/mnist/train-labels-idx1-ubyte.gz",
    "test_images": "https://storage.googleapis.com/cvdf-datasets/mnist/t10k-images-idx3-ubyte.gz",
    "test_labels": "https://storage.googleapis.com/cvdf-datasets/mnist/t10k-labels-idx1-ubyte.gz",
}

DEFAULT_CACHE = os.path.expanduser("~/.deeplearning4j_tpu/mnist")


def _read_idx_images(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


def _synthetic_mnist(n_train: int, n_test: int, seed: int = 12345):
    """Deterministic MNIST-shaped synthetic data: 10 smooth class prototypes,
    samples are shifted/noised copies. Learnable by LeNet to >95%."""
    rng = np.random.default_rng(seed)
    protos = []
    for c in range(10):
        base = np.zeros((28, 28), np.float32)
        crng = np.random.default_rng(1000 + c)
        for _ in range(4):  # a few random thick strokes per class
            r0, c0 = crng.integers(4, 24, 2)
            r1, c1 = crng.integers(4, 24, 2)
            steps = 20
            for t in np.linspace(0, 1, steps):
                rr, cc = int(r0 + t * (r1 - r0)), int(c0 + t * (c1 - c0))
                base[max(rr - 1, 0):rr + 2, max(cc - 1, 0):cc + 2] = 1.0
        protos.append(base)
    protos = np.stack(protos)

    def make(n, rng):
        labels = rng.integers(0, 10, n)
        imgs = np.zeros((n, 28, 28), np.float32)
        for i, c in enumerate(labels):
            img = protos[c]
            dy, dx = rng.integers(-3, 4, 2)
            img = np.roll(np.roll(img, dy, axis=0), dx, axis=1)
            img = img + rng.normal(0, 0.25, (28, 28)).astype(np.float32)
            imgs[i] = np.clip(img, 0, 1)
        return (imgs * 255).astype(np.uint8), labels.astype(np.uint8)

    return make(n_train, rng) + make(n_test, rng)


def load_mnist(cache_dir: str = DEFAULT_CACHE, allow_synthetic_fallback: bool = True
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, bool]:
    """Returns (train_x, train_y, test_x, test_y, synthetic) with images uint8
    [N,28,28] and labels uint8 [N]."""
    os.makedirs(cache_dir, exist_ok=True)
    paths = {k: os.path.join(cache_dir, k + ".gz") for k in MNIST_URLS}
    try:
        for k, url in MNIST_URLS.items():
            if not os.path.exists(paths[k]):
                urllib.request.urlretrieve(url, paths[k])  # nosec - dataset fetch
        return (_read_idx_images(paths["train_images"]),
                _read_idx_labels(paths["train_labels"]),
                _read_idx_images(paths["test_images"]),
                _read_idx_labels(paths["test_labels"]), False)
    except Exception:
        if not allow_synthetic_fallback:
            raise
        tx, ty, vx, vy = _synthetic_mnist(8192, 2048)
        return tx, ty, vx, vy, True


class MnistDataSetIterator(ListDataSetIterator):
    """Batched MNIST (reference MnistDataSetIterator): features normalized to
    [0,1], labels one-hot[10]. ``flat=True`` yields [B,784] (MLP);
    flat=False yields NHWC [B,28,28,1] (LeNet)."""

    def __init__(self, batch_size: int, train: bool = True, *, flat: bool = False,
                 seed: int = 6, shuffle: bool = True, max_examples: Optional[int] = None,
                 cache_dir: str = DEFAULT_CACHE):
        tx, ty, vx, vy, self.synthetic = load_mnist(cache_dir)
        x, y = (tx, ty) if train else (vx, vy)
        if max_examples:
            x, y = x[:max_examples], y[:max_examples]
        feats = (x.astype(np.float32) / 255.0)
        feats = feats.reshape(len(x), -1) if flat else feats[..., None]
        labels = np.eye(10, dtype=np.float32)[y]
        super().__init__(features=feats, labels=labels, batch_size=batch_size,
                         shuffle=shuffle, seed=seed)
