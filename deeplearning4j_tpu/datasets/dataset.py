"""DataSet container + iterator protocol.

Reference: ND4J DataSet (features, labels, featuresMask, labelsMask) consumed
by MultiLayerNetwork.fit (nn/multilayer/MultiLayerNetwork.java:1125-1176) via
DataSetIterator; AsyncDataSetIterator background prefetch
(datasets/iterator/AsyncDataSetIterator.java:30).
"""
from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

import numpy as np


@dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None
    # optional per-example record metadata (reference RecordMetaData, carried
    # through evaluate() into Evaluation's prediction records); length = N
    metadata: Optional[List] = None

    def num_examples(self) -> int:
        f = self.features[0] if isinstance(self.features, (list, tuple)) else self.features
        return int(f.shape[0])


class DataSetIterator:
    """Minimal protocol: iterable of DataSet with reset()."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self):
        pass

    def prefetch(self, depth: int = 2, *, sharding=None, dtype=None):
        """Wrap this iterator in a DevicePrefetchIterator: a background
        thread ships each batch to the device (``jax.device_put``, sharded
        when ``sharding`` is given) so host->device transfer overlaps the
        previous step's compute. See datasets/prefetch.py."""
        from .prefetch import DevicePrefetchIterator
        return DevicePrefetchIterator(self, depth, sharding=sharding,
                                      dtype=dtype)


class ListDataSetIterator(DataSetIterator):
    """Batches an in-memory dataset (reference ListDataSetIterator)."""

    def __init__(self, data: Sequence[DataSet] = None, *, features=None, labels=None,
                 batch_size: int = 32, shuffle: bool = False, seed: int = 0):
        if data is None:
            n = features.shape[0]
            data = []
            for s in range(0, n, batch_size):
                data.append(DataSet(features[s:s + batch_size], labels[s:s + batch_size]))
        self.data = list(data)
        self.shuffle = shuffle
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        order = list(range(len(self.data)))
        if self.shuffle:
            self._rng.shuffle(order)
        for i in order:
            yield self.data[i]


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper (reference AsyncDataSetIterator).

    On TPU the host->device transfer overlaps the device step automatically
    (jax dispatches asynchronously); this wrapper overlaps host-side batch
    PREPARATION (augmentation, decoding) with device compute.
    """

    def __init__(self, base: DataSetIterator, queue_size: int = 4):
        self.base = base
        self.queue_size = queue_size

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        _SENTINEL = object()
        err: List[BaseException] = []

        def producer():
            try:
                for ds in self.base:
                    q.put(ds)
            except BaseException as e:   # surfaced on the consumer side
                err.append(e)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item

    def reset(self):
        self.base.reset()
