"""Dataset fetchers: Iris, CIFAR-10, Curves.

Reference: datasets/fetchers/{IrisDataFetcher, CurvesDataFetcher}.java,
datasets/iterator/impl/{IrisDataSetIterator, CifarDataSetIterator}.java and
base/IrisUtils.java. Iris ships in-package (iris.dat — Fisher's public-domain
measurements, the same resource the reference bundles). CIFAR-10 reads the
standard python-pickle batches from a local cache dir (this environment has
no network egress; a deterministic synthetic fallback keeps tests/demos
running, mirroring datasets/mnist.py's stance). Curves is the synthetic
curves regression set, generated deterministically.
"""
from __future__ import annotations

import os
import pickle
import tarfile
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet, ListDataSetIterator

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_CACHE = os.path.expanduser("~/.deeplearning4j_tpu/datasets")


# ----------------------------------------------------------------------- Iris
def load_iris(shuffle: bool = True, seed: int = 12345
              ) -> Tuple[np.ndarray, np.ndarray]:
    """(features [150,4] float32, one-hot labels [150,3]) — reference
    IrisDataFetcher.fetch + IrisUtils.loadIris."""
    rows = np.loadtxt(os.path.join(_HERE, "iris.dat"), delimiter=",",
                      dtype=np.float32)
    x, yi = rows[:, :4], rows[:, 4].astype(np.int64)
    if shuffle:
        order = np.random.default_rng(seed).permutation(len(x))
        x, yi = x[order], yi[order]
    y = np.eye(3, dtype=np.float32)[yi]
    return x, y


class IrisDataSetIterator(ListDataSetIterator):
    """Reference datasets/iterator/impl/IrisDataSetIterator.java."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 shuffle: bool = True, seed: int = 12345):
        x, y = load_iris(shuffle=shuffle, seed=seed)
        super().__init__(features=x[:num_examples], labels=y[:num_examples],
                         batch_size=batch_size)


# --------------------------------------------------------------------- CIFAR10
def _synthetic_cifar(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Class-dependent colored blobs: learnable, deterministic, clearly
    labeled synthetic (same stance as datasets/mnist.py:_synthetic_mnist)."""
    rng = np.random.default_rng(seed)
    yi = rng.integers(0, 10, n)
    x = rng.normal(0.45, 0.2, size=(n, 32, 32, 3)).astype(np.float32)
    for c in range(10):
        mask = yi == c
        # class-specific mean color + quadrant brightening
        x[mask, :, :, c % 3] += 0.25
        qh, qw = (c // 3) % 2, (c // 6) % 2
        x[mask, qh * 16:(qh + 1) * 16, qw * 16:(qw + 1) * 16, :] += 0.15
    np.clip(x, 0.0, 1.0, out=x)
    return x, np.eye(10, dtype=np.float32)[yi]


def load_cifar10(cache_dir: str = DEFAULT_CACHE, train: bool = True,
                 allow_synthetic_fallback: bool = True,
                 n_synthetic: int = 2048
                 ) -> Tuple[np.ndarray, np.ndarray, bool]:
    """NHWC [N,32,32,3] float32 in [0,1] + one-hot labels + ``synthetic``
    flag. Looks for the standard ``cifar-10-batches-py`` pickles (or the
    .tar.gz) under ``cache_dir`` (reference CifarDataSetIterator is
    DataVec-backed; binary parsing is the capability mirrored here)."""
    root = os.path.join(cache_dir, "cifar-10-batches-py")
    tgz = os.path.join(cache_dir, "cifar-10-python.tar.gz")
    if not os.path.isdir(root) and os.path.exists(tgz):
        with tarfile.open(tgz, "r:gz") as tf:
            tf.extractall(cache_dir, filter="data")  # refuse path traversal
    if os.path.isdir(root):
        names = ([f"data_batch_{i}" for i in range(1, 6)] if train
                 else ["test_batch"])
        xs, ys = [], []
        for nm in names:
            with open(os.path.join(root, nm), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.append(np.asarray(d[b"labels"], np.int64))
        x = (np.concatenate(xs).reshape(-1, 3, 32, 32)
             .transpose(0, 2, 3, 1).astype(np.float32) / 255.0)
        y = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
        return x, y, False
    if not allow_synthetic_fallback:
        raise FileNotFoundError(
            f"CIFAR-10 not found under {cache_dir!r} and downloads are "
            f"unavailable; place cifar-10-python.tar.gz there")
    x, y = _synthetic_cifar(n_synthetic, seed=7 if train else 11)
    return x, y, True


class Cifar10DataSetIterator(ListDataSetIterator):
    def __init__(self, batch_size: int = 128, *, train: bool = True,
                 cache_dir: str = DEFAULT_CACHE, num_examples: Optional[int] = None,
                 allow_synthetic_fallback: bool = True):
        x, y, self.synthetic = load_cifar10(cache_dir, train,
                                            allow_synthetic_fallback)
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        super().__init__(features=x, labels=y, batch_size=batch_size)


# ---------------------------------------------------------------------- Curves
def load_curves(n: int = 1024, resolution: int = 28, seed: int = 12345
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic curves for unsupervised pretraining (reference
    CurvesDataFetcher downloads curves.ser — parametric 2-D curves rendered
    to 28x28 images; features == labels, an autoencoder dataset). Generated
    deterministically: random cubic Bezier curves rasterized with gaussian
    pen strokes."""
    rng = np.random.default_rng(seed)
    t = np.linspace(0.0, 1.0, 64)[:, None]
    grid = np.linspace(0.0, 1.0, resolution)
    out = np.zeros((n, resolution, resolution), np.float32)
    for i in range(n):
        p = rng.random((4, 2))    # control points in [0,1]^2
        curve = ((1 - t) ** 3 * p[0] + 3 * (1 - t) ** 2 * t * p[1]
                 + 3 * (1 - t) * t ** 2 * p[2] + t ** 3 * p[3])  # [64,2]
        dx = grid[None, :] - curve[:, 0:1]
        dy = grid[None, :] - curve[:, 1:2]
        img = np.exp(-(dx[:, None, :] ** 2 + dy[:, :, None] ** 2) / (2 * 0.03 ** 2))
        out[i] = img.max(axis=0)
    flat = out.reshape(n, -1)
    return flat, flat.copy()     # features == labels (reconstruction target)


class CurvesDataSetIterator(ListDataSetIterator):
    def __init__(self, batch_size: int = 128, num_examples: int = 1024,
                 resolution: int = 28, seed: int = 12345):
        x, y = load_curves(num_examples, resolution, seed)
        super().__init__(features=x, labels=y, batch_size=batch_size)


# ------------------------------------------------------------------------ LFW
def _synthetic_lfw(n: int, n_people: int, h: int, w: int, seed: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-person base 'face' + per-image noise: identity-learnable,
    deterministic, clearly synthetic (same stance as _synthetic_cifar)."""
    rng = np.random.default_rng(seed)
    yi = rng.integers(0, n_people, n)
    base = rng.normal(0.5, 0.18, size=(n_people, h, w, 3))
    x = base[yi] + rng.normal(0.0, 0.06, size=(n, h, w, 3))
    x = np.clip(x, 0.0, 1.0).astype(np.float32)
    return x, np.eye(n_people, dtype=np.float32)[yi]


def load_lfw(cache_dir: str = DEFAULT_CACHE, *, height: int = 64,
             width: int = 64, num_people: Optional[int] = None,
             min_images_per_person: int = 2,
             allow_synthetic_fallback: bool = True, n_synthetic: int = 256,
             n_synthetic_people: int = 5
             ) -> Tuple[np.ndarray, np.ndarray, list, bool]:
    """Labeled Faces in the Wild (reference
    datasets/fetchers/LFWDataFetcher.java: downloads+untars the lfw archive
    of person-named jpg directories, labels = person identities, images
    scaled to the requested dims).

    Looks for the standard ``lfw/<person_name>/*.jpg`` tree (or ``lfw.tgz``)
    under ``cache_dir`` — no network egress in this environment, so the
    archive must be pre-placed; otherwise a deterministic synthetic fallback
    keeps demos/tests running. Returns (x [N,h,w,3] float32 in [0,1],
    one-hot labels, person_names, synthetic_flag). People are filtered to
    those with >= ``min_images_per_person`` images (the reference's subset
    behavior) and truncated to ``num_people`` most-photographed identities.
    """
    root = os.path.join(cache_dir, "lfw")
    tgz = os.path.join(cache_dir, "lfw.tgz")
    if not os.path.isdir(root) and os.path.exists(tgz):
        with tarfile.open(tgz, "r:gz") as tf:
            tf.extractall(cache_dir, filter="data")  # refuse path traversal
    if os.path.isdir(root):
        from PIL import Image
        people = []
        for name in sorted(os.listdir(root)):
            pdir = os.path.join(root, name)
            if not os.path.isdir(pdir):
                continue
            files = sorted(f for f in os.listdir(pdir)
                           if f.lower().endswith((".jpg", ".jpeg", ".png")))
            if len(files) >= min_images_per_person:
                people.append((name, pdir, files))
        people.sort(key=lambda t: -len(t[2]))
        if num_people:
            people = people[:num_people]
        people.sort(key=lambda t: t[0])
        if not people:
            raise FileNotFoundError(
                f"LFW tree at {root!r} has no identity with >= "
                f"{min_images_per_person} images "
                f"(min_images_per_person filter) — lower the threshold or "
                f"check the directory layout (lfw/<person_name>/*.jpg)")
        xs, yi, names = [], [], []
        for label, (name, pdir, files) in enumerate(people):
            names.append(name)
            for f in files:
                img = Image.open(os.path.join(pdir, f)).convert("RGB")
                img = img.resize((width, height), Image.BILINEAR)
                xs.append(np.asarray(img, np.float32) / 255.0)
                yi.append(label)
        x = np.stack(xs)
        y = np.eye(len(people), dtype=np.float32)[np.asarray(yi)]
        return x, y, names, False
    if not allow_synthetic_fallback:
        raise FileNotFoundError(
            f"LFW not found under {cache_dir!r} and downloads are "
            f"unavailable; place lfw.tgz there")
    x, y = _synthetic_lfw(n_synthetic, n_synthetic_people, height, width,
                          seed=23)
    return x, y, [f"person_{i}" for i in range(n_synthetic_people)], True


class LFWDataSetIterator(ListDataSetIterator):
    """Reference datasets/iterator/impl/LFWDataSetIterator.java."""

    def __init__(self, batch_size: int = 32, *, height: int = 64,
                 width: int = 64, num_people: Optional[int] = None,
                 num_examples: Optional[int] = None,
                 cache_dir: str = DEFAULT_CACHE,
                 allow_synthetic_fallback: bool = True, shuffle: bool = True,
                 seed: int = 12345):
        x, y, self.people, self.synthetic = load_lfw(
            cache_dir, height=height, width=width, num_people=num_people,
            allow_synthetic_fallback=allow_synthetic_fallback)
        if shuffle:
            order = np.random.default_rng(seed).permutation(len(x))
            x, y = x[order], y[order]
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        super().__init__(features=x, labels=y, batch_size=batch_size)
