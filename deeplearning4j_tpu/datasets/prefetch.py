"""Device-side prefetch: overlap host->device transfer with device compute.

Reference: datasets/iterator/AsyncDataSetIterator.java:30 prefetches on the
HOST; the reference's ETL discipline (PerformanceListener.java:111,178
reporting lastEtlTime per iteration) treats the feed path as a first-class
perf concern. On TPU the missing half is the host->device hop: a batch
shipped synchronously inside the step pays the full transfer latency
serially (BENCH_r05: a 407 ms/step transfer floor flattened the piped
ResNet-50 row to 0.008x the device-resident rate). JAX's async dispatch
makes the fix cheap — ``jax.device_put`` returns immediately while the
copy proceeds — so a background thread that ships batch N+1 while step N
computes hides the transfer entirely whenever step time exceeds the
transfer floor (the overlap discipline of SparkNet, arXiv:1511.06051, and
the weight-update sharding work, arXiv:2004.13336).

``DevicePrefetchIterator`` wraps any ``DataSetIterator`` and keeps
``depth`` batches in flight ON DEVICE. With a ``sharding``
(``NamedSharding``), ``device_put`` lands each batch pre-sharded, so
data-parallel training consumes its per-device shards with no gather or
reshard inside the jitted step. The consumer side measures the time it
actually BLOCKED waiting for a device batch — ``last_wait_ms`` /
``total_wait_ms`` — which is the honest per-iteration ETL tax (zero when
the pipeline keeps up), surfaced through
``optimize.listeners.PerformanceListener``.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np

from ..telemetry import get_registry
from .dataset import DataSet, DataSetIterator
from .iterators import MultiDataSet


_NEVER_REUSE = object()    # slot sentinel: its buffer is aliased by a
                           # device array and must never be overwritten


def _definitely_copied(shipped, buf: np.ndarray) -> bool:
    """Did ``device_put`` genuinely COPY ``buf``? The CPU backend is
    zero-copy for suitably-aligned numpy buffers (the returned array
    ALIASES the source — the same property that makes the
    HostSyncDetector's transfer guard inert there), and the aliasing is
    per-buffer (alignment-dependent), so this must be checked on the
    actual shipped array, not probed per process. Host->accelerator
    transfers always copy; on a single CPU device the buffer pointers
    tell; anything unprovable counts as aliased (no reuse —
    correctness first)."""
    try:
        if all(d.platform != "cpu" for d in shipped.devices()):
            return True
        return shipped.unsafe_buffer_pointer() != buf.ctypes.data
    except Exception:
        return False


class _StagingPool:
    """Reusable host staging buffers for the float-cast path.

    Without it the producer allocates a fresh cast buffer for EVERY batch
    (``astype``) — at ResNet-50 batch sizes that is ~25MB of fresh pages
    per batch on the ship path (the ``resnet50_piped`` row measured
    0.047 GB/s through it). Slot-reuse safety is two-layered:
    ``device_put``'s source must stay intact until the transfer lands, so
    a slot blocks on the device array it last fed before overwriting — a
    no-op in steady state (that transfer is ``slots`` batches old by the
    time the slot rotates back), real back-pressure when the device falls
    behind. And a slot whose shipped array cannot be PROVEN a copy
    (zero-copy CPU aliasing, multi-shard arrays) is retired instead of
    reused — its buffer is leaked to the device array and a fresh one is
    allocated, which degrades exactly to the old per-batch-allocation
    behavior, never to corruption.
    """

    __slots__ = ("slots", "_pools", "_rr", "allocations", "pending_bytes")

    def __init__(self, slots: int):
        self.slots = max(2, int(slots))
        self._pools = {}    # (shape, dtype.str) -> [[buf, last_shipped]]
        self._rr = {}
        self.allocations = 0    # distinct buffers ever allocated (tests)
        self.pending_bytes = 0  # host bytes of the batch being shipped

    def stage(self, a: np.ndarray, dtype) -> list:
        """Cast-copy ``a`` into a pool slot; returns the slot (slot[0] is
        the buffer). Call ``mark(slot, shipped)`` after device_put."""
        key = (a.shape, np.dtype(dtype).str)
        pool = self._pools.setdefault(key, [])
        if len(pool) < self.slots:
            slot = [np.empty(a.shape, dtype), None]
            self.allocations += 1
            pool.append(slot)
        else:
            i = self._rr.get(key, 0)
            self._rr[key] = (i + 1) % self.slots
            slot = pool[i]
            if slot[1] is _NEVER_REUSE:
                # previous occupant aliased this buffer: retire it
                slot[0] = np.empty(a.shape, dtype)
                self.allocations += 1
                slot[1] = None
            elif slot[1] is not None:
                slot[1].block_until_ready()   # transfer landed: safe now
                slot[1] = None
        np.copyto(slot[0], a, casting="unsafe")
        return slot

    def mark(self, slot: list, shipped) -> None:
        slot[1] = (shipped if _definitely_copied(shipped, slot[0])
                   else _NEVER_REUSE)


class DevicePrefetchIterator(DataSetIterator):
    """Background-thread device prefetch wrapper.

    The producer thread pulls host batches from ``base`` (so host-side
    decode/augmentation overlaps too — subsumes AsyncDataSetIterator),
    ships every array with ``jax.device_put`` and enqueues the resulting
    device-resident DataSet into a queue of ``depth`` slots. The bounded
    queue is the back-pressure contract: at most ``depth`` batches sit
    ready plus one in the producer's hands, so a live stream feeding the
    base iterator blocks its publishers exactly as it would unwrapped.

    ``dtype``: optional float dtype every floating array is cast to on the
    HOST before shipping (integer arrays — token ids, uint8 image wire
    format — pass through, same rule as the solver's feed cast). Shipping
    uint8 and normalizing on device cuts wire traffic 4x vs f32. The cast
    goes through a reusable staging-buffer pool (``depth+2`` rotating
    slots per shape/dtype) instead of a fresh ``astype`` allocation per
    batch; a slot is only overwritten after its previous transfer landed.

    Bandwidth observability: the producer takes a BLOCKING transfer
    sample on the first batch of each epoch and every 64th after, and
    publishes the measured GB/s as the ``prefetch.host_to_device_gbps``
    telemetry gauge (also on ``self.host_to_device_gbps``) — a
    transport-limited feed path is attributed, not guessed.

    ``sharding``: optional ``jax.sharding.Sharding`` (or per-leaf target
    accepted by ``device_put``). When the leading dim of a batch does not
    tile the sharding (a remainder batch), the batch ships unsharded
    rather than failing mid-epoch.

    Early exit is clean: breaking out of (or erroring inside) the consuming
    loop closes the generator, which signals the producer to stop; the
    producer rechecks the stop flag on every queue-full tick and every
    base batch, so no thread is left shipping batches nobody will take.
    Exceptions raised by ``base`` surface in the consumer.

    Caveats shared with any prefetch (incl. the host AsyncDataSetIterator):
    batches already pulled from ``base`` but not yet consumed at an early
    abort are dropped — for a live stream that is up to ``depth`` + 1
    samples; and a base whose ``__iter__`` can block INDEFINITELY (a
    StreamingDataSetIterator with idle publishers) keeps its daemon
    producer parked inside the base until the next batch or end-of-stream,
    since the stop flag is only observable between base yields.
    """

    _TICK = 0.05   # stop-signal poll interval for a blocked producer

    def __init__(self, base: DataSetIterator, depth: int = 2, *,
                 sharding=None, dtype=None):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.base = base
        self.depth = depth
        self.sharding = sharding
        self.dtype = None if dtype is None else np.dtype(dtype)
        self.last_wait_ms = 0.0     # consumer block time for the last batch
        self.total_wait_ms = 0.0    # cumulative over the current epoch
        self.batches = 0            # batches yielded in the current epoch
        # measured host->device bandwidth (GB/s) from the periodic blocking
        # samples in the producer; 0.0 until the first sample lands
        self.host_to_device_gbps = 0.0
        # cast staging buffers rotate across depth+2 slots (depth in the
        # queue + one in the producer's hands + one being consumed).
        # Each __iter__ builds its OWN pool (held by the producer closure;
        # this attribute tracks the newest for introspection): a stale
        # producer from a broken-out-of epoch can outlive stop.set() by
        # one batch, and two producers sharing slots could overwrite a
        # buffer whose transfer is still in flight.
        self._staging = _StagingPool(depth + 2)

    # ------------------------------------------------------------- shipping
    def _put_array(self, a, pool):
        """Host cast (floats -> self.dtype, through the reusable staging
        pool) + async device_put."""
        import jax
        if a is None:
            return None
        slot = None
        if not isinstance(a, jax.Array):
            a = np.asarray(a)
            if (self.dtype is not None and a.dtype.kind == "f"
                    and a.dtype != self.dtype):
                slot = pool.stage(a, self.dtype)
                a = slot[0]
            pool.pending_bytes += a.nbytes
            if slot is not None:
                shipped = self._put_host(a)
                pool.mark(slot, shipped)
                return shipped
        return self._put_host(a)

    def _put_host(self, a):
        import jax
        if self.sharding is not None:
            # explicit tiling probe (host-only shape math): a remainder
            # batch that doesn't tile the mesh ships unsharded — the
            # consuming jit reshards (or rejects) it exactly as it would
            # have without prefetch. A sharding that DOES tile but is
            # otherwise misconfigured (wrong mesh/devices) is NOT caught
            # here: device_put raises loudly rather than silently
            # degrading every batch to the unsharded path.
            tiles = True
            try:
                self.sharding.shard_shape(np.shape(a))
            except (ValueError, IndexError):
                tiles = False
            if tiles:
                return jax.device_put(a, self.sharding)
        return jax.device_put(a)

    def _put_any(self, v, pool):
        if isinstance(v, (list, tuple)):    # MultiDataSet-style per-input lists
            return [self._put_array(u, pool) for u in v]
        return self._put_array(v, pool)

    def _ship(self, ds, pool):
        """One host batch -> the same batch with device-resident arrays."""
        if isinstance(ds, MultiDataSet):
            return MultiDataSet(
                self._put_any(ds.features, pool),
                self._put_any(ds.labels, pool),
                None if ds.features_mask is None
                else self._put_any(ds.features_mask, pool),
                None if ds.labels_mask is None
                else self._put_any(ds.labels_mask, pool))
        return DataSet(self._put_any(ds.features, pool),
                       self._put_any(ds.labels, pool),
                       self._put_any(ds.features_mask, pool),
                       self._put_any(ds.labels_mask, pool),
                       metadata=getattr(ds, "metadata", None))

    # ------------------------------------------------------------ iteration
    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        err: List[BaseException] = []
        _SENTINEL = object()
        self.last_wait_ms = 0.0
        self.total_wait_ms = 0.0
        self.batches = 0

        def offer(item) -> bool:
            """put() that gives up when the consumer went away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=self._TICK)
                    return True
                except queue.Full:
                    continue
            return False

        # Telemetry (telemetry/): ship latency + consumer stall histograms
        # and a queue-depth gauge replace the ad-hoc etl_wait_ms plumbing
        # as the shared reporting surface (the attributes below stay for
        # the PerformanceListener contract). All host-side clock reads —
        # nothing touches the in-flight device buffers.
        reg = get_registry()

        # this iteration's private staging pool: the producer closure owns
        # it, so a stale producer still draining from a previous __iter__
        # keeps ITS pool and can never corrupt this epoch's slots
        pool = _StagingPool(self.depth + 2)
        self._staging = pool

        def producer():
            import jax
            n_shipped = 0
            try:
                for ds in self.base:
                    if stop.is_set():
                        return
                    t_ship = time.perf_counter()
                    pool.pending_bytes = 0
                    shipped = self._ship(ds, pool)
                    # ship_ms observed BEFORE any blocking sample below, so
                    # the histogram (and its p99) measures the async
                    # dispatch path every batch, never the sampled wait
                    reg.histogram("prefetch.ship_ms").observe(
                        (time.perf_counter() - t_ship) * 1e3)
                    # periodic BLOCKING bandwidth sample (first batch of the
                    # epoch, then every 64th): device_put is async, so the
                    # unblocked ship time measures dispatch, not transfer —
                    # waiting for completion on a sampled batch gives the
                    # honest GB/s without serializing the steady state
                    if n_shipped % 64 == 0 and pool.pending_bytes:
                        # every array whose bytes were counted above —
                        # masks included, or the GB/s would overstate
                        jax.block_until_ready(
                            [v for v in (shipped.features, shipped.labels,
                                         shipped.features_mask,
                                         shipped.labels_mask)
                             if v is not None])
                        dt = time.perf_counter() - t_ship
                        if dt > 0:
                            self.host_to_device_gbps = \
                                pool.pending_bytes / dt / 1e9
                            if reg.enabled:
                                reg.gauge("prefetch.host_to_device_gbps") \
                                    .set(self.host_to_device_gbps)
                    n_shipped += 1
                    if not offer(shipped):
                        return
            except BaseException as e:     # surfaced on the consumer side
                err.append(e)
            finally:
                offer(_SENTINEL)

        t = threading.Thread(target=producer, daemon=True,
                             name="device-prefetch")
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                wait_ms = (time.perf_counter() - t0) * 1e3
                if item is _SENTINEL:
                    if err:
                        raise err[0]
                    return
                self.last_wait_ms = wait_ms
                self.total_wait_ms += wait_ms
                self.batches += 1
                if reg.enabled:
                    reg.histogram("prefetch.wait_ms").observe(wait_ms)
                    reg.gauge("prefetch.queue_depth").set(q.qsize())
                    reg.counter("prefetch.batches").inc()
                yield item
        finally:
            # break / exception / exhaustion: stop the producer and let it
            # notice within one tick (it polls `stop` on every queue-full
            # wait and before shipping each batch)
            stop.set()
            while True:                    # unblock a producer mid-put
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            # short, best-effort join: the producer notices the stop within
            # one tick unless it is parked inside a base that blocks
            # indefinitely (live stream, idle publishers) — the daemon
            # thread then dies with the process instead of stalling the
            # consumer here
            t.join(timeout=1.0)

    def etl_wait_ms_per_batch(self) -> float:
        """Mean consumer-side ETL wait over the current/last epoch."""
        return self.total_wait_ms / self.batches if self.batches else 0.0

    def windows(self, k: int):
        """Window mode: yield ``BatchWindow``s of ``k`` same-shape
        device-resident batches (the feed unit of the fused multi-step
        training path, ``fit(..., steps_per_dispatch=k)``), re-using the
        existing depth-bounded producer queue — the window is assembled
        from batches that were already shipped in the background, so
        windowing adds no transfer latency, only the ``jnp.stack``
        dispatch. Ragged/unstackable groups fall out as bare DataSets
        (see ``iter_windows``)."""
        return iter_windows(self, k)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()


class BatchWindow:
    """K same-shape batches destined for ONE fused K-step dispatch.

    Holds the individual ``DataSet``s (listeners still see per-step batch
    sizes) plus lazily-stacked ``[K, ...]`` feed arrays for the
    ``lax.scan`` training program. Stacking runs through ``jnp.stack`` on
    already-device-resident arrays, so it is one async dispatch, not a
    host round-trip.
    """

    __slots__ = ("datasets", "_stacked")

    def __init__(self, datasets):
        self.datasets = list(datasets)
        self._stacked = None

    def __len__(self):
        return len(self.datasets)

    def num_examples(self) -> int:
        return sum(d.num_examples() for d in self.datasets)

    def stacked(self, cast=None):
        """(xs, ys, lmasks, fmasks) stacked on a new leading K axis;
        masks are None when absent from every member batch. ``cast`` is
        applied per-array before stacking (the Solver's feed-boundary
        cast, so the fused path casts exactly like the per-step path)."""
        if self._stacked is None:
            import jax.numpy as jnp
            cast = cast if cast is not None else (lambda a: a)

            def stack(field):
                vals = [getattr(d, field) for d in self.datasets]
                if vals[0] is None:
                    return None
                return jnp.stack([cast(v) for v in vals])

            self._stacked = (stack("features"), stack("labels"),
                             stack("labels_mask"), stack("features_mask"))
        return self._stacked


def _window_stackable(group) -> bool:
    """Host-only metadata probe: can these batches be stacked into one
    [K, ...] feed? Requires single-array features/labels (multi-input
    MultiDataSet batches fall back to per-step), identical shapes, and
    consistent mask presence/shape across the group."""
    ref = group[0]
    if isinstance(ref, MultiDataSet):
        return False
    for field in ("features", "labels", "labels_mask", "features_mask"):
        vals = [getattr(d, field, None) for d in group]
        if any(isinstance(v, (list, tuple)) for v in vals):
            return False           # multi-input lists: per-step path
        none = [v is None for v in vals]
        if any(none):
            if not all(none):
                return False       # mask present in some batches only
            if field in ("features", "labels"):
                return False
            continue
        shapes = {np.shape(v) for v in vals}
        if len(shapes) != 1:
            return False           # ragged (e.g. short remainder batch)
    return True


def skip_batches(iterable, n: int):
    """Consume (without yielding) the first ``n`` batches and return an
    iterator over the rest — the mid-epoch-resume primitive shared by
    ``Solver._fit_epoch`` and ``ParallelWrapper._fit_epoch`` (the
    ElasticTrainer's bit-identical resume depends on both paths skipping
    identically). Tolerates streams shorter than ``n``."""
    src = iter(iterable)
    _miss = object()
    for _ in range(max(0, n)):
        if next(src, _miss) is _miss:
            break
    return src


def iter_windows(iterable, k: int):
    """Group a batch stream into ``BatchWindow``s of ``k``.

    Yields a ``BatchWindow`` for every run of ``k`` consecutive
    same-shape single-array batches, and bare ``DataSet``s for anything
    the fused path must not swallow: the ragged remainder at end of
    epoch, a batch whose shape differs mid-window (the whole group falls
    back — order is preserved), multi-input MultiDataSets, and windows of
    one. The consumer dispatches fused on windows and per-step on bare
    batches, so the stream stays order- and content-identical to the
    unwindowed iterator.
    """
    if k < 1:
        raise ValueError("steps_per_dispatch window size must be >= 1")
    buf = []
    for ds in iterable:
        buf.append(ds)
        if len(buf) == k:
            if k > 1 and _window_stackable(buf):
                yield BatchWindow(buf)
            else:
                yield from buf
            buf = []
    yield from buf        # ragged remainder: per-step fallback
