"""Export-based dataset pipeline: save minibatches as sharded files, stream
them back per worker.

Reference: the Spark parameter-averaging master's DEFAULT export path —
`ParameterAveragingTrainingMaster.executeTraining` first exports the RDD to
saved minibatch files and workers then stream those files
(`spark/impl/paramavg/ParameterAveragingTrainingMaster.java:326-335`,
`spark/util/ExportSupport`, `spark/iterator/PortableDataStreamDataSetIterator`).
The point of that design survives on TPU pods: decouple (slow, once)
preprocessing from (fast, repeated) training epochs, and let each host read
only ITS shards instead of shipping batches through a driver.

Format: one `.npz` per shard holding `features_<i>`, `labels_<i>` (+ optional
`features_mask_<i>` / `labels_mask_<i>`) for each minibatch i, plus a
`manifest.json` with shard/batch counts — plain numpy files any tool can
read.

Multi-host: `ShardedFileDataSetIterator(dir, shard_index=k, num_shards=n)`
reads the k-th of n interleaved shard subsets; `for_process()` picks
`jax.process_index()/process_count()` so the same script works on one host
or a pod.
"""
from __future__ import annotations

import collections
import glob
import json
import os
import re
from typing import Iterator, Optional

import numpy as np

from .dataset import DataSet, DataSetIterator


def export_dataset_iterator(iterator, out_dir: str, *,
                            batches_per_shard: int = 16,
                            prefix: str = "shard") -> dict:
    """Write every DataSet from ``iterator`` into ``out_dir`` as .npz shards
    (reference ExportSupport.exportIfRequired). Returns the manifest dict."""
    os.makedirs(out_dir, exist_ok=True)
    shard, batch_in_shard, n_batches, n_examples = 0, 0, 0, 0
    bufs: dict = {}
    shards = []

    def flush():
        nonlocal shard, batch_in_shard, bufs
        if not bufs:
            return
        path = os.path.join(out_dir, f"{prefix}_{shard:05d}.npz")
        np.savez(path, **bufs)
        shards.append({"file": os.path.basename(path),
                       "batches": batch_in_shard})
        shard += 1
        batch_in_shard = 0
        bufs = {}

    def put(name, value):
        # multi-input/multi-output graphs carry list features/labels
        # (optimize/solver.py handles the same shape); store each part as
        # <name>_inJ — the index in the key preserves positions, and a
        # <name>_len marker keeps None holes (e.g. labels_mask [None, m])
        # reconstructible. None scalars (unlabeled DataSets) are skipped
        # entirely: np.asarray(None) would pickle an object array that
        # np.load(allow_pickle=False) later refuses.
        if value is None:
            return
        if isinstance(value, (list, tuple)):
            bufs[f"{name}_len"] = np.asarray(len(value), np.int64)
            for j, v in enumerate(value):
                if v is not None:
                    bufs[f"{name}_in{j}"] = np.asarray(v)
        else:
            bufs[name] = np.asarray(value)

    for ds in iterator:
        i = batch_in_shard
        put(f"features_{i}", ds.features)
        put(f"labels_{i}", ds.labels)
        if ds.features_mask is not None:
            put(f"features_mask_{i}", ds.features_mask)
        if ds.labels_mask is not None:
            put(f"labels_mask_{i}", ds.labels_mask)
        batch_in_shard += 1
        n_batches += 1
        n_examples += ds.num_examples()
        if batch_in_shard >= batches_per_shard:
            flush()
    flush()
    manifest = {"version": 1, "prefix": prefix, "num_shards": len(shards),
                "num_batches": n_batches, "num_examples": n_examples,
                "shards": shards}
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    return manifest


class ShardedFileDataSetIterator(DataSetIterator):
    """Stream exported shards back as DataSets (reference
    PortableDataStreamDataSetIterator / the worker side of the export path).

    ``shard_index``/``num_shards`` select an interleaved subset of shard
    FILES (shard i goes to worker i % num_shards) so every worker streams a
    disjoint, load-balanced partition without a driver in the loop.

    ``reader_threads`` > 1 parallelizes the DISK side: a small thread pool
    reads shard files ahead of consumption (each worker fully materializes
    its shard's batches — numpy decompression/parse releases the GIL on
    the I/O, and the native C++ reader's memcpy is GIL-free by
    construction), while batches are yielded strictly in shard order, so
    the stream is bit-identical to the serial read. At most
    ``reader_threads`` shards are in flight plus the one being yielded —
    size against shard bytes, not batch bytes. The default (1) keeps the
    lazy footprint existing callers were sized for: one open shard, one
    batch of host memory at a time.
    """

    def __init__(self, data_dir: str, *, shard_index: int = 0,
                 num_shards: int = 1, shuffle_shards: bool = False,
                 seed: int = 0, reader_threads: int = 1):
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard_index {shard_index} out of range for "
                             f"num_shards {num_shards}")
        if reader_threads < 1:
            raise ValueError("reader_threads must be >= 1")
        self.data_dir = data_dir
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.shuffle_shards = shuffle_shards
        self.reader_threads = reader_threads
        self._rng = np.random.default_rng(seed)
        mpath = os.path.join(data_dir, "manifest.json")
        if os.path.exists(mpath):
            with open(mpath) as f:
                self.manifest = json.load(f)
            files = [s["file"] for s in self.manifest["shards"]]
        else:   # manifest-less directory of npz files still works
            self.manifest = None
            files = sorted(os.path.basename(p) for p in
                           glob.glob(os.path.join(data_dir, "*.npz")))
        if not files:
            raise FileNotFoundError(f"No exported shards in {data_dir!r}")
        self._files = [f for i, f in enumerate(files)
                       if i % num_shards == shard_index]
        if not self._files:
            # an empty partition would make this worker iterate zero batches
            # while its peers wait in collectives — fail at construction
            raise ValueError(
                f"Worker {shard_index}/{num_shards} gets no shards: only "
                f"{len(files)} shard file(s) in {data_dir!r}. Re-export with "
                f"a smaller batches_per_shard so every worker has data")

    @classmethod
    def for_process(cls, data_dir: str, **kw) -> "ShardedFileDataSetIterator":
        """Partition by jax process: worker k of n on a multi-host pod
        streams its own shard subset (reference: each Spark executor reads
        its partition's export files)."""
        import jax
        return cls(data_dir, shard_index=jax.process_index(),
                   num_shards=jax.process_count(), **kw)

    @staticmethod
    def _get(z, name):
        """Reassemble a possibly multi-part value: <name> (single array) or
        <name>_len + <name>_inJ (list features/labels of a multi-input
        graph, with None holes preserved at their positions)."""
        if name in z.files:
            return z[name]
        if f"{name}_len" in z.files:
            out = [None] * int(z[f"{name}_len"])
            for k in z.files:
                m = re.fullmatch(re.escape(name) + r"_in(\d+)", k)
                if m:
                    out[int(m.group(1))] = z[k]
            return out
        # legacy shards (written before the _len marker) carry only the
        # _inJ parts — place each at its parsed index (length = max index
        # + 1) so None holes below the highest index survive
        indexed = {}
        for k in z.files:
            m = re.fullmatch(re.escape(name) + r"_in(\d+)", k)
            if m:
                indexed[int(m.group(1))] = z[k]
        if indexed:
            out = [None] * (max(indexed) + 1)
            for j, v in indexed.items():
                out[j] = v
            return out
        return None

    def _open_npz(self, path: str):
        """Shard-file opener hook (np.load here; the native subclass
        serves the same protocol from the C++ mmap reader)."""
        return np.load(path)

    def _iter_shard(self, fname: str) -> Iterator[DataSet]:
        """Lazily yield one shard file's DataSets (members are read from
        the open npz at yield time — one batch of host memory at a time)."""
        with self._open_npz(os.path.join(self.data_dir, fname)) as z:
            n = 0
            while (f"features_{n}" in z.files
                   or f"features_{n}_len" in z.files
                   or any(k.startswith(f"features_{n}_in")
                          for k in z.files)):            # legacy shards
                n += 1
            for i in range(n):
                yield DataSet(self._get(z, f"features_{i}"),
                              self._get(z, f"labels_{i}"),
                              self._get(z, f"features_mask_{i}"),
                              self._get(z, f"labels_mask_{i}"))

    def _read_shard(self, fname: str) -> list:
        """Fully materialize one shard (the thread-pool worker unit)."""
        return list(self._iter_shard(fname))

    def __iter__(self) -> Iterator[DataSet]:
        order = list(self._files)
        if self.shuffle_shards:
            self._rng.shuffle(order)
        if self.reader_threads == 1 or len(order) == 1:
            for fname in order:
                yield from self._iter_shard(fname)
            return
        # lookahead pool: keep reader_threads shard reads in flight, yield
        # strictly in order (bit-identical stream to the serial path)
        from concurrent.futures import ThreadPoolExecutor
        pool = ThreadPoolExecutor(max_workers=self.reader_threads,
                                  thread_name_prefix="shard-reader")
        try:
            pending = collections.deque(
                pool.submit(self._read_shard, f)
                for f in order[:self.reader_threads])
            next_submit = self.reader_threads
            while pending:
                batches = pending.popleft().result()
                if next_submit < len(order):
                    pending.append(pool.submit(self._read_shard,
                                               order[next_submit]))
                    next_submit += 1
                yield from batches
        finally:
            # early break: drop queued reads; in-flight ones finish on the
            # daemon-less pool threads and are discarded
            pool.shutdown(wait=False, cancel_futures=True)

    def reset(self):
        pass


class NativeShardedFileDataSetIterator(ShardedFileDataSetIterator):
    """ShardedFileDataSetIterator served by the C++ mmap shard reader
    (native/shard_reader.cpp): zip/npy headers parse natively and member
    payloads arrive via one GIL-free memcpy — the data-plane stays native
    like the reference's DataVec/ND4J loaders (SURVEY.md §3 L3). Falls
    back to numpy parsing per file if the native parse rejects it."""

    def _open_npz(self, path: str):
        from ..native import NativeNpzFile, shard_reader_available
        if shard_reader_available():
            try:
                return NativeNpzFile(path)
            except OSError:
                pass                      # e.g. a compressed npz: numpy path
        return np.load(path)


def make_shard_iterator(data_dir: str, *, prefer_native: bool = True,
                        **kw) -> ShardedFileDataSetIterator:
    """The production entry point: native reader when the toolchain built
    it, numpy otherwise — same iterator contract either way."""
    from ..native import shard_reader_available
    if prefer_native and shard_reader_available():
        return NativeShardedFileDataSetIterator(data_dir, **kw)
    return ShardedFileDataSetIterator(data_dir, **kw)
