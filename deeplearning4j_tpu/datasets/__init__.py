from .dataset import (AsyncDataSetIterator, DataSet, DataSetIterator,
                      ListDataSetIterator)
from .export import (ShardedFileDataSetIterator,
                     export_dataset_iterator)
from .fetchers import (Cifar10DataSetIterator, CurvesDataSetIterator,
                       IrisDataSetIterator, LFWDataSetIterator,
                       load_cifar10, load_curves, load_iris, load_lfw)
from .prefetch import DevicePrefetchIterator
from .iterators import (EarlyTerminationDataSetIterator,
                        ExistingDataSetIterator, IteratorDataSetIterator,
                        ListMultiDataSetIterator, MultiDataSet,
                        MultipleEpochsIterator, SamplingDataSetIterator)
from .mnist import MnistDataSetIterator, load_mnist

__all__ = [
    "AsyncDataSetIterator", "Cifar10DataSetIterator", "CurvesDataSetIterator",
    "DataSet", "DataSetIterator", "DevicePrefetchIterator",
    "EarlyTerminationDataSetIterator",
    "ExistingDataSetIterator", "IrisDataSetIterator",
    "IteratorDataSetIterator", "LFWDataSetIterator",
    "ListDataSetIterator",
    "ListMultiDataSetIterator", "MnistDataSetIterator", "MultiDataSet",
    "MultipleEpochsIterator", "SamplingDataSetIterator",
    "ShardedFileDataSetIterator", "export_dataset_iterator", "load_cifar10",
    "load_curves", "load_iris", "load_lfw", "load_mnist",
]
