"""deeplearning4j_tpu: a TPU-native deep-learning framework with the
capabilities of Deeplearning4j, built on JAX/XLA/Pallas/pjit.

Reference capability map: /root/repo/SURVEY.md (structural analysis of
dachylong/deeplearning4j @ 0.8.1-SNAPSHOT).
"""
__version__ = "0.1.0"

from . import telemetry
from .nn.conf.config import NeuralNetConfiguration, MultiLayerConfiguration
from .nn.inputs import InputType
from .nn.multilayer import MultiLayerNetwork

__all__ = [
    "NeuralNetConfiguration", "MultiLayerConfiguration", "InputType",
    "MultiLayerNetwork", "telemetry",
]
