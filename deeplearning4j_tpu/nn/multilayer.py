"""MultiLayerNetwork: sequential-stack executor.

Reference: nn/multilayer/MultiLayerNetwork.java:88 — init/flatten params
(:455,467), feedForward (:776-888), fit (:1076), backprop (:1186),
computeGradientAndScore (:2121), evaluate, rnnTimeStep.

TPU-first design: the reference orchestrates layer-by-layer on the host; here
the ENTIRE forward(+backward+update) is one traced function that XLA compiles
and fuses (the python layer loop unrolls at trace time). Parameters are a
tuple-of-dicts pytree; the reference's single flattened parameter buffer
(flattenedParams, MultiLayerNetwork.java:1202-1206) survives as
``params_flat()`` — the canonical view for checkpointing, averaging and
gradient checks.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .conf.config import MultiLayerConfiguration
from .layers.core import BaseOutputLayerMixin
from ..optimize.updaters import MultiLayerUpdater


def _dtype_of(conf) -> Any:
    return jnp.dtype(conf.dtype)


def cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree (mixed-precision boundary;
    integer leaves — embedding ids, step counters — pass through)."""
    dt = jnp.dtype(dtype)
    return jax.tree.map(
        lambda a: a.astype(dt)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a, tree)


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = tuple(conf.layers)
        self.params: Optional[Tuple[Dict[str, jnp.ndarray], ...]] = None
        self.state: Optional[Tuple[Dict[str, jnp.ndarray], ...]] = None
        self.updater = MultiLayerUpdater(
            self.layers, conf.updater, conf.gradient_normalization,
            conf.gradient_normalization_threshold)
        self.opt_state = None
        self.iteration_count = 0
        self.listeners: List[Any] = []
        self._rnn_state: Optional[list] = None
        self._jit_cache: Dict[Any, Any] = {}

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None):
        rng = jax.random.PRNGKey(self.conf.seed if seed is None else seed)
        dtype = _dtype_of(self.conf)
        itype = self.conf.input_type
        params, state = [], []
        for i, layer in enumerate(self.layers):
            pre = self.conf.preprocessor(i)
            if pre is not None and itype is not None:
                itype = pre.output_type(itype)
            rng, sub = jax.random.split(rng)
            p, s = layer.init(sub, itype, dtype)
            params.append(p)
            state.append(s)
            if itype is not None:
                itype = layer.output_type(itype)
        self.params = tuple(params)
        self.state = tuple(state)
        self.opt_state = self.updater.init(self.params)
        return self

    # ------------------------------------------------------------- functional
    def apply_fn(self, params, state, x, *, train: bool = False, rng=None,
                 to_layer: Optional[int] = None, features_mask=None,
                 rnn_states=None, collect_rnn_states: bool = False):
        """Pure forward pass. Returns (activations_list, new_state) — or
        (activations_list, new_state, rnn_states_out) when
        ``collect_rnn_states`` (used by tBPTT and rnn_time_step).

        activations_list[i] is the OUTPUT of layer i, mirroring
        feedForwardToLayer (reference MultiLayerNetwork.java:776-888).
        Per-timestep masks propagate to mask-aware layers (reference MaskState
        flow, setLayerMaskArrays :1144-1147) and collapse when the time
        dimension does.
        """
        acts = []
        new_state = []
        rnn_out = [None] * len(self.layers)
        n = len(self.layers) if to_layer is None else to_layer + 1
        if rng is None:
            rng = jax.random.PRNGKey(0)
        # mixed precision: master params stay conf.dtype (f32); the traced
        # compute runs in compute_dtype — jax.grad through these casts yields
        # f32 master gradients automatically (the cast's VJP casts back)
        cd = getattr(self.conf, "compute_dtype", None)
        if cd:
            # params/inputs cast down; STATE is deliberately left at master
            # precision — layers cast their own state for compute (e.g.
            # BatchNormalization keeps f32 running stats and casts to x.dtype
            # itself, norm.py), so casting here would re-quantize the EMA
            # accumulators every step
            params = cast_floats(params, cd)
            x = cast_floats(x, cd)
            if rnn_states is not None:
                rnn_states = cast_floats(rnn_states, cd)
        cur_mask = features_mask
        if features_mask is not None:
            m = jnp.asarray(features_mask, x.dtype)
            x = x * m.reshape(m.shape + (1,) * (x.ndim - m.ndim))
        for i in range(len(self.layers)):
            if i >= n:
                new_state.append(state[i])
                continue
            pre = self.conf.preprocessor(i)
            if pre is not None:
                x = pre.apply(x)
            rng, sub = jax.random.split(rng)
            layer = self.layers[i]
            kwargs = {}
            if getattr(layer, "accepts_mask", False) and cur_mask is not None \
                    and getattr(cur_mask, "ndim", 0) == 2 and x.ndim == 3:
                kwargs["mask"] = cur_mask
            if hasattr(layer, "apply_with_final_state") and \
                    (collect_rnn_states or (rnn_states is not None
                                            and rnn_states[i] is not None)):
                init = rnn_states[i] if rnn_states is not None else None
                x, final = layer.apply_with_final_state(
                    params[i], state[i], x, train=train, rng=sub,
                    initial_state=init, **kwargs)
                s = state[i]
                rnn_out[i] = final
            elif getattr(self.conf, "gradient_checkpointing", False):
                # remat: recompute this layer's activations in the backward
                # pass instead of storing them (HBM for FLOPs; the TPU
                # replacement for the reference's CacheMode knobs)
                fn = jax.checkpoint(
                    lambda p, s_, xx, key, _l=layer, _kw=kwargs:
                    _l.apply(p, s_, xx, train=train, rng=key, **_kw))
                x, s = fn(params[i], state[i], x, sub)
            else:
                x, s = layer.apply(params[i], state[i], x, train=train, rng=sub,
                                   **kwargs)
            new_state.append(s)
            acts.append(x)
            if x.ndim < 3:
                cur_mask = None   # time dimension collapsed
        if cd:
            # storage/API boundary: running stats + carried rnn state at
            # master precision; activations back to f32 so output()/
            # feed_forward()/evaluate() keep their dtype contract
            new_state = cast_floats(new_state, self.conf.dtype)
            rnn_out = cast_floats(rnn_out, self.conf.dtype)
            acts = cast_floats(acts, self.conf.dtype)
        if collect_rnn_states:
            return acts, tuple(new_state), rnn_out
        return acts, tuple(new_state)

    def loss_fn(self, params, state, x, labels, *, train: bool = True, rng=None,
                labels_mask=None, features_mask=None, rnn_states=None,
                collect_rnn_states: bool = False):
        """Mean per-example loss + L1/L2 regularization (reference
        computeGradientAndScore :2121 + BaseLayer.calcL2/calcL1).

        With ``collect_rnn_states`` the aux also carries each recurrent
        layer's final (h, c) — the tBPTT chunk carry (reference
        doTruncatedBPTT state sync, MultiLayerNetwork.java:1400)."""
        out_layer = self.layers[-1]
        if not isinstance(out_layer, BaseOutputLayerMixin):
            raise ValueError("Last layer must be an output layer to compute loss")
        if rng is None:
            rng = jax.random.PRNGKey(0)
        rng, fwd_rng = jax.random.split(rng)
        rnn_out = None
        # forward to second-to-last layer
        if len(self.layers) > 1:
            res = self.apply_fn(params, state, x, train=train, rng=fwd_rng,
                                to_layer=len(self.layers) - 2,
                                features_mask=features_mask,
                                rnn_states=rnn_states,
                                collect_rnn_states=collect_rnn_states)
            if collect_rnn_states:
                acts, new_state, rnn_out = res
            else:
                acts, new_state = res
            feed = acts[-1] if acts else x
        else:
            feed = x
            if features_mask is not None:
                m = jnp.asarray(features_mask, x.dtype)
                feed = feed * m.reshape(m.shape + (1,) * (feed.ndim - m.ndim))
            new_state = state
        pre = self.conf.preprocessor(len(self.layers) - 1)
        if pre is not None:
            feed = pre.apply(feed)
        rng, sub = jax.random.split(rng)
        cd = getattr(self.conf, "compute_dtype", None)
        head_params = cast_floats(params[-1], cd) if cd else params[-1]
        if cd:
            feed = cast_floats(feed, cd)
        per_ex = out_layer.compute_loss_per_example(
            head_params, feed, labels, labels_mask, train=train, rng=sub)
        if cd:
            per_ex = per_ex.astype(jnp.dtype(self.conf.dtype))  # f32 reduce
        if labels_mask is not None and per_ex.ndim == 1 and labels_mask.ndim >= 2:
            # per-timestep masked mean: normalize by active timesteps
            denom = jnp.maximum(jnp.sum(labels_mask), 1.0)
            score = jnp.sum(per_ex) / denom
        else:
            score = jnp.mean(per_ex)
        reg = 0.0
        for layer, p in zip(self.layers, params):
            reg = reg + layer.regularization(p)
        if collect_rnn_states:
            return score + reg, (new_state, rnn_out)
        return score + reg, new_state

    # ------------------------------------------------------------- inference
    def _jitted(self, key, fn):
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(fn)
        return self._jit_cache[key]

    def output(self, x, train: bool = False):
        x = jnp.asarray(x, _dtype_of(self.conf)) if not _is_int_input(x) else jnp.asarray(x)
        fn = self._jitted(("output", train), functools.partial(self._output_pure, train=train))
        return fn(self.params, self.state, x)

    def _output_pure(self, params, state, x, *, train=False):
        acts, _ = self.apply_fn(params, state, x, train=train)
        return acts[-1]

    def feed_forward(self, x, train: bool = False):
        x = jnp.asarray(x)
        acts, _ = self.apply_fn(self.params, self.state, x, train=train)
        return [x] + acts

    def score(self, x=None, y=None, dataset=None) -> float:
        if dataset is not None:
            x, y = dataset.features, dataset.labels
            lm, fm = dataset.labels_mask, dataset.features_mask
        else:
            lm = fm = None
        fn = self._jitted(("score", lm is not None, fm is not None),
                          lambda p, s, xx, yy, lmm=None, fmm=None: self.loss_fn(
                              p, s, xx, yy, train=False, labels_mask=lmm,
                              features_mask=fmm)[0])
        args = [self.params, self.state, jnp.asarray(x), jnp.asarray(y)]
        kwargs = {}
        if lm is not None:
            kwargs["lmm"] = jnp.asarray(lm)
        if fm is not None:
            kwargs["fmm"] = jnp.asarray(fm)
        return float(fn(*args, **kwargs))

    # -------------------------------------------------------------- streaming
    def rnn_time_step(self, x):
        """Stateful streaming inference (reference
        MultiLayerNetwork.rnnTimeStep): feed [B,F] one step (or [B,T,F] a
        chunk); recurrent state is carried between calls."""
        x = jnp.asarray(x, _dtype_of(self.conf))
        single = x.ndim == 2
        if single:
            x = x[:, None, :]

        def fn(params, state, rnn_states, xx):
            acts, _, rnn_out = self.apply_fn(params, state, xx, train=False,
                                             rnn_states=rnn_states,
                                             collect_rnn_states=True)
            return acts[-1], rnn_out

        key = ("rnn_time_step", x.shape[1], self._rnn_state is None)
        jfn = self._jitted(key, fn)
        out, self._rnn_state = jfn(self.params, self.state, self._rnn_state, x)
        return out[:, -1] if (single and out.ndim == 3) else out

    def rnn_clear_previous_state(self):
        self._rnn_state = None

    # ------------------------------------------------------------ flat params
    def params_flat(self) -> jnp.ndarray:
        """All parameters as ONE 1-D vector (reference flattenedParams)."""
        leaves = []
        for layer, p in zip(self.layers, self.params):
            for name in layer.param_order:
                if name in p:
                    leaves.append(jnp.ravel(p[name]))
        if not leaves:
            return jnp.zeros((0,), _dtype_of(self.conf))
        return jnp.concatenate(leaves)

    def set_params_flat(self, flat):
        flat = jnp.asarray(flat)
        expected = self.num_params()
        if flat.shape != (expected,):
            raise ValueError(f"Expected flat parameter vector of length {expected}, "
                             f"got shape {flat.shape}")
        new_params, off = [], 0
        for layer, p in zip(self.layers, self.params):
            np_ = dict(p)
            for name in layer.param_order:
                if name in p:
                    n = int(np.prod(p[name].shape)) if p[name].ndim else 1
                    np_[name] = flat[off:off + n].reshape(p[name].shape).astype(p[name].dtype)
                    off += n
            new_params.append(np_)
        self.params = tuple(new_params)

    def num_params(self) -> int:
        return int(sum(int(np.prod(v.shape)) for p in self.params for v in p.values()))

    # ------------------------------------------------------------------ train
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def _solver(self):
        # One persistent Solver so the jitted train step survives across fit()
        # calls (the reference reuses its Solver too, MultiLayerNetwork.java:1155).
        if not hasattr(self, "_solver_inst"):
            from ..optimize.solver import Solver
            self._solver_inst = Solver(self)
        return self._solver_inst

    def fit(self, data=None, labels=None, *, epochs: int = 1, batch_size: Optional[int] = None,
            iterator=None, dataset=None, async_prefetch: bool = True,
            prefetch_depth: int = 2, steps_per_dispatch: int = 1,
            skip_first_batches: int = 0):
        """``async_prefetch``/``prefetch_depth``: iterator feeds run through
        a DevicePrefetchIterator (datasets/prefetch.py) — batch N+1 is
        host-prepared AND shipped to the device while step N computes; the
        per-iteration ETL wait is surfaced via PerformanceListener.

        ``steps_per_dispatch=K``: fuse windows of K same-shape prefetched
        batches into ONE jitted lax.scan training program (one host
        round-trip per window instead of per step) — bit-identical to K
        sequential steps; tBPTT, second-order solvers, and ragged
        remainder windows automatically run per-step.

        ``skip_first_batches=S``: consume (don't train) the first S
        batches of the FIRST epoch — the mid-epoch resume plumbing used
        by ``fit_with_checkpointing`` when a preemption landed between
        epoch boundaries (``iteration_count`` restored from the
        checkpoint already covers the skipped steps)."""
        self._solver().fit(data=data, labels=labels, epochs=epochs,
                           batch_size=batch_size, iterator=iterator,
                           dataset=dataset, async_prefetch=async_prefetch,
                           prefetch_depth=prefetch_depth,
                           steps_per_dispatch=steps_per_dispatch,
                           skip_first_batches=skip_first_batches)
        return self

    def pretrain(self, iterator, epochs: int = 1):
        self._solver().pretrain(iterator, epochs=epochs)
        return self

    # ------------------------------------------------------------------ eval
    def evaluate(self, iterator_or_x, y=None):
        """Iterator batches carrying per-example ``metadata`` feed the
        prediction-record workflow (Evaluation.get_prediction_errors etc.;
        reference MultiLayerNetwork.doEvaluation + eval/meta)."""
        from ..eval.evaluation import Evaluation
        e = Evaluation()
        if y is not None:
            e.eval(y, np.asarray(self.output(iterator_or_x)))
            return e
        for ds in iterator_or_x:
            out = np.asarray(self.output(ds.features))
            # metadata is per-example; time-series labels flatten to N*T
            # rows, so the record workflow doesn't apply there
            md = (getattr(ds, "metadata", None)
                  if np.asarray(ds.labels).ndim != 3 else None)
            e.eval(ds.labels, out, mask=ds.labels_mask, record_meta_data=md)
        return e

    # ------------------------------------------------------------------ misc
    def clone(self) -> "MultiLayerNetwork":
        import copy
        other = MultiLayerNetwork(copy.deepcopy(self.conf))
        if self.params is not None:
            # REAL copies: the trained clone's jitted steps donate their
            # buffers; sharing arrays would invalidate the source network
            copy = lambda a: jnp.array(a, copy=True) if a is not None else None
            other.params = jax.tree.map(copy, self.params)
            other.state = jax.tree.map(copy, self.state)
            other.opt_state = jax.tree.map(copy, self.opt_state)
        return other


def _is_int_input(x):
    return np.asarray(x).dtype.kind in "iu"
