"""Weight initialization schemes.

Parity with the reference WeightInit enum (reference:
nn/weights/WeightInit.java:24-42): DISTRIBUTION, ZERO, SIGMOID_UNIFORM,
UNIFORM, XAVIER, XAVIER_UNIFORM, XAVIER_FAN_IN, XAVIER_LEGACY, RELU,
RELU_UNIFORM. Distributions for DISTRIBUTION mode mirror nn/conf/distribution/*.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .conf.serde import register


@register
@dataclass(frozen=True)
class NormalDistribution:
    mean: float = 0.0
    std: float = 1.0

    def sample(self, rng, shape, dtype):
        return self.mean + self.std * jax.random.normal(rng, shape, dtype)


@register
@dataclass(frozen=True)
class UniformDistribution:
    lower: float = -1.0
    upper: float = 1.0

    def sample(self, rng, shape, dtype):
        return jax.random.uniform(rng, shape, dtype, self.lower, self.upper)


@register
@dataclass(frozen=True)
class BinomialDistribution:
    trials: int = 1
    p: float = 0.5

    def sample(self, rng, shape, dtype):
        return jax.random.binomial(rng, self.trials, self.p, shape).astype(dtype)


def init_weights(rng, shape: Tuple[int, ...], weight_init: str, fan_in: float,
                 fan_out: float, dtype=jnp.float32, distribution=None):
    """Sample an initial weight array.

    ``fan_in``/``fan_out`` are supplied by the layer (e.g. conv uses
    channels*kernel products, reference ConvolutionParamInitializer).
    """
    wi = str(weight_init).lower()
    fan_in, fan_out = float(fan_in), float(fan_out)
    # python-float scalars keep weak typing so the sampled dtype is preserved
    # (a jnp scalar would be strongly f64 under x64 and promote the result)
    if wi == "zero":
        return jnp.zeros(shape, dtype)
    if wi == "ones":
        return jnp.ones(shape, dtype)
    if wi == "distribution":
        if distribution is None:
            raise ValueError("WeightInit DISTRIBUTION requires a distribution config")
        return distribution.sample(rng, shape, dtype)
    if wi == "uniform":
        a = 1.0 / fan_in ** 0.5
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if wi == "xavier":
        std = (2.0 / (fan_in + fan_out)) ** 0.5
        return std * jax.random.normal(rng, shape, dtype)
    if wi == "xavier_uniform":
        a = (6.0 / (fan_in + fan_out)) ** 0.5
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if wi == "xavier_fan_in":
        return jax.random.normal(rng, shape, dtype) / fan_in ** 0.5
    if wi == "xavier_legacy":
        std = 1.0 / (fan_in + fan_out) ** 0.5
        return std * jax.random.normal(rng, shape, dtype)
    if wi == "relu":
        return (2.0 / fan_in) ** 0.5 * jax.random.normal(rng, shape, dtype)
    if wi == "relu_uniform":
        a = (6.0 / fan_in) ** 0.5
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if wi == "sigmoid_uniform":
        a = 4.0 * (6.0 / (fan_in + fan_out)) ** 0.5
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if wi == "lecun_normal":
        return jax.random.normal(rng, shape, dtype) / fan_in ** 0.5
    raise ValueError(f"Unknown weight init {weight_init!r}")
