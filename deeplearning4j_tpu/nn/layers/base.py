"""Layer SPI: config dataclasses with pure init/apply functions.

The reference splits each layer into a config class (nn/conf/layers/*) and an
impl class (nn/layers/*) holding INDArray views into the flat parameter buffer
(reference: nn/api/Layer.java:40 Layer SPI; nn/params/* param initializers).
Here a layer is ONE dataclass: serializable hyperparameters plus pure
``init``/``apply`` functions over param pytrees — the TPU-idiomatic form
(params live in a pytree; XLA fuses the whole network into one program, so
there is no per-layer execution object).

Mutable per-layer state (batch-norm running stats, reference
nn/layers/normalization/BatchNormalization.java) is threaded functionally:
``apply`` returns ``(output, new_state)``.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, ClassVar, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..conf.serde import register
from ..activations import get_activation
from ..weights import init_weights
from ..inputs import (InputTypeConvolutional, InputTypeConvolutionalFlat,
                      InputTypeFeedForward, InputTypeRecurrent)


def maybe_dropout(x, retain_prob, rng, train):
    """Inverted dropout on a layer's input (reference util/Dropout.java:
    applyDropout — ``dropOut`` is the RETAIN probability; scaling by 1/p at
    train time so inference is identity)."""
    if not train or retain_prob is None or retain_prob <= 0 or retain_prob >= 1:
        return x
    keep = jax.random.bernoulli(rng, retain_prob, x.shape)
    return jnp.where(keep, x / retain_prob, 0.0).astype(x.dtype)


@dataclass
class LayerConf:
    """Base for all layer configs. Fields that are None inherit the global
    default from NeuralNetConfiguration at build() time (reference:
    NeuralNetConfiguration.Builder cascade, NeuralNetConfiguration.java:604-608).
    """
    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    distribution: Optional[Any] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None           # retain probability; 0/None = off
    updater: Optional[Any] = None             # per-layer IUpdater override
    learning_rate: Optional[float] = None
    bias_learning_rate: Optional[float] = None
    frozen: bool = False                      # reference misc/FrozenLayer: no updates

    # --- class-level metadata overridden by subclasses (not serialized) ---
    param_order: ClassVar[Tuple[str, ...]] = ()
    weight_param_names: ClassVar[Tuple[str, ...]] = ("W",)
    expected_input: ClassVar[str] = "ff"

    # ---- SPI ----
    def output_type(self, itype):
        return itype

    def init(self, rng, itype, dtype) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        return {}, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        raise NotImplementedError

    # ---- helpers ----
    def act(self, x):
        return get_activation(self.activation or "identity")(x)

    def has_params(self):
        return bool(self.param_order)

    def _winit(self, rng, shape, fan_in, fan_out, dtype):
        return init_weights(rng, shape, self.weight_init or "xavier", fan_in,
                            fan_out, dtype, self.distribution)

    def _binit(self, shape, dtype):
        return jnp.full(shape, self.bias_init or 0.0, dtype)

    def regularization(self, params):
        """0.5*l2*||W||^2 + l1*|W| over weight params only (reference
        BaseLayer.calcL2/calcL1)."""
        reg = 0.0
        l1 = self.l1 or 0.0
        l2 = self.l2 or 0.0
        if l1 == 0.0 and l2 == 0.0:
            return 0.0
        for name in self.weight_param_names:
            if name in params:
                w = params[name]
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(w * w)
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(w))
        return reg


def resolve_ff_size(itype) -> int:
    """Feed-forward input width for a layer fed by ``itype``."""
    if isinstance(itype, (InputTypeFeedForward, InputTypeRecurrent)):
        return itype.size
    if isinstance(itype, (InputTypeConvolutional, InputTypeConvolutionalFlat)):
        return itype.flat_size()
    raise ValueError(f"Cannot infer feed-forward size from {itype}")
