"""Recurrent layers: LSTM, GravesLSTM (peepholes), GravesBidirectionalLSTM.

Reference: nn/layers/recurrent/LSTMHelpers.java (fwd time loop :184, gemm
:201-207, bwd loop :466), nn/conf/layers/GravesLSTM.java:47 (peephole
connections, forgetGateBiasInit, gateActivationFn sigmoid default),
GravesBidirectionalLSTM.java (fwd+bwd outputs SUMMED, activateOutput).

TPU-first: the time loop is ONE ``lax.scan`` — the input projection
x @ W for ALL timesteps is hoisted out of the scan as a single [B*T, 4H]
matmul (MXU-shaped), only the recurrent h @ R matmul lives in the carry loop.
Masking multiplies state updates so padded steps carry state through
unchanged (the reference zeroes activations via maskArray; carrying state is
equivalent for right-padded sequences and keeps rnn_time_step consistent).

Layout: [B, T, F] (batch-major; the reference uses [B, F, T] NCW).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, ClassVar, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..conf.serde import register
from ..activations import get_activation
from ..inputs import InputTypeRecurrent
from .base import LayerConf, maybe_dropout


def _lstm_scan(x_proj, h0, c0, R, act, gate_act, peepholes=None, mask=None,
               reverse=False, activation_names=("", "")):
    """Run an LSTM over time: fused Pallas kernel when applicable, else scan.

    x_proj: [T, B, 4H] precomputed input projections (+bias).
    Gate order along the 4H axis: [i, f, o, g].
    peepholes: None or (p_i, p_f, p_o) each [H] (Graves variant).
    mask: [T, B, 1] or None.
    activation_names: (activation, gate_activation) strings for the fused-path
    probe. Returns h sequence [T, B, H] and final (h, c).

    The fused path is the reference's accelerated-helper seam
    (ConvolutionLayer.java:72 reflection probe for cuDNN) done the TPU way:
    ops/pallas_lstm.py pins the recurrent matrix in VMEM across the whole
    time loop; measured 2.4-2.7x device-time vs this scan and 3.0x vs the
    flax OptimizedLSTMCell reference at the char-RNN bench shape (2-layer
    net, T=64, B=32, H=512) — numbers in ops/pallas_lstm.py.
    """
    H = h0.shape[-1]
    from ...ops.pallas_lstm import (fused_lstm, fused_lstm_applicable,
                                    fused_lstm_peephole)
    # probe with reverse=False: THIS dispatcher implements reverse by
    # flipping inputs/outputs around the forward-only kernels
    if fused_lstm_applicable(h0.shape[0], H, x_proj.dtype,
                             peepholes=peepholes, mask=mask, reverse=False,
                             activation=activation_names[0],
                             gate_activation=activation_names[1]):
        m2d = None if mask is None else mask[:, :, 0].astype(x_proj.dtype)
        if reverse:
            # a reverse LSTM is a forward LSTM over the flipped sequence
            # (the backward half of GravesBidirectionalLSTM)
            x_proj = jnp.flip(x_proj, 0)
            m2d = None if m2d is None else jnp.flip(m2d, 0)
        if peepholes is not None:
            hs, final = fused_lstm_peephole(x_proj, h0, c0, R, *peepholes,
                                            mask=m2d)
        else:
            hs, final = fused_lstm(x_proj, h0, c0, R, mask=m2d)
        return (jnp.flip(hs, 0) if reverse else hs), final

    def step(carry, inp):
        h_prev, c_prev = carry
        xp, m = inp
        gates = xp + h_prev @ R
        zi, zf, zo, zg = (gates[..., :H], gates[..., H:2 * H],
                          gates[..., 2 * H:3 * H], gates[..., 3 * H:])
        if peepholes is not None:
            p_i, p_f, p_o = peepholes
            zi = zi + c_prev * p_i
            zf = zf + c_prev * p_f
        i = gate_act(zi)
        f = gate_act(zf)
        g = act(zg)
        c = f * c_prev + i * g
        if peepholes is not None:
            zo = zo + c * p_o
        o = gate_act(zo)
        h = o * act(c)
        if m is not None:
            h = m * h + (1 - m) * h_prev
            c = m * c + (1 - m) * c_prev
        return (h, c), h

    ms = mask if mask is not None else jnp.ones((x_proj.shape[0], 1, 1), x_proj.dtype)
    (hT, cT), hs = lax.scan(step, (h0, c0), (x_proj, ms), reverse=reverse)
    return hs, (hT, cT)


@register
@dataclass
class LSTM(LayerConf):
    """Standard LSTM without peepholes (reference nn/conf/layers/LSTM.java)."""
    n_in: Optional[int] = None
    n_out: int = 0
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    param_order: ClassVar[Tuple[str, ...]] = ("W", "R", "b")
    weight_param_names: ClassVar[Tuple[str, ...]] = ("W", "R")
    expected_input: ClassVar[str] = "rnn"
    accepts_mask: ClassVar[bool] = True
    has_peepholes: ClassVar[bool] = False

    def __post_init__(self):
        if self.activation is None:
            self.activation = "tanh"

    def output_type(self, itype):
        return InputTypeRecurrent(self.n_out, getattr(itype, "timestep_length", -1))

    def init(self, rng, itype, dtype):
        n_in = self.n_in or itype.size
        H = self.n_out
        k1, k2 = jax.random.split(rng)
        W = self._winit(k1, (n_in, 4 * H), n_in, H, dtype)
        R = self._winit(k2, (H, 4 * H), H, H, dtype)
        b = jnp.zeros((4 * H,), dtype)
        # forget-gate bias init (reference forgetGateBiasInit default 1.0)
        b = b.at[H:2 * H].set(jnp.asarray(self.forget_gate_bias_init, dtype))
        params = {"W": W, "R": R, "b": b}
        if self.has_peepholes:
            params.update({"pi": jnp.zeros((H,), dtype),
                           "pf": jnp.zeros((H,), dtype),
                           "po": jnp.zeros((H,), dtype)})
        return params, {}

    def _peepholes(self, params):
        return (params["pi"], params["pf"], params["po"]) if self.has_peepholes else None

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              initial_state=None):
        x = maybe_dropout(x, self.dropout, rng, train)
        B, T, _ = x.shape
        H = self.n_out
        act = get_activation(self.activation or "tanh")
        gate_act = get_activation(self.gate_activation)
        # hoist the input projection out of the scan: one big MXU matmul
        x_proj = (x @ params["W"] + params["b"]).transpose(1, 0, 2)  # [T,B,4H]
        if initial_state is not None:
            h0, c0 = initial_state
        else:
            h0 = jnp.zeros((B, H), x.dtype)
            c0 = jnp.zeros((B, H), x.dtype)
        m = None if mask is None else mask.astype(x.dtype).T[..., None]  # [T,B,1]
        hs, (hT, cT) = _lstm_scan(x_proj, h0, c0, params["R"], act, gate_act,
                                  self._peepholes(params), m,
                                  activation_names=(self.activation or "tanh",
                                                    self.gate_activation))
        out = hs.transpose(1, 0, 2)  # [B,T,H]
        return out, state

    def apply_with_final_state(self, params, state, x, *, train=False, rng=None,
                               mask=None, initial_state=None):
        """Like apply but also returns (h_T, c_T) — used by tBPTT and
        rnn_time_step (reference RecurrentLayer rnnTimeStep/tBpttState APIs)."""
        x = maybe_dropout(x, self.dropout, rng, train)
        B, T, _ = x.shape
        H = self.n_out
        act = get_activation(self.activation or "tanh")
        gate_act = get_activation(self.gate_activation)
        x_proj = (x @ params["W"] + params["b"]).transpose(1, 0, 2)
        if initial_state is None:
            initial_state = (jnp.zeros((B, H), x.dtype), jnp.zeros((B, H), x.dtype))
        m = None if mask is None else mask.astype(x.dtype).T[..., None]
        hs, final = _lstm_scan(x_proj, initial_state[0], initial_state[1],
                               params["R"], act, gate_act,
                               self._peepholes(params), m,
                               activation_names=(self.activation or "tanh",
                                                 self.gate_activation))
        return hs.transpose(1, 0, 2), final


@register
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference GravesLSTM.java:47,
    LSTMHelpers peephole terms)."""
    param_order: ClassVar[Tuple[str, ...]] = ("W", "R", "b", "pi", "pf", "po")
    has_peepholes: ClassVar[bool] = True


@register
@dataclass
class GravesBidirectionalLSTM(LayerConf):
    """Bidirectional Graves LSTM; forward and backward outputs are SUMMED
    (reference GravesBidirectionalLSTM.activateOutput 'sum outputs')."""
    n_in: Optional[int] = None
    n_out: int = 0
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    param_order: ClassVar[Tuple[str, ...]] = ("Wf", "Rf", "bf", "pif", "pff", "pof",
                                              "Wb", "Rb", "bb", "pib", "pfb", "pob")
    weight_param_names: ClassVar[Tuple[str, ...]] = ("Wf", "Rf", "Wb", "Rb")
    expected_input: ClassVar[str] = "rnn"
    accepts_mask: ClassVar[bool] = True

    def __post_init__(self):
        if self.activation is None:
            self.activation = "tanh"

    def output_type(self, itype):
        return InputTypeRecurrent(self.n_out, getattr(itype, "timestep_length", -1))

    def init(self, rng, itype, dtype):
        n_in = self.n_in or itype.size
        H = self.n_out
        keys = jax.random.split(rng, 4)
        params = {}
        for d, (kw, kr) in zip("fb", [(keys[0], keys[1]), (keys[2], keys[3])]):
            W = self._winit(kw, (n_in, 4 * H), n_in, H, dtype)
            R = self._winit(kr, (H, 4 * H), H, H, dtype)
            b = jnp.zeros((4 * H,), dtype).at[H:2 * H].set(
                jnp.asarray(self.forget_gate_bias_init, dtype))
            params.update({f"W{d}": W, f"R{d}": R, f"b{d}": b,
                           f"pi{d}": jnp.zeros((H,), dtype),
                           f"pf{d}": jnp.zeros((H,), dtype),
                           f"po{d}": jnp.zeros((H,), dtype)})
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = maybe_dropout(x, self.dropout, rng, train)
        B, T, _ = x.shape
        H = self.n_out
        act = get_activation(self.activation or "tanh")
        gate_act = get_activation(self.gate_activation)
        m = None if mask is None else mask.astype(x.dtype).T[..., None]
        outs = []
        for d, reverse in (("f", False), ("b", True)):
            x_proj = (x @ params[f"W{d}"] + params[f"b{d}"]).transpose(1, 0, 2)
            h0 = jnp.zeros((B, H), x.dtype)
            c0 = jnp.zeros((B, H), x.dtype)
            peep = (params[f"pi{d}"], params[f"pf{d}"], params[f"po{d}"])
            hs, _ = _lstm_scan(x_proj, h0, c0, params[f"R{d}"], act, gate_act,
                               peep, m, reverse=reverse,
                               activation_names=(self.activation or "tanh",
                                                 self.gate_activation))
            outs.append(hs.transpose(1, 0, 2))
        return outs[0] + outs[1], state


@register
@dataclass
class LastTimeStepLayer(LayerConf):
    """[B,T,F] -> [B,F] (reference recurrent/LastTimeStep wrapper semantics)."""
    expected_input: ClassVar[str] = "rnn"
    accepts_mask: ClassVar[bool] = True

    def output_type(self, itype):
        from ..inputs import InputTypeFeedForward
        return InputTypeFeedForward(itype.size)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if mask is not None:
            idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx], state
        return x[:, -1], state
