"""Variational autoencoder + RBM: the unsupervised pretrain layer family.

Reference parity:
- VariationalAutoencoder conf  -> nn/conf/layers/variational/VariationalAutoencoder.java
- VariationalAutoencoder impl  -> nn/layers/variational/VariationalAutoencoder.java
  (1,156 LoC: encoder/decoder stacks, reparameterized ELBO pretraining
  :computeGradientAndScore, supervised forward = mean of q(z|x) :activate,
  reconstructionLogProbability / generateAtMean / generateRandom APIs)
- Reconstruction distributions -> nn/conf/layers/variational/
  {GaussianReconstructionDistribution, BernoulliReconstructionDistribution,
   ExponentialReconstructionDistribution, CompositeReconstructionDistribution,
   LossFunctionWrapper}.java
- RBM conf/impl                -> nn/conf/layers/RBM.java +
  nn/layers/feedforward/rbm/RBM.java (contrastive divergence, Gibbs sampling,
  HiddenUnit/VisibleUnit types)

TPU-first design notes: the whole ELBO (encoder stack, reparameterized
sampling over ``num_samples`` draws, decoder stack, reconstruction
log-likelihood, KL) is ONE pure function — jax.grad differentiates it and XLA
fuses the stacks into back-to-back MXU matmuls; the reference hand-derives the
backward pass over ~400 lines. CD-k for the RBM is expressed as a free-energy
surrogate loss whose jax.grad IS the CD-k update (positive phase minus
stop-gradient negative phase), so the same jitted pretrain path drives it.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, ClassVar, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..conf.serde import register
from ..activations import get_activation
from ..inputs import InputTypeFeedForward
from ..losses import get_loss
from .base import LayerConf, maybe_dropout, resolve_ff_size


# --------------------------------------------------------------------------
# Reconstruction distributions p(x|z)
# --------------------------------------------------------------------------

@register
@dataclass
class GaussianReconstructionDistribution:
    """p(x|z) = N(mu, sigma^2) with [mu | log sigma^2] produced by the decoder
    (reference GaussianReconstructionDistribution.java: distributionInputSize
    = 2*dataSize; activation applied to the mean half only)."""
    activation: str = "identity"

    def input_size(self, data_size: int) -> int:
        return 2 * data_size

    def _split(self, pre):
        d = pre.shape[-1] // 2
        mu = get_activation(self.activation)(pre[..., :d])
        log_var = pre[..., d:]
        return mu, log_var

    def neg_log_prob(self, x, pre):
        mu, log_var = self._split(pre)
        var = jnp.exp(log_var)
        ll = -0.5 * (jnp.log(2 * jnp.pi) + log_var + (x - mu) ** 2 / var)
        return -jnp.sum(ll, axis=-1)

    def generate_at_mean(self, pre):
        return self._split(pre)[0]

    def generate_random(self, rng, pre):
        mu, log_var = self._split(pre)
        return mu + jnp.exp(0.5 * log_var) * jax.random.normal(rng, mu.shape, mu.dtype)


@register
@dataclass
class BernoulliReconstructionDistribution:
    """p(x|z) = Bernoulli(sigmoid(pre)) — binary/binarized data (reference
    BernoulliReconstructionDistribution.java)."""
    activation: str = "sigmoid"

    def input_size(self, data_size: int) -> int:
        return data_size

    def neg_log_prob(self, x, pre):
        if self.activation == "sigmoid":
            # numerically stable fused form
            ll = x * jax.nn.log_sigmoid(pre) + (1 - x) * jax.nn.log_sigmoid(-pre)
        else:
            p = jnp.clip(get_activation(self.activation)(pre), 1e-10, 1 - 1e-10)
            ll = x * jnp.log(p) + (1 - x) * jnp.log1p(-p)
        return -jnp.sum(ll, axis=-1)

    def generate_at_mean(self, pre):
        return get_activation(self.activation)(pre)

    def generate_random(self, rng, pre):
        p = get_activation(self.activation)(pre)
        return jax.random.bernoulli(rng, p).astype(pre.dtype)


@register
@dataclass
class ExponentialReconstructionDistribution:
    """p(x|z) = lambda*exp(-lambda*x), lambda = exp(activation(pre))
    (reference ExponentialReconstructionDistribution.java: gamma = preOut
    through activation, lambda = exp(gamma); logP = gamma - x*exp(gamma))."""
    activation: str = "identity"

    def input_size(self, data_size: int) -> int:
        return data_size

    def neg_log_prob(self, x, pre):
        gamma = get_activation(self.activation)(pre)
        ll = gamma - x * jnp.exp(gamma)
        return -jnp.sum(ll, axis=-1)

    def generate_at_mean(self, pre):
        gamma = get_activation(self.activation)(pre)
        return jnp.exp(-gamma)     # mean = 1/lambda

    def generate_random(self, rng, pre):
        lam = jnp.exp(get_activation(self.activation)(pre))
        u = jax.random.uniform(rng, pre.shape, pre.dtype, minval=1e-10, maxval=1.0)
        return -jnp.log(u) / lam


@register
@dataclass
class LossFunctionWrapper:
    """Wraps a standard loss function as a (non-probabilistic) reconstruction
    "distribution" (reference LossFunctionWrapper.java) — the VAE becomes an
    unsupervised net trained on reconstruction error + KL."""
    loss: str = "mse"
    activation: str = "identity"

    def input_size(self, data_size: int) -> int:
        return data_size

    def neg_log_prob(self, x, pre):
        return get_loss(self.loss)(x, pre, self.activation, None)

    def generate_at_mean(self, pre):
        return get_activation(self.activation)(pre)

    def generate_random(self, rng, pre):
        return self.generate_at_mean(pre)


@register
@dataclass
class CompositeReconstructionDistribution:
    """Different distributions for column slices of the data (reference
    CompositeReconstructionDistribution.java). ``parts`` is a list of
    (data_size, distribution) pairs covering the input columns in order."""
    parts: List[Any] = field(default_factory=list)    # [[size, dist], ...]

    def input_size(self, data_size: int) -> int:
        total = sum(int(s) for s, _ in self.parts)
        if data_size != total:
            raise ValueError(f"Composite part sizes sum to {total}, but the "
                             f"layer input size is {data_size}")
        return sum(d.input_size(int(s)) for s, d in self.parts)

    def _slices(self):
        x_off, p_off = 0, 0
        for s, d in self.parts:
            s = int(s)
            ps = d.input_size(s)
            yield (x_off, s, p_off, ps, d)
            x_off += s
            p_off += ps

    def neg_log_prob(self, x, pre):
        total = 0.0
        for x0, xs, p0, ps, d in self._slices():
            total = total + d.neg_log_prob(x[..., x0:x0 + xs], pre[..., p0:p0 + ps])
        return total

    def generate_at_mean(self, pre):
        outs = [d.generate_at_mean(pre[..., p0:p0 + ps])
                for _, _, p0, ps, d in self._slices()]
        return jnp.concatenate(outs, axis=-1)

    def generate_random(self, rng, pre):
        outs = []
        for _, _, p0, ps, d in self._slices():
            rng, sub = jax.random.split(rng)
            outs.append(d.generate_random(sub, pre[..., p0:p0 + ps]))
        return jnp.concatenate(outs, axis=-1)


# --------------------------------------------------------------------------
# VariationalAutoencoder layer
# --------------------------------------------------------------------------

@register
@dataclass
class VariationalAutoencoder(LayerConf):
    """VAE layer: pretrained on the reparameterized ELBO; as a layer in a
    supervised stack its forward pass is mean(q(z|x)) through
    ``pzx_activation`` (reference nn/layers/variational/
    VariationalAutoencoder.java:activate — decoder params take no part and no
    gradient in supervised backprop, mirrored here via ``supervised_params``).
    """
    n_in: Optional[int] = None
    n_out: int = 0                                  # latent space size
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    reconstruction_distribution: Any = None          # default Gaussian(identity)
    pzx_activation: str = "identity"
    num_samples: int = 1

    def __post_init__(self):
        if self.reconstruction_distribution is None:
            self.reconstruction_distribution = GaussianReconstructionDistribution()
        self.encoder_layer_sizes = tuple(self.encoder_layer_sizes)
        self.decoder_layer_sizes = tuple(self.decoder_layer_sizes)

    # param layout (reference VariationalAutoencoderParamInitializer):
    # encoder stack, q(z|x) mean + log-variance heads, decoder stack, p(x|z) head
    @property
    def param_order(self) -> Tuple[str, ...]:        # type: ignore[override]
        names = []
        for i in range(len(self.encoder_layer_sizes)):
            names += [f"eW{i}", f"eb{i}"]
        names += ["pZXMeanW", "pZXMeanb", "pZXLogStd2W", "pZXLogStd2b"]
        for i in range(len(self.decoder_layer_sizes)):
            names += [f"dW{i}", f"db{i}"]
        names += ["pXZW", "pXZb"]
        return tuple(names)

    @property
    def weight_param_names(self) -> Tuple[str, ...]:  # type: ignore[override]
        """Weights subject to l1/l2 in the SUPERVISED loss: encoder + mean head
        only. Decoder/logStd2/pXZ params are pretrain-only (reference
        isPretrainParam) — penalizing them in a supervised stack would decay a
        pretrained decoder that takes no part in the forward pass."""
        return tuple(n for n in self.supervised_params() if "W" in n)

    def supervised_params(self) -> Tuple[str, ...]:
        """Params that participate in supervised forward/backprop (reference
        isPretrainParam: decoder + pXZ + logStd2 head are pretrain-only)."""
        names = []
        for i in range(len(self.encoder_layer_sizes)):
            names += [f"eW{i}", f"eb{i}"]
        names += ["pZXMeanW", "pZXMeanb"]
        return tuple(names)

    def output_type(self, itype):
        return InputTypeFeedForward(self.n_out)

    def init(self, rng, itype, dtype):
        n_in = self.n_in or resolve_ff_size(itype)
        self.n_in = n_in
        dist_size = self.reconstruction_distribution.input_size(n_in)
        params = {}

        def dense(rng, name_w, name_b, fi, fo):
            params[name_w] = self._winit(rng, (fi, fo), fi, fo, dtype)
            params[name_b] = self._binit((fo,), dtype)

        cur = n_in
        for i, h in enumerate(self.encoder_layer_sizes):
            rng, sub = jax.random.split(rng)
            dense(sub, f"eW{i}", f"eb{i}", cur, h)
            cur = h
        rng, s1, s2 = jax.random.split(rng, 3)
        dense(s1, "pZXMeanW", "pZXMeanb", cur, self.n_out)
        dense(s2, "pZXLogStd2W", "pZXLogStd2b", cur, self.n_out)
        cur = self.n_out
        for i, h in enumerate(self.decoder_layer_sizes):
            rng, sub = jax.random.split(rng)
            dense(sub, f"dW{i}", f"db{i}", cur, h)
            cur = h
        rng, sub = jax.random.split(rng)
        dense(sub, "pXZW", "pXZb", cur, dist_size)
        return params, {}

    # ---- encoder / decoder stacks ----
    def _encoder_hidden(self, params, x):
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = self.act(h @ params[f"eW{i}"] + params[f"eb{i}"])
        return h

    def encode(self, params, x):
        """q(z|x): returns (mean, log_var), both through ``pzx_activation``
        (reference preOut -> pzxActivationFn for both heads)."""
        h = self._encoder_hidden(params, x)
        pzx_act = get_activation(self.pzx_activation)
        mu = pzx_act(h @ params["pZXMeanW"] + params["pZXMeanb"])
        log_var = pzx_act(h @ params["pZXLogStd2W"] + params["pZXLogStd2b"])
        return mu, log_var

    def decode(self, params, z):
        """p(x|z) distribution parameters (pre-activation)."""
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = self.act(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["pXZW"] + params["pXZb"]

    # ---- supervised layer SPI ----
    def apply(self, params, state, x, *, train=False, rng=None):
        x = maybe_dropout(x, self.dropout, rng, train)
        h = self._encoder_hidden(params, x)
        mu = get_activation(self.pzx_activation)(h @ params["pZXMeanW"] + params["pZXMeanb"])
        return mu, state

    # ---- pretrain: -ELBO ----
    def elbo_per_example(self, params, x, rng):
        """negative ELBO per example: KL(q(z|x) || N(0,I)) + E_q[-log p(x|z)],
        expectation over ``num_samples`` reparameterized draws (reference
        computeGradientAndScore ELBO loop)."""
        mu, log_var = self.encode(params, x)
        kl = -0.5 * jnp.sum(1 + log_var - mu ** 2 - jnp.exp(log_var), axis=-1)
        recon = 0.0
        for s in range(self.num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * log_var) * eps
            pre = self.decode(params, z)
            recon = recon + self.reconstruction_distribution.neg_log_prob(x, pre)
        return kl + recon / self.num_samples

    def pretrain_loss(self, params, x, rng):
        return jnp.mean(self.elbo_per_example(params, x, rng))

    # ---- user-facing generative APIs (reference :reconstructionProbability,
    #      :generateAtMeanGivenZ, :generateRandomGivenZ) ----
    def reconstruction_log_probability(self, params, x, num_samples: int = 5, rng=None):
        """Importance-sampling estimate of log p(x) (reference
        reconstructionLogProbability): log mean_s exp(log p(x|z_s) + log p(z_s)
        - log q(z_s|x))."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        mu, log_var = self.encode(params, x)
        log_ws = []
        for s in range(num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * log_var) * eps
            pre = self.decode(params, z)
            log_p_xz = -self.reconstruction_distribution.neg_log_prob(x, pre)
            log_p_z = -0.5 * jnp.sum(jnp.log(2 * jnp.pi) + z ** 2, axis=-1)
            log_q = -0.5 * jnp.sum(jnp.log(2 * jnp.pi) + log_var
                                   + eps ** 2, axis=-1)
            log_ws.append(log_p_xz + log_p_z - log_q)
        log_w = jnp.stack(log_ws)                      # [S, B]
        return jax.nn.logsumexp(log_w, axis=0) - jnp.log(float(num_samples))

    def generate_at_mean_given_z(self, params, z):
        return self.reconstruction_distribution.generate_at_mean(self.decode(params, z))

    def generate_random_given_z(self, params, z, rng):
        return self.reconstruction_distribution.generate_random(rng, self.decode(params, z))


# --------------------------------------------------------------------------
# RBM layer
# --------------------------------------------------------------------------

@register
@dataclass
class RBM(LayerConf):
    """Restricted Boltzmann machine (reference nn/conf/layers/RBM.java +
    nn/layers/feedforward/rbm/RBM.java). Pretrained with CD-k; as a
    feed-forward layer it is propUp: act(x@W + b) (reference RBM.activate).

    CD-k on TPU: expressed as the free-energy surrogate
    ``mean F(v_data) - mean F(stop_gradient(v_model))`` whose jax.grad equals
    the CD-k parameter update — one jitted program, no hand-written
    positive/negative phase gradients.
    """
    n_in: Optional[int] = None
    n_out: int = 0
    hidden_unit: str = "binary"       # binary | rectified (reference HiddenUnit)
    visible_unit: str = "binary"      # binary | gaussian  (reference VisibleUnit)
    k: int = 1                        # CD-k Gibbs steps

    param_order: ClassVar[Tuple[str, ...]] = ("W", "b", "vb")

    def output_type(self, itype):
        return InputTypeFeedForward(self.n_out)

    def init(self, rng, itype, dtype):
        n_in = self.n_in or resolve_ff_size(itype)
        self.n_in = n_in
        W = self._winit(rng, (n_in, self.n_out), n_in, self.n_out, dtype)
        return {"W": W, "b": self._binit((self.n_out,), dtype),
                "vb": self._binit((n_in,), dtype)}, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        x = maybe_dropout(x, self.dropout, rng, train)
        act = self.activation or "sigmoid"
        return get_activation(act)(x @ params["W"] + params["b"]), state

    # ---- CD-k machinery ----
    def free_energy(self, params, v):
        """F(v) = -v.vb - sum G(v@W + b), where G is the hidden-unit log
        partition term: softplus for binary hiddens (dG/dpre = sigmoid =
        E[h|v]), 0.5*relu(pre)^2 for rectified hiddens (dG/dpre = relu(pre),
        the NReLU mean-field expectation of Nair & Hinton 2010 — so the CD
        statistics match what the Gibbs chain samples). Gaussian visible
        replaces the linear visible term with 0.5||v - vb||^2."""
        pre_h = v @ params["W"] + params["b"]
        if self.hidden_unit == "rectified":
            hidden_term = jnp.sum(0.5 * jnp.maximum(pre_h, 0.0) ** 2, axis=-1)
        else:
            hidden_term = jnp.sum(jax.nn.softplus(pre_h), axis=-1)
        if self.visible_unit == "gaussian":
            vis_term = 0.5 * jnp.sum((v - params["vb"]) ** 2, axis=-1)
            return vis_term - hidden_term
        return -(v @ params["vb"]) - hidden_term

    def _sample_h(self, params, v, rng):
        pre = v @ params["W"] + params["b"]
        if self.hidden_unit == "rectified":
            # NReLU sampling: max(0, pre + N(0, sigmoid(pre))) (reference
            # RBM.java RectifiedLinear hidden sampling)
            noise = jax.random.normal(rng, pre.shape, pre.dtype)
            return jnp.maximum(0.0, pre + noise * jnp.sqrt(jax.nn.sigmoid(pre)))
        p = jax.nn.sigmoid(pre)
        return jax.random.bernoulli(rng, p).astype(v.dtype)

    def _sample_v(self, params, h, rng):
        pre = h @ params["W"].T + params["vb"]
        if self.visible_unit == "gaussian":
            return pre + jax.random.normal(rng, pre.shape, pre.dtype)
        p = jax.nn.sigmoid(pre)
        return jax.random.bernoulli(rng, p).astype(h.dtype)

    def gibbs_chain(self, params, v0, rng, k: Optional[int] = None):
        """k alternating Gibbs steps v -> h -> v' (reference RBM.gibbhVh)."""
        v = v0
        for step in range(k or self.k):
            r1 = jax.random.fold_in(rng, 2 * step)
            r2 = jax.random.fold_in(rng, 2 * step + 1)
            h = self._sample_h(params, v, r1)
            v = self._sample_v(params, h, r2)
        return v

    def pretrain_loss(self, params, x, rng):
        v_model = jax.lax.stop_gradient(self.gibbs_chain(params, x, rng))
        return jnp.mean(self.free_energy(params, x)) - \
            jnp.mean(self.free_energy(params, v_model))

    def reconstruct(self, params, x):
        """Deterministic one-step reconstruction (mean-field v->h->v) using
        each unit type's conditional mean: sigmoid for binary hiddens,
        relu(pre) for rectified (NReLU) — consistent with free_energy and
        the Gibbs sampler."""
        pre_h = x @ params["W"] + params["b"]
        if self.hidden_unit == "rectified":
            h = jnp.maximum(pre_h, 0.0)
        else:
            h = jax.nn.sigmoid(pre_h)
        pre_v = h @ params["W"].T + params["vb"]
        if self.visible_unit == "gaussian":
            return pre_v
        return jax.nn.sigmoid(pre_v)
