"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Reference parity:
- BatchNormalization -> nn/conf/layers/BatchNormalization.java +
  nn/layers/normalization/BatchNormalization.java (helper probe :56; cuDNN
  impl CudnnBatchNormalizationHelper). On TPU the fused form is what XLA
  emits natively — no helper seam needed; running stats live in the layer
  STATE pytree and are updated functionally at train time.
- LocalResponseNormalization -> nn/layers/normalization/
  LocalResponseNormalization.java (cross-channel window; k/n/alpha/beta
  defaults match the reference).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import ClassVar, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..conf.serde import register
from .base import LayerConf


@register
@dataclass
class BatchNormalization(LayerConf):
    n_out: Optional[int] = None        # feature/channel count (inferred)
    decay: float = 0.9                 # running-stat EMA decay (reference default)
    eps: float = 1e-5
    lock_gamma_beta: bool = False      # reference lockGammaBeta: fixed gamma/beta
    gamma_init: float = 1.0
    beta_init: float = 0.0

    param_order: ClassVar[Tuple[str, ...]] = ("gamma", "beta")
    weight_param_names: ClassVar[Tuple[str, ...]] = ()   # no l1/l2 on gamma/beta
    expected_input: ClassVar[str] = "any"

    def _nf(self, itype):
        if self.n_out:
            return self.n_out
        from ..inputs import InputTypeConvolutional
        if itype is None:
            raise ValueError(
                "BatchNormalization cannot infer its feature count: set "
                "n_out explicitly or provide an input type (set_input_type "
                "or n_in on the first layer)")
        if isinstance(itype, InputTypeConvolutional):
            return itype.channels
        return itype.size

    def init(self, rng, itype, dtype):
        nf = self._nf(itype)
        params = {}
        if not self.lock_gamma_beta:
            params = {"gamma": jnp.full((nf,), self.gamma_init, dtype),
                      "beta": jnp.full((nf,), self.beta_init, dtype)}
        state = {"mean": jnp.zeros((nf,), jnp.float32),
                 "var": jnp.ones((nf,), jnp.float32)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        axes = tuple(range(x.ndim - 1))  # all but channel/feature dim
        if train:
            # E[x^2]-E[x]^2: both reductions fuse into ONE pass over the
            # activation map (jnp.var re-reads x after computing the mean;
            # flax's default use_fast_variance does the same). Cancellation
            # can drive the difference slightly negative for large-mean/
            # small-variance activations — clamp so rsqrt(var+eps) stays
            # finite (precision in that regime is limited either way).
            mean = jnp.mean(x, axis=axes)
            var = jnp.maximum(jnp.mean(x * x, axis=axes) - mean * mean, 0.0)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean.astype(jnp.float32),
                "var": self.decay * state["var"] + (1 - self.decay) * var.astype(jnp.float32),
            }
        else:
            mean, var = state["mean"].astype(x.dtype), state["var"].astype(x.dtype)
            new_state = state
        inv = lax.rsqrt(var.astype(x.dtype) + jnp.asarray(self.eps, x.dtype))
        y = (x - mean.astype(x.dtype)) * inv
        if not self.lock_gamma_beta:
            y = y * params["gamma"] + params["beta"]
        else:
            y = y * self.gamma_init + self.beta_init
        return self.act(y), new_state


@register
@dataclass
class LayerNormalization(LayerConf):
    """Per-example layer norm over the FEATURE axis (net-new beyond the
    reference — its era predates transformers; required by the pre-LN
    transformer blocks in models.transformer_lm). Works on [B,F] and
    [B,T,F]; gain/bias per feature; no running stats (stateless, unlike
    BatchNormalization — nothing to desynchronize across a mesh)."""
    n_out: Optional[int] = None        # feature count (inferred)
    eps: float = 1e-5

    param_order: ClassVar[Tuple[str, ...]] = ("gain", "bias")
    weight_param_names: ClassVar[Tuple[str, ...]] = ()
    expected_input: ClassVar[str] = "any"

    def init(self, rng, itype, dtype):
        nf = self.n_out or (itype.size if itype is not None else None)
        if not nf:
            raise ValueError("LayerNormalization cannot infer its feature "
                             "count: set n_out or provide an input type")
        self.n_out = nf
        return {"gain": jnp.ones((nf,), dtype),
                "bias": jnp.zeros((nf,), dtype)}, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.maximum(jnp.mean(x * x, axis=-1, keepdims=True)
                          - mean * mean, 0.0)
        inv = lax.rsqrt(var + jnp.asarray(self.eps, x.dtype))
        y = (x - mean) * inv * params["gain"] + params["bias"]
        return self.act(y), state


@register
@dataclass
class LocalResponseNormalization(LayerConf):
    """Cross-channel LRN over NHWC (reference defaults k=2, n=5, alpha=1e-4,
    beta=0.75)."""
    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    expected_input: ClassVar[str] = "cnn"

    def apply(self, params, state, x, *, train=False, rng=None):
        half = self.n // 2
        sq = x * x
        # windowed sum over the channel (last) dim
        summed = lax.reduce_window(sq, 0.0, lax.add,
                                   (1, 1, 1, self.n), (1, 1, 1, 1),
                                   ((0, 0), (0, 0), (0, 0), (half, half)))
        denom = (self.k + self.alpha * summed) ** self.beta
        return x / denom, state
