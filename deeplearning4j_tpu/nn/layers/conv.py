"""Convolution + pooling + padding layers (NHWC, TPU-native).

Reference parity:
- ConvolutionLayer   -> nn/conf/layers/ConvolutionLayer.java +
  nn/layers/convolution/ConvolutionLayer.java (im2col+gemm fallback :181-197,
  cuDNN helper probe :72). Here the conv IS the accelerated path:
  lax.conv_general_dilated lowers straight onto the MXU — the helper seam the
  reference needed for cuDNN is replaced by XLA lowering (SURVEY.md §2.6.2).
- Convolution1DLayer -> nn/conf/layers/Convolution1DLayer.java (NWC).
- SubsamplingLayer   -> nn/layers/convolution/subsampling/* (MAX/AVG/PNORM/SUM)
- Subsampling1DLayer
- ZeroPaddingLayer   -> nn/conf/layers/ZeroPaddingLayer.java
- SpaceToDepth-style reshapes are covered by preprocessors.

ConvolutionMode semantics (reference nn/conf/ConvolutionMode.java):
"strict"/"truncate" = VALID with explicit padding; "same" = SAME (stride-aware).

Layouts: NHWC / HWIO — channels ride the 128-lane minor dimension; bf16-ready.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..conf.serde import register
from ..inputs import InputTypeConvolutional, InputTypeRecurrent
from .base import LayerConf, maybe_dropout


def _pair(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def conv_output_size(size, k, s, p, mode):
    if mode == "same":
        return -(-size // s)  # ceil
    return (size + 2 * p - k) // s + 1


@register
@dataclass
class ConvolutionLayer(LayerConf):
    n_in: Optional[int] = None            # input channels (inferred)
    n_out: int = 0                        # output channels
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"    # strict | truncate | same
    dilation: Tuple[int, int] = (1, 1)
    cudnn_algo_mode: Optional[str] = None  # accepted no-op (XLA autotunes; SURVEY §2.6.8)
    # reference ConvolutionLayer hasBias; False saves the full-activation-map
    # bias add (+ its reduce in backward) when a BatchNorm follows
    has_bias: bool = True

    param_order: ClassVar[Tuple[str, ...]] = ("W", "b")
    expected_input: ClassVar[str] = "cnn"

    def _geom(self):
        return _pair(self.kernel_size), _pair(self.stride), _pair(self.padding), _pair(self.dilation)

    def output_type(self, itype):
        (kh, kw), (sh, sw), (ph, pw), _ = self._geom()
        mode = self.convolution_mode
        h = conv_output_size(itype.height, kh, sh, ph, mode)
        w = conv_output_size(itype.width, kw, sw, pw, mode)
        return InputTypeConvolutional(h, w, self.n_out)

    def init(self, rng, itype, dtype):
        (kh, kw), _, _, _ = self._geom()
        c_in = self.n_in if self.n_in else itype.channels
        fan_in = kh * kw * c_in
        fan_out = kh * kw * self.n_out
        W = self._winit(rng, (kh, kw, c_in, self.n_out), fan_in, fan_out, dtype)
        params = {"W": W}
        if self.has_bias:
            params["b"] = self._binit((self.n_out,), dtype)
        return params, {}

    def pre_output(self, params, x, *, train=False, rng=None):
        x = maybe_dropout(x, self.dropout, rng, train)
        (kh, kw), (sh, sw), (ph, pw), (dh, dw) = self._geom()
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            pad = [(ph, ph), (pw, pw)]
        # no preferred_element_type: the TPU MXU already accumulates bf16
        # matmuls in f32, and forcing f32 outputs breaks the conv VJP
        # (f32 cotangent vs bf16 kernel in the transpose conv)
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(sh, sw), padding=pad,
            rhs_dilation=(dh, dw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + params["b"] if self.has_bias else y

    def apply(self, params, state, x, *, train=False, rng=None):
        # fused conv1x1+bias+relu helper probe (the reference's cuDNN
        # helper seam, ConvolutionLayer.java:72, done the registry way)
        from ...ops.kernels.conv import (conv1x1_bias_relu,
                                         conv1x1_bias_relu_applicable)
        (kh, kw), (sh, sw), (ph, pw), (dh, dw) = self._geom()
        if self.has_bias and "b" in params and x.ndim == 4 and \
                conv1x1_bias_relu_applicable(
                    (kh, kw), (sh, sw), (dh, dw), (ph, pw),
                    self.convolution_mode, True, self.activation,
                    int(x.shape[-1]), int(params["W"].shape[-1]), x.dtype):
            x = maybe_dropout(x, self.dropout, rng, train)
            return conv1x1_bias_relu(x, params["W"], params["b"]), state
        return self.act(self.pre_output(params, x, train=train, rng=rng)), state


@register
@dataclass
class Convolution1DLayer(LayerConf):
    """Temporal convolution over [B,T,F] (reference Convolution1DLayer)."""
    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    convolution_mode: str = "same"
    dilation: int = 1

    param_order: ClassVar[Tuple[str, ...]] = ("W", "b")
    expected_input: ClassVar[str] = "rnn"

    def output_type(self, itype):
        t = itype.timestep_length
        if t and t > 0:
            t = conv_output_size(t, self.kernel_size, self.stride, self.padding,
                                 self.convolution_mode)
        return InputTypeRecurrent(self.n_out, t)

    def init(self, rng, itype, dtype):
        c_in = self.n_in if self.n_in else itype.size
        fan_in = self.kernel_size * c_in
        fan_out = self.kernel_size * self.n_out
        W = self._winit(rng, (self.kernel_size, c_in, self.n_out), fan_in, fan_out, dtype)
        return {"W": W, "b": self._binit((self.n_out,), dtype)}, {}

    def pre_output(self, params, x, *, train=False, rng=None):
        x = maybe_dropout(x, self.dropout, rng, train)
        pad = "SAME" if self.convolution_mode == "same" else [(self.padding, self.padding)]
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        return y + params["b"]

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.act(self.pre_output(params, x, train=train, rng=rng)), state


@register
@dataclass
class SubsamplingLayer(LayerConf):
    """Spatial pooling (reference nn/layers/convolution/subsampling/
    SubsamplingLayer.java): MAX / AVG / SUM / PNORM."""
    pooling_type: str = "max"
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2
    # Reference SubsamplingLayer averages over the full (zero-padded) window
    # (activate: col2d.mean over the padded im2col; backprop divides by
    # prod(kernelSize)); TF/Keras excludes implicit padding. Default matches
    # the reference; the Keras importer sets False (DL4J's own
    # avgPoolIncludePadInDivisor seam).
    avg_pool_include_pad_in_divisor: bool = True

    expected_input: ClassVar[str] = "cnn"

    def output_type(self, itype):
        (kh, kw), (sh, sw), (ph, pw) = _pair(self.kernel_size), _pair(self.stride), _pair(self.padding)
        h = conv_output_size(itype.height, kh, sh, ph, self.convolution_mode)
        w = conv_output_size(itype.width, kw, sw, pw, self.convolution_mode)
        return InputTypeConvolutional(h, w, itype.channels)

    def apply(self, params, state, x, *, train=False, rng=None):
        (kh, kw), (sh, sw), (ph, pw) = _pair(self.kernel_size), _pair(self.stride), _pair(self.padding)
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        pt = self.pooling_type.lower()
        if pt == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            y = lax.reduce_window(x, init, lax.max, dims, strides, pad)
        elif pt in ("avg", "sum"):
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            if pt == "avg":
                if pad == "SAME" and not self.avg_pool_include_pad_in_divisor:
                    # exclude implicit padding from the denominator (TF/Keras
                    # semantics; windows at the edge average over fewer cells)
                    ones = jnp.ones(x.shape[:1] + x.shape[1:3] + (1,), x.dtype)
                    cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                            pad)
                    y = y / cnt
                else:
                    y = y / (kh * kw)
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, pad) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type!r}")
        return y, state


@register
@dataclass
class Subsampling1DLayer(LayerConf):
    pooling_type: str = "max"
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = "truncate"
    pnorm: int = 2
    # see SubsamplingLayer: reference divides by the full kernel size
    avg_pool_include_pad_in_divisor: bool = True

    expected_input: ClassVar[str] = "rnn"

    def output_type(self, itype):
        t = itype.timestep_length
        if t and t > 0:
            t = conv_output_size(t, self.kernel_size, self.stride, self.padding,
                                 self.convolution_mode)
        return InputTypeRecurrent(itype.size, t)

    def apply(self, params, state, x, *, train=False, rng=None):
        k, s, p = self.kernel_size, self.stride, self.padding
        dims, strides = (1, k, 1), (1, s, 1)
        pad = "SAME" if self.convolution_mode == "same" else ((0, 0), (p, p), (0, 0))
        pt = self.pooling_type.lower()
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        elif pt in ("avg", "sum"):
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            if pt == "avg":
                if pad == "SAME" and not self.avg_pool_include_pad_in_divisor:
                    # exclude implicit padding (TF/Keras edge semantics)
                    ones = jnp.ones(x.shape[:2] + (1,), x.dtype)
                    cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                            pad)
                    y = y / cnt
                else:
                    y = y / k
        elif pt == "pnorm":
            pw = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** pw, 0.0, lax.add, dims, strides, pad) ** (1.0 / pw)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type!r}")
        return y, state


@register
@dataclass
class ZeroPaddingLayer(LayerConf):
    """Spatial zero padding (reference nn/conf/layers/ZeroPaddingLayer.java).
    padding = (top, bottom, left, right) or (h, w)."""
    padding: Tuple[int, ...] = (0, 0)

    expected_input: ClassVar[str] = "cnn"

    def _pads(self):
        p = tuple(int(v) for v in self.padding)
        if len(p) == 2:
            return (p[0], p[0], p[1], p[1])
        return p

    def output_type(self, itype):
        t, b, l, r = self._pads()
        return InputTypeConvolutional(itype.height + t + b, itype.width + l + r,
                                      itype.channels)

    def apply(self, params, state, x, *, train=False, rng=None):
        t, b, l, r = self._pads()
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register
@dataclass
class ZeroPadding1DLayer(LayerConf):
    """Temporal zero padding over [B,T,F] (reference
    nn/conf/layers — Keras registry ZeroPadding1D, KerasLayer.java:53-70).
    padding = int (symmetric) or (left, right)."""
    padding: Tuple[int, ...] = (0, 0)

    expected_input: ClassVar[str] = "rnn"

    def _pads(self):
        p = self.padding
        if isinstance(p, int):
            return (p, p)
        p = tuple(int(v) for v in p)
        return (p[0], p[0]) if len(p) == 1 else p

    def output_type(self, itype):
        l, r = self._pads()
        t = itype.timestep_length
        return InputTypeRecurrent(itype.size, t + l + r if t and t > 0 else t)

    def apply(self, params, state, x, *, train=False, rng=None):
        l, r = self._pads()
        return jnp.pad(x, ((0, 0), (l, r), (0, 0))), state


@register
@dataclass
class GlobalPoolingLayer(LayerConf):
    """Global pooling over spatial (CNN) or time (RNN) dims with mask support
    (reference nn/layers/pooling/GlobalPoolingLayer.java; masked reductions
    util/MaskedReductionUtil.java)."""
    pooling_type: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True

    expected_input: ClassVar[str] = "any"

    def output_type(self, itype):
        from ..inputs import InputTypeFeedForward
        if isinstance(itype, InputTypeRecurrent):
            return InputTypeFeedForward(itype.size)
        if isinstance(itype, InputTypeConvolutional):
            return InputTypeFeedForward(itype.channels)
        return itype

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # [B,T,F] -> reduce T ; [B,H,W,C] -> reduce H,W
        axes = (1,) if x.ndim == 3 else (1, 2)
        pt = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            m = mask.astype(x.dtype)[..., None]
            if pt == "max":
                x = jnp.where(m > 0, x, -jnp.inf)
            else:
                x = x * m
        if pt == "max":
            y = jnp.max(x, axis=axes)
        elif pt == "sum":
            y = jnp.sum(x, axis=axes)
        elif pt == "avg":
            if mask is not None and x.ndim == 3:
                denom = jnp.clip(jnp.sum(mask.astype(x.dtype), axis=1, keepdims=False), 1.0, None)
                y = jnp.sum(x, axis=1) / denom[:, None]
            else:
                y = jnp.mean(x, axis=axes)
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type!r}")
        return y, state
