"""Self-attention layer (net-new, beyond reference parity).

The reference's sequence story is LSTM-only (SURVEY.md §5.7 explicitly notes
no attention exists). This layer adds the modern long-context primitive in
the framework's own layer SPI: multi-head softmax self-attention over
[B,T,F], mask-aware, causal-optional — single-device math in
parallel/ring_attention.attention, and the time axis is mesh-shardable via
parallel/ring_attention.ring_attention_sharded (sequence/context
parallelism over ICI).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, ClassVar, Optional, Tuple

import jax
import jax.numpy as jnp

from ..conf.serde import register
from ..inputs import InputTypeRecurrent
from .base import LayerConf, maybe_dropout, resolve_ff_size


@register
@dataclass
class SelfAttentionLayer(LayerConf):
    """Multi-head self-attention, [B,T,F] -> [B,T,n_out].

    ``n_out`` must be divisible by ``n_heads``. With ``causal`` each position
    attends only to itself and earlier steps. A [B,T] feature mask excludes
    padded timesteps as attention KEYS (queries at masked positions produce
    outputs that downstream masked losses ignore, matching the framework's
    masking convention).
    """
    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 4
    causal: bool = False
    project_out: bool = True

    param_order: ClassVar[Tuple[str, ...]] = ("Wq", "Wk", "Wv", "Wo", "b")
    weight_param_names: ClassVar[Tuple[str, ...]] = ("Wq", "Wk", "Wv", "Wo")
    expected_input: ClassVar[str] = "rnn"
    accepts_mask: ClassVar[bool] = True

    def output_type(self, itype):
        t = itype.timestep_length if isinstance(itype, InputTypeRecurrent) else -1
        return InputTypeRecurrent(self.n_out, t)

    def init(self, rng, itype, dtype):
        n_in = self.n_in or resolve_ff_size(itype)
        self.n_in = n_in
        if self.n_out % self.n_heads:
            raise ValueError(f"n_out={self.n_out} must be divisible by "
                             f"n_heads={self.n_heads}")
        ks = jax.random.split(rng, 4)
        d = self.n_out
        params = {
            "Wq": self._winit(ks[0], (n_in, d), n_in, d, dtype),
            "Wk": self._winit(ks[1], (n_in, d), n_in, d, dtype),
            "Wv": self._winit(ks[2], (n_in, d), n_in, d, dtype),
            "Wo": self._winit(ks[3], (d, d), d, d, dtype),
            "b": self._binit((d,), dtype),
        }
        return params, {}

    def _heads(self, x):
        B, T, _ = x.shape
        return x.reshape(B, T, self.n_heads, -1).transpose(0, 2, 1, 3)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from ...ops.pallas_attention import (flash_attention,
                                             fused_attention_applicable)
        from ...parallel.ring_attention import attention
        x = maybe_dropout(x, self.dropout, rng, train)
        q = self._heads(x @ params["Wq"])
        k = self._heads(x @ params["Wk"])
        v = self._heads(x @ params["Wv"])
        B, H, T, Dh = q.shape
        if fused_attention_applicable(B, H, T, Dh, q.dtype):
            # fused Pallas path: O(T) HBM traffic (ops/pallas_attention.py)
            out = flash_attention(q, k, v, causal=self.causal, key_mask=mask)
        else:
            out = attention(q, k, v, causal=self.causal, key_mask=mask)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
        if self.project_out:
            out = out @ params["Wo"] + params["b"]
        return self.act(out), state
