"""Core feed-forward layers: Dense, Activation, Dropout, Embedding, Output family.

Reference parity:
- DenseLayer        -> nn/conf/layers/DenseLayer.java + nn/layers/feedforward/dense/DenseLayer.java
- ActivationLayer   -> nn/conf/layers/ActivationLayer.java
- DropoutLayer      -> nn/conf/layers/DropoutLayer.java
- EmbeddingLayer    -> nn/layers/feedforward/embedding/EmbeddingLayer.java
- OutputLayer       -> nn/conf/layers/OutputLayer.java + nn/layers/BaseOutputLayer
- LossLayer         -> nn/conf/layers/LossLayer.java (no params, loss on input)
- RnnOutputLayer    -> nn/conf/layers/RnnOutputLayer.java (time-distributed output)
- AutoEncoder       -> nn/layers/feedforward/autoencoder/AutoEncoder.java (denoising AE)

TPU notes: Dense on a recurrent [B,T,F] input applies per-timestep via a single
batched matmul (equivalent to the reference's RnnToFeedForwardPreProcessor
sandwich, but as ONE einsum the MXU tiles directly).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, ClassVar, Optional, Tuple

import jax
import jax.numpy as jnp

from ..conf.serde import register
from ..inputs import (InputTypeConvolutional, InputTypeConvolutionalFlat,
                      InputTypeFeedForward, InputTypeRecurrent)
from ..losses import get_loss
from .base import LayerConf, maybe_dropout, resolve_ff_size


@register
@dataclass
class DenseLayer(LayerConf):
    n_in: Optional[int] = None
    n_out: int = 0

    param_order: ClassVar[Tuple[str, ...]] = ("W", "b")

    def output_type(self, itype):
        if isinstance(itype, InputTypeRecurrent):
            return InputTypeRecurrent(self.n_out, itype.timestep_length)
        return InputTypeFeedForward(self.n_out)

    def init(self, rng, itype, dtype):
        n_in = self.n_in or resolve_ff_size(itype)
        W = self._winit(rng, (n_in, self.n_out), n_in, self.n_out, dtype)
        return {"W": W, "b": self._binit((self.n_out,), dtype)}, {}

    def pre_output(self, params, x, *, train=False, rng=None):
        x = maybe_dropout(x, self.dropout, rng, train)
        return x @ params["W"] + params["b"]

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.act(self.pre_output(params, x, train=train, rng=rng)), state


@register
@dataclass
class ActivationLayer(LayerConf):
    expected_input: ClassVar[str] = "any"

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.act(x), state


@register
@dataclass
class DropoutLayer(LayerConf):
    expected_input: ClassVar[str] = "any"

    def apply(self, params, state, x, *, train=False, rng=None):
        return maybe_dropout(x, self.dropout, rng, train), state


@register
@dataclass
class EmbeddingLayer(LayerConf):
    """Index -> vector lookup. Input: int indices [B] or [B,1] (the reference
    expects a single index column, EmbeddingLayer.java). A gather on TPU; the
    backward pass is a scatter-add XLA emits natively."""
    n_in: Optional[int] = None     # vocab size
    n_out: int = 0

    param_order: ClassVar[Tuple[str, ...]] = ("W", "b")
    expected_input: ClassVar[str] = "any"

    def output_type(self, itype):
        return InputTypeFeedForward(self.n_out)

    def init(self, rng, itype, dtype):
        n_in = self.n_in or resolve_ff_size(itype)
        W = self._winit(rng, (n_in, self.n_out), n_in, self.n_out, dtype)
        return {"W": W, "b": self._binit((self.n_out,), dtype)}, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        out = params["W"][idx] + params["b"]
        return self.act(out), state


@register
@dataclass
class EmbeddingSequenceLayer(LayerConf):
    """Sequence of token ids -> sequence of vectors: [B,T] (or [B,T,1])
    int ids -> [B,T,n_out] (reference
    nn/conf/layers/EmbeddingSequenceLayer.java). ONE gather instead of a
    one-hot matmul — the TPU-first input path for transformer/RNN LMs:
    HBM traffic O(B*T*d) instead of O(B*T*V), backward is the scatter-add
    XLA emits natively. Declare the graph input as
    ``InputType.recurrent(1, T)`` (one index per timestep)."""
    n_in: Optional[int] = None     # vocab size (required)
    n_out: int = 0

    param_order: ClassVar[Tuple[str, ...]] = ("W",)
    expected_input: ClassVar[str] = "any"

    def output_type(self, itype):
        T = getattr(itype, "timestep_length", -1)
        return InputTypeRecurrent(self.n_out, T)

    def init(self, rng, itype, dtype):
        if not self.n_in:
            raise ValueError("EmbeddingSequenceLayer needs n_in (the vocab "
                             "size) — it cannot be inferred from a [B,T] "
                             "index input")
        W = self._winit(rng, (self.n_in, self.n_out), self.n_in, self.n_out,
                        dtype)
        return {"W": W}, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        idx = x
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        idx = idx.astype(jnp.int32)
        out = params["W"][idx]
        return self.act(maybe_dropout(out, self.dropout, rng, train)), state


@register
@dataclass
class PositionalEmbeddingLayer(LayerConf):
    """Learned absolute positional embeddings added to [B,T,F] activations
    (net-new — required for order-aware attention stacks like
    models.transformer_lm; the reference's recurrent nets carry position in
    their state and never needed one). ``max_length`` bounds T; shorter
    sequences use the table prefix."""
    n_out: Optional[int] = None        # feature size (inferred)
    max_length: int = 2048

    param_order: ClassVar[Tuple[str, ...]] = ("P",)
    weight_param_names: ClassVar[Tuple[str, ...]] = ()   # no decay on positions
    expected_input: ClassVar[str] = "rnn"

    def output_type(self, itype):
        return itype

    def init(self, rng, itype, dtype):
        nf = self.n_out or resolve_ff_size(itype)
        self.n_out = nf
        # small-scale normal init (transformer convention)
        P = 0.02 * jax.random.normal(rng, (self.max_length, nf), dtype)
        return {"P": P}, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        T = x.shape[1]
        if T > self.max_length:
            raise ValueError(f"sequence length {T} exceeds max_length "
                             f"{self.max_length}")
        return self.act(x + params["P"][:T][None]), state


class BaseOutputLayerMixin:
    """Shared loss plumbing for output layers (reference nn/layers/BaseOutputLayer).

    ``compute_loss_per_example`` runs on PRE-activation output so softmax/sigmoid
    cross-entropies take the fused stable path.
    """

    def compute_loss_per_example(self, params, x, labels, mask=None, *, train=False, rng=None):
        pre = self.pre_output(params, x, train=train, rng=rng)
        return get_loss(self.loss)(labels, pre, self.activation or "identity", mask)


@register
@dataclass
class OutputLayer(DenseLayer, BaseOutputLayerMixin):
    loss: str = "mcxent"


@register
@dataclass
class LossLayer(LayerConf, BaseOutputLayerMixin):
    """Loss on the incoming activations; no parameters."""
    loss: str = "mcxent"
    expected_input: ClassVar[str] = "any"

    def pre_output(self, params, x, *, train=False, rng=None):
        return x

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.act(x), state


@register
@dataclass
class RnnOutputLayer(DenseLayer, BaseOutputLayerMixin):
    """Time-distributed output layer for [B,T,F] activations (reference
    nn/conf/layers/RnnOutputLayer.java; per-timestep loss with masking)."""
    loss: str = "mcxent"
    expected_input: ClassVar[str] = "rnn"

    def output_type(self, itype):
        t = itype.timestep_length if isinstance(itype, InputTypeRecurrent) else -1
        return InputTypeRecurrent(self.n_out, t)


@register
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Output layer with center loss (reference
    nn/layers/training/CenterLossOutputLayer.java): adds lambda * ||f - c_y||^2
    and maintains per-class centers with EMA alpha."""
    alpha: float = 0.05
    lambda_: float = 2e-4

    param_order: ClassVar[Tuple[str, ...]] = ("W", "b", "centers")

    def init(self, rng, itype, dtype):
        params, state = super().init(rng, itype, dtype)
        n_in = self.n_in or resolve_ff_size(itype)
        params["centers"] = jnp.zeros((self.n_out, n_in), dtype)
        return params, state

    def compute_loss_per_example(self, params, x, labels, mask=None, *, train=False, rng=None):
        base = super().compute_loss_per_example(params, x, labels, mask, train=train, rng=rng)
        # Two one-sided terms replicate the reference's dynamics functionally:
        # features are pulled toward (stop-gradient) centers at rate lambda;
        # centers move toward (stop-gradient) features at rate alpha — SGD on
        # the alpha term is the EMA center update of the reference.
        centers_batch = labels @ params["centers"]  # [B, n_in], labels one-hot
        pull = jnp.sum((x - jax.lax.stop_gradient(centers_batch)) ** 2, axis=-1)
        chase = jnp.sum((jax.lax.stop_gradient(x) - centers_batch) ** 2, axis=-1)
        return base + 0.5 * self.lambda_ * pull + 0.5 * self.alpha * chase


@register
@dataclass
class AutoEncoder(LayerConf):
    """Denoising autoencoder. As a feed-forward layer it is encode();
    ``pretrain_loss`` gives the reconstruction objective with input corruption
    (reference nn/layers/feedforward/autoencoder/AutoEncoder.java)."""
    n_in: Optional[int] = None
    n_out: int = 0
    corruption_level: float = 0.3
    loss: str = "mse"

    param_order: ClassVar[Tuple[str, ...]] = ("W", "b", "vb")

    def output_type(self, itype):
        return InputTypeFeedForward(self.n_out)

    def init(self, rng, itype, dtype):
        n_in = self.n_in or resolve_ff_size(itype)
        W = self._winit(rng, (n_in, self.n_out), n_in, self.n_out, dtype)
        return {"W": W, "b": self._binit((self.n_out,), dtype),
                "vb": self._binit((n_in,), dtype)}, {}

    def encode(self, params, x):
        return self.act(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self.act(h @ params["W"].T + params["vb"])

    def apply(self, params, state, x, *, train=False, rng=None):
        x = maybe_dropout(x, self.dropout, rng, train)
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, rng):
        corrupt_rng, _ = jax.random.split(rng)
        keep = jax.random.bernoulli(corrupt_rng, 1.0 - self.corruption_level, x.shape)
        corrupted = jnp.where(keep, x, 0.0)
        recon_pre = self.encode(params, corrupted) @ params["W"].T + params["vb"]
        per_ex = get_loss(self.loss)(x, recon_pre, self.activation or "identity", None)
        return jnp.mean(per_ex)
