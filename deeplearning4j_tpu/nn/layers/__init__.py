from .base import LayerConf
from .core import (ActivationLayer, AutoEncoder, CenterLossOutputLayer,
                   DenseLayer, DropoutLayer, EmbeddingLayer,
                   EmbeddingSequenceLayer, LossLayer,
                   PositionalEmbeddingLayer,
                   OutputLayer, RnnOutputLayer)
from .conv import (Convolution1DLayer, ConvolutionLayer, GlobalPoolingLayer,
                   SubsamplingLayer, Subsampling1DLayer, ZeroPadding1DLayer,
                   ZeroPaddingLayer)
from .norm import (BatchNormalization, LayerNormalization,
                   LocalResponseNormalization)
from .attention import SelfAttentionLayer
from .recurrent import (GravesBidirectionalLSTM, GravesLSTM, LSTM,
                        LastTimeStepLayer)
from .variational import (BernoulliReconstructionDistribution,
                          CompositeReconstructionDistribution,
                          ExponentialReconstructionDistribution,
                          GaussianReconstructionDistribution,
                          LossFunctionWrapper, RBM, VariationalAutoencoder)

__all__ = [
    "SelfAttentionLayer",
    "BernoulliReconstructionDistribution", "CompositeReconstructionDistribution",
    "ExponentialReconstructionDistribution", "GaussianReconstructionDistribution",
    "LossFunctionWrapper", "RBM", "VariationalAutoencoder",
    "LayerConf", "ActivationLayer", "AutoEncoder", "CenterLossOutputLayer",
    "DenseLayer", "DropoutLayer", "EmbeddingLayer", "EmbeddingSequenceLayer",
    "LossLayer", "OutputLayer",
    "PositionalEmbeddingLayer",
    "RnnOutputLayer", "Convolution1DLayer", "ConvolutionLayer",
    "GlobalPoolingLayer", "SubsamplingLayer", "Subsampling1DLayer",
    "ZeroPadding1DLayer", "ZeroPaddingLayer", "BatchNormalization",
    "LayerNormalization",
    "LocalResponseNormalization",
    "GravesBidirectionalLSTM", "GravesLSTM", "LSTM", "LastTimeStepLayer",
]
