"""Activation function registry.

Covers the reference's Activation enum surface (reference:
nd4j Activation / used via string in deeplearning4j-nn layer configs, e.g.
nn/conf/layers/* ``activation(...)``): identity, relu, leakyrelu, sigmoid,
softmax, tanh, softplus, softsign, elu, selu, cube, hardtanh, hardsigmoid,
rationaltanh, rrelu(-as-leakyrelu), plus TPU-era additions (gelu, swish).

All are pure jnp functions — they fuse into the surrounding XLA computation
(the reference dispatches each through an ND4J transform op; on TPU they are
free, folded into the preceding matmul's epilogue by XLA).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_ACTIVATIONS = {}


def register_activation(name):
    def deco(fn):
        _ACTIVATIONS[name] = fn
        return fn
    return deco


def get_activation(name):
    if callable(name):
        return name
    key = str(name).lower()
    if key not in _ACTIVATIONS:
        raise ValueError(f"Unknown activation {name!r}; available: {sorted(_ACTIVATIONS)}")
    return _ACTIVATIONS[key]


def activation_names():
    return sorted(_ACTIVATIONS)


register_activation("identity")(lambda x: x)
register_activation("relu")(jax.nn.relu)
register_activation("relu6")(jax.nn.relu6)
register_activation("sigmoid")(jax.nn.sigmoid)
register_activation("tanh")(jnp.tanh)
register_activation("softplus")(jax.nn.softplus)
register_activation("softsign")(jax.nn.soft_sign)
register_activation("elu")(jax.nn.elu)
register_activation("selu")(jax.nn.selu)
register_activation("gelu")(jax.nn.gelu)
register_activation("swish")(jax.nn.silu)
register_activation("cube")(lambda x: x ** 3)
register_activation("hardtanh")(lambda x: jnp.clip(x, -1.0, 1.0))
register_activation("hardsigmoid")(jax.nn.hard_sigmoid)


@register_activation("softmax")
def softmax(x):
    return jax.nn.softmax(x, axis=-1)


@register_activation("logsoftmax")
def log_softmax(x):
    return jax.nn.log_softmax(x, axis=-1)


@register_activation("leakyrelu")
def leaky_relu(x):
    # Reference default alpha = 0.01
    return jax.nn.leaky_relu(x, negative_slope=0.01)


@register_activation("rrelu")
def rrelu(x):
    # Deterministic rrelu (mean slope) — reference randomizes slope in train.
    return jax.nn.leaky_relu(x, negative_slope=(1.0 / 8.0 + 1.0 / 3.0) / 2.0)


@register_activation("rationaltanh")
def rational_tanh(x):
    """Rational approximation of 1.7159*tanh(2x/3) (reference ActivationRationalTanh)."""
    y = 2.0 * x / 3.0
    a = jnp.abs(y)
    approx = 1.0 - 1.0 / (1.0 + a + y * y + 1.41645 * (y ** 4))
    return 1.7159 * jnp.sign(y) * approx
