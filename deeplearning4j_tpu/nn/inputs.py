"""Input type system: shape inference between layers.

Mirrors the capability of the reference InputType system
(reference: deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/inputs/InputType.java:62-94),
which drives automatic nIn inference and automatic insertion of input
preprocessors between layer families (CNN<->FF, FF<->RNN, CNN<->RNN).

TPU note: all shapes here are static python ints — XLA requires static shapes,
so shape inference happens once at config-build time, never inside jit.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass

from .conf.serde import register


class InputType:
    """Factory namespace, mirroring InputType.feedForward(...) etc."""

    @staticmethod
    def feed_forward(size: int) -> "InputTypeFeedForward":
        return InputTypeFeedForward(int(size))

    @staticmethod
    def recurrent(size: int, timestep_length: int = -1) -> "InputTypeRecurrent":
        return InputTypeRecurrent(int(size), int(timestep_length))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputTypeConvolutional":
        return InputTypeConvolutional(int(height), int(width), int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputTypeConvolutionalFlat":
        return InputTypeConvolutionalFlat(int(height), int(width), int(channels))


@register
@dataclass(frozen=True)
class InputTypeFeedForward:
    size: int

    def flat_size(self) -> int:
        return self.size

    def batch_shape(self, batch: int):
        return (batch, self.size)


@register
@dataclass(frozen=True)
class InputTypeRecurrent:
    size: int
    timestep_length: int = -1

    def flat_size(self) -> int:
        return self.size

    def batch_shape(self, batch: int):
        # Layout: [batch, time, features] (time-major inside scan is handled by
        # the layer; public layout is batch-major, unlike the reference's
        # [miniBatch, size, timeSeriesLength] NCW layout — BTC is the
        # TPU/XLA-friendly layout for scan + masking).
        return (batch, self.timestep_length, self.size)


@register
@dataclass(frozen=True)
class InputTypeConvolutional:
    height: int
    width: int
    channels: int

    def flat_size(self) -> int:
        return self.height * self.width * self.channels

    def batch_shape(self, batch: int):
        # NHWC: TPU-native conv layout (the reference uses NCHW for cuDNN;
        # XLA:TPU prefers NHWC with channels on the 128-lane minor dim).
        return (batch, self.height, self.width, self.channels)


@register
@dataclass(frozen=True)
class InputTypeConvolutionalFlat:
    height: int
    width: int
    channels: int

    def flat_size(self) -> int:
        return self.height * self.width * self.channels

    def batch_shape(self, batch: int):
        return (batch, self.flat_size())
