"""Loss functions.

Parity with the reference LossFunctions surface (used by output layers via
``lossFunction(...)``; the impls live in ND4J's nd4j-backends loss classes —
referenced from nn/conf/layers/OutputLayer.java and
nn/layers/BaseOutputLayer computeScore): MSE, L1, L2, XENT (binary CE),
MCXENT, NEGATIVELOGLIKELIHOOD, HINGE, SQUARED_HINGE, KL_DIVERGENCE,
MEAN_ABSOLUTE_ERROR, MEAN_ABSOLUTE_PERCENTAGE_ERROR,
MEAN_SQUARED_LOGARITHMIC_ERROR, COSINE_PROXIMITY, POISSON.

Each loss takes *pre-activation* output ("preout") plus the activation name so
that softmax/sigmoid cross-entropies use the numerically-stable fused
log-softmax / logits formulations (the reference relies on clipped doubles;
fused logits is the XLA-friendly equivalent). Autodiff supplies gradients —
the reference's hand-written computeGradient methods are unnecessary.

All losses return per-example scores of shape [batch]; masks (per-element or
per-example) multiply elementwise losses before reduction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .activations import get_activation

_LOSSES = {}


def register_loss(*names):
    def deco(fn):
        for n in names:
            _LOSSES[n] = fn
        return fn
    return deco


def get_loss(name):
    key = str(name).lower()
    if key not in _LOSSES:
        raise ValueError(f"Unknown loss {name!r}; available: {sorted(_LOSSES)}")
    return _LOSSES[key]


def loss_names():
    return sorted(_LOSSES)


def _reduce(elementwise, mask):
    """Sum elementwise loss over feature dims -> per-example score; apply mask."""
    if mask is not None:
        mask = jnp.broadcast_to(mask.astype(elementwise.dtype).reshape(
            mask.shape + (1,) * (elementwise.ndim - mask.ndim)), elementwise.shape)
        elementwise = elementwise * mask
    axes = tuple(range(1, elementwise.ndim))
    return jnp.sum(elementwise, axis=axes)


def _activate(preout, activation):
    return get_activation(activation)(preout)


@register_loss("mse", "squared_loss")
def mse(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    # Reference MSE divides by nOut (LossMSE = LossL2 / nOut).
    return _reduce((out - labels) ** 2, mask) / labels.shape[-1]


@register_loss("l2")
def l2(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    return _reduce((out - labels) ** 2, mask)


@register_loss("mean_absolute_error", "mae")
def mae(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    return _reduce(jnp.abs(out - labels), mask) / labels.shape[-1]


@register_loss("l1")
def l1(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    return _reduce(jnp.abs(out - labels), mask)


@register_loss("mean_absolute_percentage_error", "mape")
def mape(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    eps = 1e-8
    return _reduce(100.0 * jnp.abs((out - labels) / (labels + eps)), mask) / labels.shape[-1]


@register_loss("mean_squared_logarithmic_error", "msle")
def msle(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    eps = 1e-8
    d = jnp.log1p(out + eps) - jnp.log1p(labels + eps)
    return _reduce(d ** 2, mask) / labels.shape[-1]


@register_loss("xent", "binary_crossentropy")
def xent(labels, preout, activation, mask=None):
    act = str(activation).lower()
    if act == "sigmoid":
        # Fused stable form from logits.
        ew = jnp.maximum(preout, 0) - preout * labels + jnp.log1p(jnp.exp(-jnp.abs(preout)))
    else:
        out = jnp.clip(_activate(preout, activation), 1e-7, 1.0 - 1e-7)
        ew = -(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out))
    return _reduce(ew, mask)


@register_loss("mcxent", "negativeloglikelihood", "categorical_crossentropy")
def mcxent(labels, preout, activation, mask=None):
    act = str(activation).lower()
    if act == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        logp = jnp.log(jnp.clip(_activate(preout, activation), 1e-7, 1.0))
    return _reduce(-labels * logp, mask)


@register_loss("sparse_mcxent")
def sparse_mcxent(labels, preout, activation, mask=None):
    """labels are integer class indices of shape [batch] (or [batch, time])."""
    logp = jax.nn.log_softmax(preout, axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if mask is not None:
        picked = picked * mask.astype(picked.dtype)
    axes = tuple(range(1, picked.ndim))
    return -jnp.sum(picked, axis=axes) if axes else -picked


@register_loss("hinge")
def hinge(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    # labels in {-1, +1}
    return _reduce(jnp.maximum(0.0, 1.0 - labels * out), mask)


@register_loss("squared_hinge")
def squared_hinge(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    return _reduce(jnp.maximum(0.0, 1.0 - labels * out) ** 2, mask)


@register_loss("kl_divergence", "kld", "reconstruction_crossentropy")
def kl_divergence(labels, preout, activation, mask=None):
    out = jnp.clip(_activate(preout, activation), 1e-7, 1.0 - 1e-7)
    lab = jnp.clip(labels, 1e-7, 1.0)
    return _reduce(lab * (jnp.log(lab) - jnp.log(out)), mask)


@register_loss("poisson")
def poisson(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    return _reduce(out - labels * jnp.log(jnp.clip(out, 1e-7, None)), mask)


@register_loss("cosine_proximity")
def cosine_proximity(labels, preout, activation, mask=None):
    out = _activate(preout, activation)
    ln = jnp.linalg.norm(labels, axis=-1, keepdims=True)
    on = jnp.linalg.norm(out, axis=-1, keepdims=True)
    cos = jnp.sum(labels * out, axis=-1, keepdims=True) / jnp.clip(ln * on, 1e-8, None)
    return _reduce(-cos, mask)
