"""Input preprocessors: shape adapters between layer families.

Reference: nn/conf/preprocessor/* (CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor,
FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor, RnnToCnnPreProcessor) —
auto-inserted by the InputType system (nn/conf/inputs/InputType.java:62-94).

All are pure static reshapes/transposes: free under XLA (layout changes fuse).
Layouts: FF [B,F]; RNN [B,T,F]; CNN [B,H,W,C] (NHWC, TPU-native — the
reference is NCHW for cuDNN).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .conf.serde import register
from .inputs import (InputType, InputTypeConvolutional, InputTypeConvolutionalFlat,
                     InputTypeFeedForward, InputTypeRecurrent)


@register
@dataclass
class CnnToFeedForwardPreProcessor:
    height: int
    width: int
    channels: int

    def apply(self, x):
        return x.reshape(x.shape[0], -1)

    def output_type(self, itype):
        return InputTypeFeedForward(self.height * self.width * self.channels)


@register
@dataclass
class FeedForwardToCnnPreProcessor:
    height: int
    width: int
    channels: int

    def apply(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, itype):
        return InputTypeConvolutional(self.height, self.width, self.channels)


@register
@dataclass
class RnnToFeedForwardPreProcessor:
    """[B,T,F] -> [B*T,F]. Rarely needed on TPU (dense layers are
    time-distributed natively) but provided for explicit reference parity."""

    def apply(self, x):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, itype):
        return InputTypeFeedForward(itype.size)


@register
@dataclass
class FeedForwardToRnnPreProcessor:
    timestep_length: int = -1

    def apply(self, x):
        t = self.timestep_length
        return x.reshape(-1, t, x.shape[-1])

    def output_type(self, itype):
        return InputTypeRecurrent(itype.size, self.timestep_length)


@register
@dataclass
class CnnToRnnPreProcessor:
    """[B,T? folded] — reference folds CNN activations per timestep. Layout
    here: [B*T,H,W,C] -> [B,T,H*W*C]."""
    height: int
    width: int
    channels: int
    timestep_length: int = -1

    def apply(self, x):
        f = self.height * self.width * self.channels
        return x.reshape(-1, self.timestep_length, f)

    def output_type(self, itype):
        return InputTypeRecurrent(self.height * self.width * self.channels,
                                  self.timestep_length)


@register
@dataclass
class RnnToCnnPreProcessor:
    height: int
    width: int
    channels: int

    def apply(self, x):
        return x.reshape(-1, self.height, self.width, self.channels)

    def output_type(self, itype):
        return InputTypeConvolutional(self.height, self.width, self.channels)


def auto_preprocessor(itype, expected: str):
    """Return (preprocessor|None, new_input_type) adapting ``itype`` to the
    layer-family input a layer expects (reference InputType auto-insertion)."""
    if expected == "any":
        return None, itype
    if expected == "ff":
        if isinstance(itype, InputTypeConvolutional):
            p = CnnToFeedForwardPreProcessor(itype.height, itype.width, itype.channels)
            return p, p.output_type(itype)
        if isinstance(itype, InputTypeConvolutionalFlat):
            return None, InputTypeFeedForward(itype.flat_size())
        return None, itype
    if expected == "cnn":
        if isinstance(itype, InputTypeConvolutionalFlat):
            p = FeedForwardToCnnPreProcessor(itype.height, itype.width, itype.channels)
            return p, p.output_type(itype)
        if isinstance(itype, InputTypeFeedForward):
            raise ValueError("Cannot feed flat FF input to a CNN layer without "
                             "an explicit FeedForwardToCnnPreProcessor")
        return None, itype
    if expected == "rnn":
        if isinstance(itype, InputTypeFeedForward):
            raise ValueError("Cannot feed FF input to an RNN layer without an "
                             "explicit FeedForwardToRnnPreProcessor")
        if isinstance(itype, (InputTypeConvolutional, InputTypeConvolutionalFlat)):
            # the time axis is ambiguous for a plain image: CNN->RNN is the
            # video pipeline (T folded into batch) and needs the explicit
            # CnnToRnnPreProcessor(h, w, c, timestep_length) — silently
            # guessing here would mispredict every downstream shape
            raise ValueError(
                "Cannot feed CNN activations to an RNN layer without an "
                "explicit CnnToRnnPreProcessor(height, width, channels, "
                "timestep_length): the time dimension is ambiguous "
                "(reference InputTypeUtil CNN->RNN is the time-distributed "
                "video seam)")
        return None, itype
    return None, itype
