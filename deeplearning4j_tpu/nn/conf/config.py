"""Network configuration: fluent builder -> serializable MultiLayerConfiguration.

Reference: nn/conf/NeuralNetConfiguration.java:76 (Builder :535 — global
hyperparams cascaded into per-layer confs at build, :604-608),
nn/conf/MultiLayerConfiguration.java (JSON round-trip), BackpropType enum.

The TPU build keeps: the cascade semantics, n_in inference from InputType,
automatic preprocessor insertion, and config-as-JSON persistence. It drops:
workspace/cache modes (subsumed by XLA buffer assignment) — accepted as no-op
kwargs for API familiarity.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import serde
from .serde import register
from ..inputs import InputType, InputTypeFeedForward
from ..preprocessors import auto_preprocessor
from ...optimize.updaters import Sgd, UpdaterConf, updater_from_name


@register
@dataclass
class MultiLayerConfiguration:
    layers: List[Any] = field(default_factory=list)
    input_preprocessors: Dict[str, Any] = field(default_factory=dict)  # idx(str) -> preproc
    input_type: Optional[Any] = None
    seed: int = 12345
    dtype: str = "float32"
    backprop_type: str = "standard"       # "standard" | "tbptt"
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    pretrain: bool = False
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    updater: Optional[Any] = None         # global updater (layers may override)
    # reference nn/api/OptimizationAlgorithm.java:27 — STOCHASTIC_GRADIENT_DESCENT,
    # LINE_GRADIENT_DESCENT, CONJUGATE_GRADIENT, LBFGS
    optimization_algorithm: str = "sgd"
    max_num_line_search_iterations: int = 5
    # jax.checkpoint each layer's forward: activations are re-computed in the
    # backward pass instead of stored — trades FLOPs for HBM (the TPU
    # replacement for the reference's activation-caching knobs; deep stacks /
    # long sequences fit in memory at ~1.3x step cost)
    gradient_checkpointing: bool = False
    # mixed precision: keep MASTER params/updater state in ``dtype`` (f32)
    # but run the forward/backward compute in this dtype (e.g. 'bfloat16'
    # for the MXU fast path). Net-new beyond the reference — ND4J-era
    # DL4J has no AMP; on TPU it is the standard training recipe.
    compute_dtype: Optional[str] = None

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return serde.from_json(s)

    def preprocessor(self, idx: int):
        return self.input_preprocessors.get(str(idx))


class NeuralNetConfiguration:
    """Global-defaults builder (reference NeuralNetConfiguration.Builder).

    Usage::

        conf = (NeuralNetConfiguration(seed=42, updater=Adam(1e-3), l2=1e-4,
                                       weight_init="xavier", activation="relu")
                .list(DenseLayer(n_out=128),
                      OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional_flat(28, 28, 1))
                .build())
    """

    def __init__(self, seed: int = 12345, activation: str = "sigmoid",
                 weight_init: str = "xavier", bias_init: float = 0.0,
                 distribution=None, l1: float = 0.0, l2: float = 0.0,
                 dropout: float = 0.0, updater=None, learning_rate: Optional[float] = None,
                 bias_learning_rate: Optional[float] = None,
                 gradient_normalization: Optional[str] = None,
                 gradient_normalization_threshold: float = 1.0,
                 dtype: str = "float32", optimization_algorithm: str = "sgd",
                 max_num_line_search_iterations: int = 5,
                 gradient_checkpointing: bool = False,
                 compute_dtype: Optional[str] = None, **workspace_noops):
        if updater is None:
            updater = Sgd(learning_rate=learning_rate if learning_rate is not None else 0.1)
        elif isinstance(updater, str):
            updater = updater_from_name(updater, learning_rate or 0.1)
        elif learning_rate is not None and updater.learning_rate != learning_rate:
            updater = dataclasses.replace(updater, learning_rate=learning_rate)
        self.seed = seed
        self.activation = activation
        self.weight_init = weight_init
        self.bias_init = bias_init
        self.distribution = distribution
        self.l1 = l1
        self.l2 = l2
        self.dropout = dropout
        self.updater = updater
        self.learning_rate = learning_rate
        self.bias_learning_rate = bias_learning_rate
        self.gradient_normalization = gradient_normalization
        self.gradient_normalization_threshold = gradient_normalization_threshold
        self.dtype = dtype
        self.optimization_algorithm = optimization_algorithm.lower()
        self.max_num_line_search_iterations = max_num_line_search_iterations
        self.gradient_checkpointing = gradient_checkpointing
        if compute_dtype is not None:
            import jax.numpy as jnp
            try:
                jnp.dtype(compute_dtype)
            except TypeError as e:
                raise ValueError(
                    f"Unknown compute_dtype {compute_dtype!r} (expected a "
                    f"dtype name like 'bfloat16' or 'float32')") from e
        self.compute_dtype = compute_dtype

    # --- cascade (reference :604-608): fill None fields from globals ---
    def _cascade(self, layer):
        layer = dataclasses.replace(layer)
        if layer.activation is None:
            layer.activation = self.activation
        if layer.weight_init is None:
            layer.weight_init = self.weight_init
        if layer.distribution is None:
            layer.distribution = self.distribution
        if layer.bias_init is None:
            layer.bias_init = self.bias_init
        if layer.l1 is None:
            layer.l1 = self.l1
        if layer.l2 is None:
            layer.l2 = self.l2
        if layer.dropout is None:
            layer.dropout = self.dropout
        if layer.bias_learning_rate is None:
            layer.bias_learning_rate = self.bias_learning_rate
        return layer

    def list(self, *layers) -> "ListBuilder":
        return ListBuilder(self, list(layers))

    def graph_builder(self) -> "Any":
        try:
            from .graph_conf import GraphBuilder
        except ImportError as e:
            raise NotImplementedError(
                "ComputationGraph configuration lands with the DAG executor") from e
        return GraphBuilder(self)


class ListBuilder:
    def __init__(self, nn_conf: NeuralNetConfiguration, layers: List[Any]):
        self.nn_conf = nn_conf
        self.layers = layers
        self._input_type = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_bwd = 20
        self._pretrain = False

    def layer(self, layer_or_idx, maybe_layer=None) -> "ListBuilder":
        self.layers.append(maybe_layer if maybe_layer is not None else layer_or_idx)
        return self

    def set_input_type(self, itype) -> "ListBuilder":
        self._input_type = itype
        return self

    def backprop_type(self, bp: str) -> "ListBuilder":
        self._backprop_type = bp
        return self

    def tbptt_length(self, fwd: int, bwd: Optional[int] = None) -> "ListBuilder":
        """See GraphBuilder.tbptt_length: the fused XLA chunk step backprops
        through the whole chunk, so bwd != fwd is rejected, not ignored."""
        self._backprop_type = "tbptt"
        if bwd is not None and bwd != fwd:
            raise ValueError(
                "tbptt bwd length must equal fwd length: the fused XLA chunk "
                "step computes exact gradients over the full chunk, so "
                "bwd<fwd truncation has no cost to avoid here")
        self._tbptt_fwd = fwd
        self._tbptt_bwd = fwd
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = flag
        return self

    def build(self) -> MultiLayerConfiguration:
        nc = self.nn_conf
        itype = self._input_type
        if itype is None:
            first = self.layers[0]
            n_in = getattr(first, "n_in", None)
            if n_in:
                itype = InputTypeFeedForward(n_in)
                # record it so init()-time shape inference (e.g. BatchNorm
                # feature-count) sees the same chain build() used
                self._input_type = itype
        resolved, preprocs = [], {}
        for i, layer in enumerate(self.layers):
            layer = nc._cascade(layer)
            if itype is not None:
                pre, itype = auto_preprocessor(itype, layer.expected_input)
                if pre is not None:
                    preprocs[str(i)] = pre
                if getattr(layer, "n_in", "absent") is None:
                    layer.n_in = _infer_n_in(layer, itype)
                itype = layer.output_type(itype)
            resolved.append(layer)
        return MultiLayerConfiguration(
            layers=resolved, input_preprocessors=preprocs,
            input_type=self._input_type, seed=nc.seed, dtype=nc.dtype,
            backprop_type=self._backprop_type, tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd, pretrain=self._pretrain,
            gradient_normalization=nc.gradient_normalization,
            gradient_normalization_threshold=nc.gradient_normalization_threshold,
            updater=nc.updater,
            optimization_algorithm=nc.optimization_algorithm,
            max_num_line_search_iterations=nc.max_num_line_search_iterations,
            gradient_checkpointing=nc.gradient_checkpointing,
            compute_dtype=nc.compute_dtype)


def _infer_n_in(layer, itype):
    from ..layers.base import resolve_ff_size
    from ..inputs import InputTypeConvolutional
    if layer.expected_input == "cnn" and isinstance(itype, InputTypeConvolutional):
        return itype.channels
    return resolve_ff_size(itype)
