"""Config serialization framework.

The reference serializes every network configuration to JSON/YAML and treats the
JSON as the persistence format inside model zips (reference:
deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/MultiLayerConfiguration.java
toJson/fromJson; custom deserializers in nn/conf/serde/BaseNetConfigDeserializer.java).

Here every serializable config object is a dataclass registered in a global
registry; encoding tags each object with ``"@class"`` so round-trips reconstruct
the exact type. Version shims can be added per-class via ``_migrate``.
"""
from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Dict, Type

_REGISTRY: Dict[str, Type] = {}

# v2: SubsamplingLayer/Subsampling1DLayer gained
# avg_pool_include_pad_in_divisor and serialize it explicitly. Payloads
# without the field (v1) deserialize to the reference semantics (True) —
# the long-standing contract; the brief window where SAME avg-pool used
# TF-style exclude-pad unconditionally was a deviation (see ADVICE r3) and
# is not preserved. The Keras importer has always been the only exclude-pad
# producer and now records the field explicitly.
CONFIG_FORMAT_VERSION = 2


def register(cls):
    """Class decorator: make a dataclass JSON round-trippable."""
    _REGISTRY[cls.__name__] = cls
    return cls


def lookup(name: str):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"Unknown config class {name!r}; registered: {sorted(_REGISTRY)}")


def to_dict(obj: Any) -> Any:
    """Recursively encode a config object tree to plain JSON-able data."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return {"@enum": type(obj).__name__, "value": obj.name}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): to_dict(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj):
        d = {"@class": type(obj).__name__}
        for f in dataclasses.fields(obj):
            if not f.metadata.get("skip_serde", False):
                d[f.name] = to_dict(getattr(obj, f.name))
        return d
    raise TypeError(f"Cannot serialize {type(obj)!r}: {obj!r}")


def from_dict(data: Any) -> Any:
    """Inverse of :func:`to_dict`."""
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return [from_dict(v) for v in data]
    if isinstance(data, dict):
        if "@enum" in data:
            return lookup(data["@enum"])[data["value"]]
        if "@class" in data:
            cls = lookup(data["@class"])
            raw = {k: from_dict(v) for k, v in data.items() if k != "@class"}
            if hasattr(cls, "_migrate"):
                raw = cls._migrate(raw)
            field_names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {k: v for k, v in raw.items() if k in field_names}
            return cls(**kwargs)
        return {k: from_dict(v) for k, v in data.items()}
    raise TypeError(f"Cannot deserialize {data!r}")


def to_json(obj: Any, indent: int = 2) -> str:
    return json.dumps({"format_version": CONFIG_FORMAT_VERSION, "config": to_dict(obj)},
                      indent=indent)


def from_json(s: str) -> Any:
    data = json.loads(s)
    if isinstance(data, dict) and "format_version" in data:
        data = data["config"]
    return from_dict(data)


def register_enum(cls):
    """Decorator registering an Enum for serde."""
    _REGISTRY[cls.__name__] = cls
    return cls
