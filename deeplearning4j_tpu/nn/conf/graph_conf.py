"""ComputationGraph configuration + GraphBuilder.

Reference: nn/conf/ComputationGraphConfiguration.java (755 LoC; GraphBuilder
addInputs/addLayer/addVertex/setOutputs/setInputTypes/build), topological
validation, JSON round-trip.

The topological sort happens once at build time (the reference sorts at
network init, ComputationGraph.java:1138); the executor traces vertices in
that fixed order so XLA sees one static DAG.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import serde
from .serde import register
from ..graph.vertices import LayerVertex, VertexConf
from ..preprocessors import auto_preprocessor


@register
@dataclass
class ComputationGraphConfiguration:
    network_inputs: List[str] = field(default_factory=list)
    network_outputs: List[str] = field(default_factory=list)
    vertex_names: List[str] = field(default_factory=list)          # topo order
    vertices: Dict[str, Any] = field(default_factory=dict)         # name -> VertexConf
    vertex_inputs: Dict[str, List[str]] = field(default_factory=dict)
    input_types: Optional[List[Any]] = None
    seed: int = 12345
    dtype: str = "float32"
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    updater: Optional[Any] = None
    # reference nn/api/OptimizationAlgorithm.java:27 (see config.py)
    optimization_algorithm: str = "sgd"
    max_num_line_search_iterations: int = 5
    gradient_checkpointing: bool = False   # see MultiLayerConfiguration
    compute_dtype: Optional[str] = None    # see MultiLayerConfiguration

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return serde.from_json(s)


def topological_sort(names, inputs_of, network_inputs):
    """Kahn's algorithm over the vertex dependency graph (reference
    ComputationGraph.java:1138 topologicalSortOrder)."""
    remaining = {n: [i for i in inputs_of[n] if i not in network_inputs]
                 for n in names}
    order, ready = [], [n for n, deps in remaining.items() if not deps]
    consumers: Dict[str, List[str]] = {}
    for n in names:
        for i in remaining[n]:
            consumers.setdefault(i, []).append(n)
    ready = sorted(ready)
    while ready:
        n = ready.pop(0)
        order.append(n)
        for c in consumers.get(n, []):
            remaining[c].remove(n)
            if not remaining[c]:
                ready.append(c)
    if len(order) != len(names):
        cyc = sorted(set(names) - set(order))
        raise ValueError(f"Graph has a cycle or missing inputs involving {cyc}")
    return order


class GraphBuilder:
    """Reference ComputationGraphConfiguration.GraphBuilder."""

    def __init__(self, nn_conf):
        self.nn_conf = nn_conf
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, VertexConf] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._input_types: Optional[List[Any]] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_bwd = 20

    def backprop_type(self, bp: str) -> "GraphBuilder":
        self._backprop_type = bp
        return self

    def tbptt_length(self, fwd: int, bwd: Optional[int] = None) -> "GraphBuilder":
        """Enable truncated BPTT with the given chunk length (reference
        ComputationGraphConfiguration.GraphBuilder tBPTT settings).

        The jitted chunk step backprops through the WHOLE chunk (one fused
        XLA program), so a shorter backward truncation would only discard
        gradient terms without saving work; bwd != fwd is therefore rejected
        rather than silently ignored."""
        self._backprop_type = "tbptt"
        if bwd is not None and bwd != fwd:
            raise ValueError(
                "tbptt bwd length must equal fwd length: the fused XLA chunk "
                "step computes exact gradients over the full chunk, so "
                "bwd<fwd truncation has no cost to avoid here")
        self._tbptt_fwd = fwd
        self._tbptt_bwd = fwd
        return self

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer, *inputs: str, preprocessor=None) -> "GraphBuilder":
        layer = self.nn_conf._cascade(layer)
        self._vertices[name] = LayerVertex(layer_conf=layer, preprocessor=preprocessor)
        self._vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(self, name: str, vertex: VertexConf, *inputs: str) -> "GraphBuilder":
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *itypes) -> "GraphBuilder":
        self._input_types = list(itypes)
        return self

    def build(self) -> ComputationGraphConfiguration:
        for name, ins in self._vertex_inputs.items():
            for i in ins:
                if i not in self._inputs and i not in self._vertices:
                    raise ValueError(f"Vertex {name!r} references unknown input {i!r}")
        for o in self._outputs:
            if o not in self._vertices:
                raise ValueError(f"Unknown output vertex {o!r}")
        if not self._outputs:
            raise ValueError("setOutputs(...) required")
        order = topological_sort(list(self._vertices), self._vertex_inputs, self._inputs)

        # shape inference + nIn setting + auto preprocessor insertion
        if self._input_types is not None:
            itypes: Dict[str, Any] = dict(zip(self._inputs, self._input_types))
            for name in order:
                v = self._vertices[name]
                in_types = [itypes[i] for i in self._vertex_inputs[name]]
                # Eager validation (reference nn/conf/layers/LayerValidation.java
                # + ComputationGraphConfiguration validation): a malformed graph
                # fails at build() naming the offending vertex, instead of as an
                # opaque shape error at first trace.
                try:
                    if isinstance(v, LayerVertex):
                        if v.preprocessor is None:
                            pre, new_it = auto_preprocessor(in_types[0],
                                                            v.layer_conf.expected_input)
                            if pre is not None:
                                v.preprocessor = pre
                            in_types = [new_it] + in_types[1:]
                        else:
                            in_types = [v.preprocessor.output_type(in_types[0])] + in_types[1:]
                        if getattr(v.layer_conf, "n_in", "absent") is None:
                            from .config import _infer_n_in
                            v.layer_conf.n_in = _infer_n_in(v.layer_conf, in_types[0])
                        itypes[name] = v.layer_conf.output_type(in_types[0])
                    else:
                        itypes[name] = v.output_type(in_types)
                except ValueError as e:
                    raise ValueError(
                        f"Invalid configuration at vertex {name!r} "
                        f"(inputs {self._vertex_inputs[name]}): {e}") from e

        nc = self.nn_conf
        return ComputationGraphConfiguration(
            network_inputs=list(self._inputs), network_outputs=list(self._outputs),
            vertex_names=order, vertices=dict(self._vertices),
            vertex_inputs=dict(self._vertex_inputs),
            input_types=self._input_types, seed=nc.seed, dtype=nc.dtype,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd, tbptt_bwd_length=self._tbptt_bwd,
            gradient_normalization=nc.gradient_normalization,
            gradient_normalization_threshold=nc.gradient_normalization_threshold,
            updater=nc.updater,
            optimization_algorithm=nc.optimization_algorithm,
            max_num_line_search_iterations=nc.max_num_line_search_iterations,
            gradient_checkpointing=nc.gradient_checkpointing,
            compute_dtype=nc.compute_dtype)
