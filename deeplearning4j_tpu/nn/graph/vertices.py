"""Graph vertices: the DAG building blocks.

Reference: nn/graph/vertex/GraphVertex.java SPI + impls in
nn/graph/vertex/impl/ (LayerVertex, MergeVertex, ElementWiseVertex,
SubsetVertex, StackVertex, UnstackVertex, ScaleVertex, ShiftVertex, L2Vertex,
L2NormalizeVertex, PreprocessorVertex, rnn/{LastTimeStepVertex,
DuplicateToTimeSeriesVertex}); config mirror in nn/conf/graph/*.

Here config and impl are one dataclass (like layers): ``apply(params, state,
inputs, ...)`` over a LIST of input arrays, pure; shape inference via
``output_type(input_types)``. Everything is trace-time static, so the whole
DAG fuses into one XLA program.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, ClassVar, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..conf.serde import register
from ..inputs import (InputTypeConvolutional, InputTypeFeedForward,
                      InputTypeRecurrent)


@dataclass
class VertexConf:
    """Base vertex. ``n_params`` vertices override init/param plumbing."""

    def output_type(self, itypes: List[Any]):
        return itypes[0]

    def init(self, rng, itypes, dtype):
        return {}, {}

    def apply(self, params, state, inputs: List[Any], *, train=False, rng=None):
        raise NotImplementedError

    @property
    def layer(self):
        return None


@register
@dataclass
class LayerVertex(VertexConf):
    """Wraps a layer conf (+ optional explicit preprocessor)."""
    layer_conf: Any = None
    preprocessor: Optional[Any] = None

    @property
    def layer(self):
        return self.layer_conf

    def output_type(self, itypes):
        it = itypes[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer_conf.output_type(it)

    def init(self, rng, itypes, dtype):
        it = itypes[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer_conf.init(rng, it, dtype)

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        x = inputs[0]
        if self.preprocessor is not None:
            x = self.preprocessor.apply(x)
        kwargs = {}
        if mask is not None and getattr(self.layer_conf, "accepts_mask", False) \
                and x.ndim == 3:
            kwargs["mask"] = mask
        return self.layer_conf.apply(params, state, x, train=train, rng=rng,
                                     **kwargs)

    def apply_with_final_state(self, params, state, inputs, *, train=False,
                               rng=None, mask=None, initial_state=None):
        """Recurrent-layer passthrough for tBPTT/streaming state carry
        (reference GraphVertex wrapping a RecurrentLayer;
        ComputationGraph.rnnTimeStep :2301)."""
        x = inputs[0]
        if self.preprocessor is not None:
            x = self.preprocessor.apply(x)
        kwargs = {}
        if mask is not None and getattr(self.layer_conf, "accepts_mask", False) \
                and x.ndim == 3:
            kwargs["mask"] = mask
        return self.layer_conf.apply_with_final_state(
            params, state, x, train=train, rng=rng, initial_state=initial_state,
            **kwargs)

    @property
    def recurrent(self):
        return hasattr(self.layer_conf, "apply_with_final_state")


@register
@dataclass
class MergeVertex(VertexConf):
    """Concatenate along the feature (last) axis (reference MergeVertex —
    NCHW depth concat becomes NHWC channel concat here)."""

    def output_type(self, itypes):
        it0 = itypes[0]
        if isinstance(it0, InputTypeConvolutional):
            bad = [i for i in itypes
                   if not isinstance(i, InputTypeConvolutional)
                   or (i.height, i.width) != (it0.height, it0.width)]
            if bad:
                raise ValueError(
                    f"MergeVertex concatenates channels, so all inputs must be "
                    f"convolutional with equal spatial dims; got {itypes}")
            return InputTypeConvolutional(it0.height, it0.width,
                                          sum(i.channels for i in itypes))
        if isinstance(it0, InputTypeRecurrent):
            return InputTypeRecurrent(sum(i.size for i in itypes), it0.timestep_length)
        return InputTypeFeedForward(sum(i.size for i in itypes))

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return jnp.concatenate(inputs, axis=-1), state


@register
@dataclass
class ElementWiseVertex(VertexConf):
    """Elementwise add/subtract/product/average/max (reference ElementWiseVertex)."""
    op: str = "add"

    def output_type(self, itypes):
        # reference ElementWiseVertex.getOutputType: all inputs must agree.
        # Conv inputs must match on the FULL (h, w, c) shape; across families
        # the runtime arrays only need equal flat size (e.g. ConvolutionalFlat
        # merged with an equal-width FeedForward branch is a valid [B,N] add).
        def sig(it):
            if isinstance(it, InputTypeConvolutional):
                return ("cnn", it.height, it.width, it.channels)
            if isinstance(it, InputTypeRecurrent):
                return ("rnn", it.size)
            return ("flat", it.flat_size())
        if len({sig(i) for i in itypes}) > 1:
            raise ValueError(
                f"ElementWiseVertex({self.op}) requires same-shaped inputs; "
                f"got {itypes}")
        return itypes[0]

    def apply(self, params, state, inputs, *, train=False, rng=None):
        op = self.op.lower()
        if op == "add":
            out = sum(inputs[1:], inputs[0])
        elif op == "subtract":
            out = inputs[0] - inputs[1]
        elif op in ("product", "mult"):
            out = inputs[0]
            for v in inputs[1:]:
                out = out * v
        elif op in ("average", "avg"):
            out = sum(inputs[1:], inputs[0]) / len(inputs)
        elif op == "max":
            out = inputs[0]
            for v in inputs[1:]:
                out = jnp.maximum(out, v)
        else:
            raise ValueError(f"Unknown elementwise op {self.op!r}")
        return out, state


@register
@dataclass
class PoolHelperVertex(VertexConf):
    """Strip the first spatial row and column of a pooled activation
    (reference nn/graph/vertex/impl/PoolHelperVertex.java — compensates the
    off-by-one pooling of Caffe-trained inception models at import). NHWC
    here, so x[:, 1:, 1:, :] (the reference is NCHW x[:, :, 1:, 1:])."""

    def output_type(self, itypes):
        it = itypes[0]
        if not isinstance(it, InputTypeConvolutional):
            raise ValueError(f"PoolHelperVertex expects convolutional input, "
                             f"got {it}")
        return InputTypeConvolutional(it.height - 1, it.width - 1, it.channels)

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return inputs[0][:, 1:, 1:, :], state


@register
@dataclass
class SubsetVertex(VertexConf):
    """Feature-range slice [from, to] inclusive (reference SubsetVertex)."""
    from_idx: int = 0
    to_idx: int = 0

    def output_type(self, itypes):
        n = self.to_idx - self.from_idx + 1
        it = itypes[0]
        if isinstance(it, InputTypeRecurrent):
            return InputTypeRecurrent(n, it.timestep_length)
        return InputTypeFeedForward(n)

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return inputs[0][..., self.from_idx:self.to_idx + 1], state


@register
@dataclass
class StackVertex(VertexConf):
    """Stack along batch dim (reference StackVertex — used for sharing one
    layer across several inputs)."""

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return jnp.concatenate(inputs, axis=0), state


@register
@dataclass
class UnstackVertex(VertexConf):
    """Inverse of StackVertex: take stack slice ``from_idx`` of ``stack_size``."""
    from_idx: int = 0
    stack_size: int = 1

    def apply(self, params, state, inputs, *, train=False, rng=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_idx * step:(self.from_idx + 1) * step], state


@register
@dataclass
class ScaleVertex(VertexConf):
    scale_factor: float = 1.0

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return inputs[0] * self.scale_factor, state


@register
@dataclass
class ShiftVertex(VertexConf):
    shift_factor: float = 0.0

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return inputs[0] + self.shift_factor, state


@register
@dataclass
class L2NormalizeVertex(VertexConf):
    eps: float = 1e-8

    def apply(self, params, state, inputs, *, train=False, rng=None):
        x = inputs[0]
        norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + self.eps)
        return x / norm, state


@register
@dataclass
class L2Vertex(VertexConf):
    """Pairwise L2 distance between two inputs (reference L2Vertex)."""
    eps: float = 1e-8

    def output_type(self, itypes):
        return InputTypeFeedForward(1)

    def apply(self, params, state, inputs, *, train=False, rng=None):
        d = inputs[0] - inputs[1]
        return jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) + self.eps), state


@register
@dataclass
class PreprocessorVertex(VertexConf):
    preprocessor: Any = None

    def output_type(self, itypes):
        return self.preprocessor.output_type(itypes[0])

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return self.preprocessor.apply(inputs[0]), state


@register
@dataclass
class LastTimeStepVertex(VertexConf):
    """[B,T,F] -> [B,F] at the last unmasked step (reference
    rnn/LastTimeStepVertex). With no mask: the literal last step."""
    mask_input: Optional[str] = None

    def output_type(self, itypes):
        return InputTypeFeedForward(itypes[0].size)

    def apply(self, params, state, inputs, *, train=False, rng=None, mask=None):
        x = inputs[0]
        if mask is not None:
            idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx], state
        return x[:, -1], state


@register
@dataclass
class DuplicateToTimeSeriesVertex(VertexConf):
    """[B,F] -> [B,T,F] broadcast over time; T taken from a reference input
    (reference rnn/DuplicateToTimeSeriesVertex)."""
    reference_input: Optional[str] = None
    timestep_length: int = -1

    def output_type(self, itypes):
        return InputTypeRecurrent(itypes[0].size, self.timestep_length)

    def apply(self, params, state, inputs, *, train=False, rng=None, timesteps=None):
        x = inputs[0]
        t = timesteps if timesteps is not None else self.timestep_length
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[-1])), state
