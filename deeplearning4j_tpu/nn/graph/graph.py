"""ComputationGraph: DAG executor.

Reference: nn/graph/ComputationGraph.java (3200 LoC) — topological-order
forward (:1302,1369), reverse-order backward with epsilon accumulation
(:1570), multi-input/multi-output fit (:793-1079), evaluate (:2784).

TPU-first: forward in fixed topo order traced once; backward IS jax.grad of
the traced graph (fan-out epsilon accumulation is what reverse-mode autodiff
does by construction — the reference's hand-rolled accumulation machinery
disappears). Multi-output losses sum per the reference's
score += each output layer's computeScore.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..conf.graph_conf import ComputationGraphConfiguration
from ..layers.base import LayerConf
from ..layers.core import BaseOutputLayerMixin
from ..graph.vertices import (DuplicateToTimeSeriesVertex, LastTimeStepVertex,
                              LayerVertex)
from ...optimize.updaters import MultiLayerUpdater


def _as_list(x):
    if x is None:
        return None
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.vertex_names = list(conf.vertex_names)
        self.vertices = [conf.vertices[n] for n in self.vertex_names]
        layer_confs = [(v.layer if v.layer is not None else LayerConf())
                       for v in self.vertices]
        self.layers = tuple(layer_confs)
        self.updater = MultiLayerUpdater(
            layer_confs, conf.updater, conf.gradient_normalization,
            conf.gradient_normalization_threshold)
        self.params = None
        self.state = None
        self.opt_state = None
        self.iteration_count = 0
        self.listeners: List[Any] = []
        self._rnn_state: Optional[list] = None
        self._jit_cache: Dict[Any, Any] = {}

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None):
        rng = jax.random.PRNGKey(self.conf.seed if seed is None else seed)
        dtype = jnp.dtype(self.conf.dtype)
        itypes: Dict[str, Any] = {}
        if self.conf.input_types is not None:
            itypes.update(zip(self.conf.network_inputs, self.conf.input_types))
        params, state = [], []
        for name, v in zip(self.vertex_names, self.vertices):
            in_types = [itypes.get(i) for i in self.conf.vertex_inputs[name]]
            rng, sub = jax.random.split(rng)
            p, s = v.init(sub, in_types, dtype)
            params.append(p)
            state.append(s)
            if all(t is not None for t in in_types):
                # Eager per-vertex shape validation (reference
                # nn/conf/layers/LayerValidation.java): a config whose shapes
                # don't line up must fail HERE naming the vertex, not as an
                # opaque trace-time error inside the first jitted step.
                try:
                    itypes[name] = v.output_type(in_types)
                except Exception as e:
                    raise ValueError(
                        f"Shape inference failed at vertex {name!r} "
                        f"(inputs {self.conf.vertex_inputs[name]} -> "
                        f"{in_types}): {e}") from e
            else:
                itypes[name] = None
        self.params = tuple(params)
        self.state = tuple(state)
        self.opt_state = self.updater.init(self.params)
        return self

    # ------------------------------------------------------------- functional
    def apply_fn(self, params, state, inputs, *, train=False, rng=None,
                 features_masks=None, rnn_states=None,
                 collect_rnn_states: bool = False):
        """Forward in topo order. Returns (activations: dict name->array,
        new_state tuple) — or (acts, new_state, rnn_states_out) when
        ``collect_rnn_states`` (the tBPTT/streaming carry; reference
        ComputationGraph.rnnTimeStep :2301, tBPTT state sync :908).

        Per-timestep feature masks propagate vertex-to-vertex: a vertex's mask
        is its first input's mask, dropped once the time dimension collapses
        (reference MaskState flow through GraphVertex.setMaskArrays).
        """
        inputs = _as_list(inputs)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        # mixed precision (see MultiLayerNetwork.apply_fn): master params stay
        # conf.dtype; compute runs in compute_dtype
        cd = getattr(self.conf, "compute_dtype", None)
        if cd:
            # state deliberately NOT cast — see MultiLayerNetwork.apply_fn
            from ..multilayer import cast_floats
            params = cast_floats(params, cd)
            inputs = cast_floats(inputs, cd)
            if rnn_states is not None:
                rnn_states = cast_floats(rnn_states, cd)
        acts: Dict[str, Any] = dict(zip(self.conf.network_inputs, inputs))
        masks: Dict[str, Any] = {}
        if features_masks is not None:
            masks.update({k: m for k, m in zip(self.conf.network_inputs,
                                               _as_list(features_masks)) if m is not None})
        new_state = []
        rnn_out = [None] * len(self.vertices)
        for idx, (name, v) in enumerate(zip(self.vertex_names, self.vertices)):
            in_names = self.conf.vertex_inputs[name]
            vin = [acts[i] for i in in_names]
            in_mask = next((masks[i] for i in in_names if i in masks), None)
            rng, sub = jax.random.split(rng)
            if isinstance(v, LastTimeStepVertex):
                mask = masks.get(v.mask_input) if v.mask_input else in_mask
                if mask is not None and getattr(vin[0], "ndim", 0) == 3 and \
                        mask.shape[1] != vin[0].shape[1]:
                    mask = None   # sequence length changed upstream
                out, s = v.apply(params[idx], state[idx], vin, train=train,
                                 rng=sub, mask=mask)
            elif isinstance(v, DuplicateToTimeSeriesVertex):
                t = None
                if v.reference_input is not None:
                    t = acts[v.reference_input].shape[1]
                out, s = v.apply(params[idx], state[idx], vin, train=train,
                                 rng=sub, timesteps=t)
            elif isinstance(v, LayerVertex) and v.recurrent and \
                    (collect_rnn_states or (rnn_states is not None
                                            and rnn_states[idx] is not None)):
                init = rnn_states[idx] if rnn_states is not None else None
                out, final = v.apply_with_final_state(
                    params[idx], state[idx], vin, train=train, rng=sub,
                    mask=in_mask, initial_state=init)
                s = state[idx]
                rnn_out[idx] = final
            elif isinstance(v, LayerVertex) and \
                    getattr(self.conf, "gradient_checkpointing", False):
                fn = jax.checkpoint(
                    lambda p, s_, xx, key, _v=v, _m=in_mask:
                    _v.apply(p, s_, xx, train=train, rng=key, mask=_m))
                out, s = fn(params[idx], state[idx], vin, sub)
            elif isinstance(v, LayerVertex):
                out, s = v.apply(params[idx], state[idx], vin, train=train,
                                 rng=sub, mask=in_mask)
            else:
                out, s = v.apply(params[idx], state[idx], vin, train=train, rng=sub)
            acts[name] = out
            new_state.append(s)
            # propagate only while the time axis is unchanged — a vertex that
            # alters sequence length (e.g. strided Convolution1D) invalidates
            # the [B,T] mask for its consumers
            if in_mask is not None and getattr(out, "ndim", 0) == 3 and \
                    out.shape[1] == in_mask.shape[1]:
                masks[name] = in_mask
        if cd:
            from ..multilayer import cast_floats
            new_state = cast_floats(new_state, self.conf.dtype)
            rnn_out = cast_floats(rnn_out, self.conf.dtype)
            acts = cast_floats(acts, self.conf.dtype)
        if collect_rnn_states:
            return acts, tuple(new_state), rnn_out
        return acts, tuple(new_state)

    def loss_fn(self, params, state, x, labels, *, train=True, rng=None,
                labels_mask=None, features_mask=None, rnn_states=None,
                collect_rnn_states: bool = False):
        """Sum of output-layer losses + regularization (reference
        ComputationGraph.computeGradientAndScore :1245). With
        ``collect_rnn_states`` the aux also carries each recurrent vertex's
        final state — the tBPTT chunk carry (reference tBPTT branch :908)."""
        inputs = _as_list(x)
        labels = _as_list(labels)
        lmasks = _as_list(labels_mask) or [None] * len(labels)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        rng, fwd = jax.random.split(rng)
        rnn_out = None
        res = self.apply_fn(params, state, inputs, train=train,
                            rng=fwd, features_masks=features_mask,
                            rnn_states=rnn_states,
                            collect_rnn_states=collect_rnn_states)
        if collect_rnn_states:
            acts, new_state, rnn_out = res
        else:
            acts, new_state = res
        cd = getattr(self.conf, "compute_dtype", None)
        if cd:
            from ..multilayer import cast_floats
        total = 0.0
        for k, out_name in enumerate(self.conf.network_outputs):
            vi = self.vertex_names.index(out_name)
            v = self.vertices[vi]
            if not (isinstance(v, LayerVertex)
                    and isinstance(v.layer_conf, BaseOutputLayerMixin)):
                # The reference allows any vertex as a network output
                # (ComputationGraph.java: outputs need not be IOutputLayer);
                # only SCORING against labels requires a loss-bearing layer.
                if k < len(labels) and labels[k] is not None:
                    raise ValueError(
                        f"Network output {out_name!r} is not an output layer; "
                        f"it can be predicted via output() but not scored "
                        f"against labels")
                continue
            feed_name = self.conf.vertex_inputs[out_name][0]
            feed = (acts[feed_name] if feed_name not in self.conf.network_inputs
                    else inputs[self.conf.network_inputs.index(feed_name)])
            if v.preprocessor is not None:
                feed = v.preprocessor.apply(feed)
            rng, sub = jax.random.split(rng)
            head_params = params[vi]
            if cd:
                head_params = cast_floats(head_params, cd)
                feed = cast_floats(feed, cd)
            per_ex = v.layer_conf.compute_loss_per_example(
                head_params, feed, labels[k], lmasks[k], train=train, rng=sub)
            if cd:
                per_ex = per_ex.astype(jnp.dtype(self.conf.dtype))
            lm = lmasks[k]
            if lm is not None and per_ex.ndim == 1 and lm.ndim >= 2:
                total = total + jnp.sum(per_ex) / jnp.maximum(jnp.sum(lm), 1.0)
            else:
                total = total + jnp.mean(per_ex)
        for layer, p in zip(self.layers, params):
            total = total + layer.regularization(p)
        if collect_rnn_states:
            return total, (new_state, rnn_out)
        return total, new_state

    # ------------------------------------------------------------- inference
    def _jitted(self, key, fn):
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(fn)
        return self._jit_cache[key]

    def output(self, *inputs, train: bool = False):
        inputs = [jnp.asarray(i) for i in inputs]
        fn = self._jitted(("output", train, len(inputs)),
                          functools.partial(self._output_pure, train=train))
        outs = fn(self.params, self.state, inputs)
        return outs[0] if len(outs) == 1 else outs

    def _output_pure(self, params, state, inputs, *, train=False):
        acts, _ = self.apply_fn(params, state, inputs, train=train)
        return [acts[o] for o in self.conf.network_outputs]

    def feed_forward(self, *inputs, train: bool = False):
        acts, _ = self.apply_fn(self.params, self.state,
                                [jnp.asarray(i) for i in inputs], train=train)
        return acts

    def score(self, x=None, y=None, dataset=None) -> float:
        if dataset is not None:
            x, y = dataset.features, dataset.labels
        fn = self._jitted(("score",),
                          lambda p, s, xx, yy: self.loss_fn(p, s, xx, yy,
                                                            train=False)[0])
        x = [jnp.asarray(v) for v in _as_list(x)]
        y = [jnp.asarray(v) for v in _as_list(y)]
        return float(fn(self.params, self.state, x, y))

    # -------------------------------------------------------------- streaming
    def rnn_time_step(self, *inputs):
        """Stateful streaming inference (reference
        ComputationGraph.rnnTimeStep :2301): feed [B,F] one step (or [B,T,F]
        a chunk) per network input; recurrent vertex state is carried between
        calls. Returns the network output(s) for the fed step(s)."""
        dtype = jnp.dtype(self.conf.dtype)
        xs = [jnp.asarray(i, dtype) for i in inputs]
        single = all(x.ndim == 2 for x in xs)
        if single:
            xs = [x[:, None, :] for x in xs]

        def fn(params, state, rnn_states, xx):
            acts, _, rnn_out = self.apply_fn(params, state, xx, train=False,
                                             rnn_states=rnn_states,
                                             collect_rnn_states=True)
            return [acts[o] for o in self.conf.network_outputs], rnn_out

        key = ("rnn_time_step", tuple(x.shape[1] for x in xs),
               self._rnn_state is None)
        jfn = self._jitted(key, fn)
        outs, self._rnn_state = jfn(self.params, self.state, self._rnn_state, xs)
        if single:
            outs = [o[:, -1] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self):
        self._rnn_state = None

    # ------------------------------------------------------------ flat params
    def params_flat(self) -> jnp.ndarray:
        leaves = []
        for v, p in zip(self.vertices, self.params):
            layer = v.layer
            order = layer.param_order if layer is not None else sorted(p)
            for name in order:
                if name in p:
                    leaves.append(jnp.ravel(p[name]))
        if not leaves:
            return jnp.zeros((0,), jnp.dtype(self.conf.dtype))
        return jnp.concatenate(leaves)

    def set_params_flat(self, flat):
        flat = jnp.asarray(flat)
        expected = self.num_params()
        if flat.shape != (expected,):
            raise ValueError(f"Expected flat parameter vector of length {expected}, "
                             f"got shape {flat.shape}")
        new_params, off = [], 0
        for v, p in zip(self.vertices, self.params):
            layer = v.layer
            order = layer.param_order if layer is not None else sorted(p)
            np_ = dict(p)
            for name in order:
                if name in p:
                    n = int(np.prod(p[name].shape)) if p[name].ndim else 1
                    np_[name] = flat[off:off + n].reshape(p[name].shape).astype(p[name].dtype)
                    off += n
            new_params.append(np_)
        self.params = tuple(new_params)

    def num_params(self) -> int:
        return int(sum(int(np.prod(v.shape)) for p in self.params for v in p.values()))

    # ------------------------------------------------------------------ train
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def _solver(self):
        if not hasattr(self, "_solver_inst"):
            from ...optimize.solver import Solver
            self._solver_inst = Solver(self)
        return self._solver_inst

    def fit(self, data=None, labels=None, *, epochs: int = 1,
            batch_size: Optional[int] = None, iterator=None, dataset=None,
            async_prefetch: bool = True, prefetch_depth: int = 2,
            steps_per_dispatch: int = 1, skip_first_batches: int = 0):
        """``async_prefetch``/``prefetch_depth``: iterator feeds (incl.
        MultiDataSet multi-input batches) run through a
        DevicePrefetchIterator — see MultiLayerNetwork.fit.

        ``steps_per_dispatch=K`` fuses K-step windows into one lax.scan
        program (see MultiLayerNetwork.fit); multi-input MultiDataSet
        batches are not stackable and run per-step.

        ``skip_first_batches=S``: mid-epoch resume — see
        MultiLayerNetwork.fit."""
        self._solver().fit(data=data, labels=labels, epochs=epochs,
                           batch_size=batch_size, iterator=iterator,
                           dataset=dataset, async_prefetch=async_prefetch,
                           prefetch_depth=prefetch_depth,
                           steps_per_dispatch=steps_per_dispatch,
                           skip_first_batches=skip_first_batches)
        return self

    def pretrain(self, iterator, epochs: int = 1):
        """Layerwise unsupervised pretraining of pretrainable layer
        vertices (reference ComputationGraph.pretrain)."""
        self._solver().pretrain(iterator, epochs=epochs)
        return self

    # ------------------------------------------------------------------ eval
    def evaluate(self, iterator_or_x, y=None):
        """Per-output classification evaluation. Single-output graphs return
        ONE Evaluation (reference ComputationGraph.evaluate :2784);
        multi-output graphs return a list of Evaluations, one per network
        output in declaration order."""
        from ...eval.evaluation import Evaluation
        n_out = len(self.conf.network_outputs)
        evals = [Evaluation() for _ in range(n_out)]

        def eval_batch(features, labels, lmask, metadata=None):
            outs = self.output(*_as_list(features))
            outs = outs if isinstance(outs, list) else [outs]
            labels_l = _as_list(labels)
            if len(labels_l) != n_out:
                raise ValueError(
                    f"evaluate() got {len(labels_l)} label array(s) for a "
                    f"{n_out}-output graph ({self.conf.network_outputs}); "
                    f"pass one per output (None to skip an output)")
            masks_l = _as_list(lmask) if lmask is not None else [None] * n_out
            if len(masks_l) != n_out:
                raise ValueError(
                    f"evaluate() got {len(masks_l)} label mask(s) for a "
                    f"{n_out}-output graph; pass one per output (None for "
                    f"unmasked outputs)")
            for e, o, l, m in zip(evals, outs, labels_l, masks_l):
                if l is not None:
                    # per-example metadata only applies to 2D outputs; a
                    # time-series output evaluates without records
                    md = metadata if np.asarray(l).ndim != 3 else None
                    e.eval(l, np.asarray(o), mask=m, record_meta_data=md)

        if y is not None:
            eval_batch(iterator_or_x, y, None)
        else:
            for ds in iterator_or_x:
                eval_batch(ds.features, ds.labels, ds.labels_mask,
                           metadata=getattr(ds, "metadata", None))
        return evals[0] if n_out == 1 else evals

    def clone(self) -> "ComputationGraph":
        import copy
        other = ComputationGraph(copy.deepcopy(self.conf))
        if self.params is not None:
            # REAL copies: the trained clone's jitted steps donate their
            # buffers; sharing arrays would invalidate the source network
            copy = lambda a: jnp.array(a, copy=True) if a is not None else None
            other.params = jax.tree.map(copy, self.params)
            other.state = jax.tree.map(copy, self.state)
            other.opt_state = jax.tree.map(copy, self.opt_state)
        return other
