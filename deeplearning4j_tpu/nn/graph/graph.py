"""ComputationGraph: DAG executor.

Reference: nn/graph/ComputationGraph.java (3200 LoC) — topological-order
forward (:1302,1369), reverse-order backward with epsilon accumulation
(:1570), multi-input/multi-output fit (:793-1079), evaluate (:2784).

TPU-first: forward in fixed topo order traced once; backward IS jax.grad of
the traced graph (fan-out epsilon accumulation is what reverse-mode autodiff
does by construction — the reference's hand-rolled accumulation machinery
disappears). Multi-output losses sum per the reference's
score += each output layer's computeScore.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..conf.graph_conf import ComputationGraphConfiguration
from ..layers.base import LayerConf
from ..layers.core import BaseOutputLayerMixin
from ..graph.vertices import (DuplicateToTimeSeriesVertex, LastTimeStepVertex,
                              LayerVertex)
from ...optimize.updaters import MultiLayerUpdater


def _as_list(x):
    if x is None:
        return None
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.vertex_names = list(conf.vertex_names)
        self.vertices = [conf.vertices[n] for n in self.vertex_names]
        layer_confs = [(v.layer if v.layer is not None else LayerConf())
                       for v in self.vertices]
        self.layers = tuple(layer_confs)
        self.updater = MultiLayerUpdater(
            layer_confs, conf.updater, conf.gradient_normalization,
            conf.gradient_normalization_threshold)
        self.params = None
        self.state = None
        self.opt_state = None
        self.iteration_count = 0
        self.listeners: List[Any] = []
        self._jit_cache: Dict[Any, Any] = {}

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None):
        rng = jax.random.PRNGKey(self.conf.seed if seed is None else seed)
        dtype = jnp.dtype(self.conf.dtype)
        itypes: Dict[str, Any] = {}
        if self.conf.input_types is not None:
            itypes.update(zip(self.conf.network_inputs, self.conf.input_types))
        params, state = [], []
        for name, v in zip(self.vertex_names, self.vertices):
            in_types = [itypes.get(i) for i in self.conf.vertex_inputs[name]]
            rng, sub = jax.random.split(rng)
            p, s = v.init(sub, in_types, dtype)
            params.append(p)
            state.append(s)
            try:
                itypes[name] = (v.output_type(in_types)
                                if all(t is not None for t in in_types) else None)
            except Exception:
                itypes[name] = None
        self.params = tuple(params)
        self.state = tuple(state)
        self.opt_state = self.updater.init(self.params)
        return self

    # ------------------------------------------------------------- functional
    def apply_fn(self, params, state, inputs, *, train=False, rng=None,
                 features_masks=None):
        """Forward in topo order. Returns (activations: dict name->array,
        new_state tuple)."""
        inputs = _as_list(inputs)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        acts: Dict[str, Any] = dict(zip(self.conf.network_inputs, inputs))
        masks: Dict[str, Any] = {}
        if features_masks is not None:
            masks.update({k: m for k, m in zip(self.conf.network_inputs,
                                               _as_list(features_masks)) if m is not None})
        new_state = []
        for idx, (name, v) in enumerate(zip(self.vertex_names, self.vertices)):
            vin = [acts[i] for i in self.conf.vertex_inputs[name]]
            rng, sub = jax.random.split(rng)
            if isinstance(v, LastTimeStepVertex):
                mask = masks.get(v.mask_input) if v.mask_input else None
                out, s = v.apply(params[idx], state[idx], vin, train=train,
                                 rng=sub, mask=mask)
            elif isinstance(v, DuplicateToTimeSeriesVertex):
                t = None
                if v.reference_input is not None:
                    t = acts[v.reference_input].shape[1]
                out, s = v.apply(params[idx], state[idx], vin, train=train,
                                 rng=sub, timesteps=t)
            else:
                out, s = v.apply(params[idx], state[idx], vin, train=train, rng=sub)
            acts[name] = out
            new_state.append(s)
        return acts, tuple(new_state)

    def loss_fn(self, params, state, x, labels, *, train=True, rng=None,
                labels_mask=None, features_mask=None):
        """Sum of output-layer losses + regularization (reference
        ComputationGraph.computeGradientAndScore :1245)."""
        inputs = _as_list(x)
        labels = _as_list(labels)
        lmasks = _as_list(labels_mask) or [None] * len(labels)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        rng, fwd = jax.random.split(rng)
        acts, new_state = self.apply_fn(params, state, inputs, train=train,
                                        rng=fwd, features_masks=features_mask)
        total = 0.0
        for k, out_name in enumerate(self.conf.network_outputs):
            vi = self.vertex_names.index(out_name)
            v = self.vertices[vi]
            if not (isinstance(v, LayerVertex)
                    and isinstance(v.layer_conf, BaseOutputLayerMixin)):
                # The reference allows any vertex as a network output
                # (ComputationGraph.java: outputs need not be IOutputLayer);
                # only SCORING against labels requires a loss-bearing layer.
                if k < len(labels) and labels[k] is not None:
                    raise ValueError(
                        f"Network output {out_name!r} is not an output layer; "
                        f"it can be predicted via output() but not scored "
                        f"against labels")
                continue
            feed_name = self.conf.vertex_inputs[out_name][0]
            feed = (acts[feed_name] if feed_name not in self.conf.network_inputs
                    else inputs[self.conf.network_inputs.index(feed_name)])
            if v.preprocessor is not None:
                feed = v.preprocessor.apply(feed)
            rng, sub = jax.random.split(rng)
            per_ex = v.layer_conf.compute_loss_per_example(
                params[vi], feed, labels[k], lmasks[k], train=train, rng=sub)
            lm = lmasks[k]
            if lm is not None and per_ex.ndim == 1 and lm.ndim >= 2:
                total = total + jnp.sum(per_ex) / jnp.maximum(jnp.sum(lm), 1.0)
            else:
                total = total + jnp.mean(per_ex)
        for layer, p in zip(self.layers, params):
            total = total + layer.regularization(p)
        return total, new_state

    # ------------------------------------------------------------- inference
    def _jitted(self, key, fn):
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(fn)
        return self._jit_cache[key]

    def output(self, *inputs, train: bool = False):
        inputs = [jnp.asarray(i) for i in inputs]
        fn = self._jitted(("output", train, len(inputs)),
                          functools.partial(self._output_pure, train=train))
        outs = fn(self.params, self.state, inputs)
        return outs[0] if len(outs) == 1 else outs

    def _output_pure(self, params, state, inputs, *, train=False):
        acts, _ = self.apply_fn(params, state, inputs, train=train)
        return [acts[o] for o in self.conf.network_outputs]

    def feed_forward(self, *inputs, train: bool = False):
        acts, _ = self.apply_fn(self.params, self.state,
                                [jnp.asarray(i) for i in inputs], train=train)
        return acts

    def score(self, x=None, y=None, dataset=None) -> float:
        if dataset is not None:
            x, y = dataset.features, dataset.labels
        fn = self._jitted(("score",),
                          lambda p, s, xx, yy: self.loss_fn(p, s, xx, yy,
                                                            train=False)[0])
        x = [jnp.asarray(v) for v in _as_list(x)]
        y = [jnp.asarray(v) for v in _as_list(y)]
        return float(fn(self.params, self.state, x, y))

    # ------------------------------------------------------------ flat params
    def params_flat(self) -> jnp.ndarray:
        leaves = []
        for v, p in zip(self.vertices, self.params):
            layer = v.layer
            order = layer.param_order if layer is not None else sorted(p)
            for name in order:
                if name in p:
                    leaves.append(jnp.ravel(p[name]))
        if not leaves:
            return jnp.zeros((0,), jnp.dtype(self.conf.dtype))
        return jnp.concatenate(leaves)

    def set_params_flat(self, flat):
        flat = jnp.asarray(flat)
        expected = self.num_params()
        if flat.shape != (expected,):
            raise ValueError(f"Expected flat parameter vector of length {expected}, "
                             f"got shape {flat.shape}")
        new_params, off = [], 0
        for v, p in zip(self.vertices, self.params):
            layer = v.layer
            order = layer.param_order if layer is not None else sorted(p)
            np_ = dict(p)
            for name in order:
                if name in p:
                    n = int(np.prod(p[name].shape)) if p[name].ndim else 1
                    np_[name] = flat[off:off + n].reshape(p[name].shape).astype(p[name].dtype)
                    off += n
            new_params.append(np_)
        self.params = tuple(new_params)

    def num_params(self) -> int:
        return int(sum(int(np.prod(v.shape)) for p in self.params for v in p.values()))

    # ------------------------------------------------------------------ train
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def _solver(self):
        if not hasattr(self, "_solver_inst"):
            from ...optimize.solver import Solver
            self._solver_inst = Solver(self)
        return self._solver_inst

    def fit(self, data=None, labels=None, *, epochs: int = 1,
            batch_size: Optional[int] = None, iterator=None, dataset=None):
        self._solver().fit(data=data, labels=labels, epochs=epochs,
                           batch_size=batch_size, iterator=iterator, dataset=dataset)
        return self

    # ------------------------------------------------------------------ eval
    def evaluate(self, iterator_or_x, y=None):
        from ...eval.evaluation import Evaluation
        e = Evaluation()
        if y is not None:
            e.eval(y, np.asarray(self.output(iterator_or_x)))
            return e
        for ds in iterator_or_x:
            out = self.output(*_as_list(ds.features))
            e.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
        return e

    def clone(self) -> "ComputationGraph":
        import copy
        other = ComputationGraph(copy.deepcopy(self.conf))
        if self.params is not None:
            other.params = jax.tree.map(lambda a: a, self.params)
            other.state = jax.tree.map(lambda a: a, self.state)
            other.opt_state = jax.tree.map(lambda a: a, self.opt_state)
        return other
