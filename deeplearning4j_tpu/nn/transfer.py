"""Transfer learning: clone-and-edit trained networks.

Reference: nn/transferlearning/TransferLearning.java:35-37 (builder: freeze up
to a boundary via setFeatureExtractor, nOutReplace, removeOutputLayer,
addLayer, fineTuneConfiguration), FineTuneConfiguration (global hyperparam
overrides), TransferLearningHelper (featurization: cache frozen-part
activations and train only the unfrozen head).

Freezing = the layer conf's ``frozen`` flag; the updater skips frozen layers
(XLA dead-code-eliminates their backward graph, so frozen layers cost nothing
at train time — the TPU equivalent of the reference's FrozenLayer wrapper).
"""
from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .conf.config import MultiLayerConfiguration
from .multilayer import MultiLayerNetwork


@dataclass
class FineTuneConfiguration:
    """Hyperparameter overrides applied to every (non-frozen) layer
    (reference nn/transferlearning/FineTuneConfiguration.java)."""
    updater: Optional[Any] = None
    learning_rate: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    activation: Optional[str] = None
    seed: Optional[int] = None

    def apply_to_layer(self, layer_conf):
        """Per-layer half of the override (shared by the MLN and CG
        builders)."""
        for f in ("learning_rate", "l1", "l2", "dropout", "activation"):
            v = getattr(self, f)
            if v is not None:
                setattr(layer_conf, f, v)

    def apply_to(self, conf: MultiLayerConfiguration):
        if self.updater is not None:
            conf.updater = self.updater
        if self.seed is not None:
            conf.seed = self.seed
        # skip frozen layers, matching TransferLearningGraph.build — frozen
        # pretrained weights keep their original regularization/dropout
        for layer in conf.layers:
            if not getattr(layer, "frozen", False):
                self.apply_to_layer(layer)


class TransferLearning:
    """Builder over a trained MultiLayerNetwork (reference
    TransferLearning.Builder)."""

    def __init__(self, net: MultiLayerNetwork):
        self._net = net
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[int] = None
        self._n_out_replace: Dict[int, tuple] = {}
        self._remove_from_output: int = 0
        self._added_layers: List[Any] = []

    def fine_tune_configuration(self, ftc: FineTuneConfiguration) -> "TransferLearning":
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, layer_idx: int) -> "TransferLearning":
        """Freeze layers [0..layer_idx] inclusive."""
        self._freeze_until = layer_idx
        return self

    def n_out_replace(self, layer_idx: int, n_out: int,
                      weight_init: str = "xavier") -> "TransferLearning":
        """Replace layer's output width with fresh weights; the next layer's
        inputs are re-initialized to match (reference nOutReplace)."""
        self._n_out_replace[layer_idx] = (n_out, weight_init)
        return self

    def remove_output_layer(self) -> "TransferLearning":
        self._remove_from_output = max(self._remove_from_output, 1)
        return self

    def remove_layers_from_output(self, n: int) -> "TransferLearning":
        self._remove_from_output = max(self._remove_from_output, n)
        return self

    def add_layer(self, layer) -> "TransferLearning":
        self._added_layers.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        src = self._net
        conf = copy.deepcopy(src.conf)
        params: List[Dict[str, Any]] = [dict(p) for p in src.params]
        state: List[Dict[str, Any]] = [dict(s) for s in src.state]
        keep = len(conf.layers) - self._remove_from_output
        conf.layers = conf.layers[:keep]
        params, state = params[:keep], state[:keep]
        conf.input_preprocessors = {k: v for k, v in conf.input_preprocessors.items()
                                    if int(k) < keep}

        # nOutReplace: re-init layer and the following layer's fan-in
        reinit = set()
        for idx, (n_out, wi) in self._n_out_replace.items():
            conf.layers[idx] = dataclasses.replace(conf.layers[idx], n_out=n_out,
                                                   weight_init=wi)
            reinit.add(idx)
            if idx + 1 < len(conf.layers) and hasattr(conf.layers[idx + 1], "n_in"):
                conf.layers[idx + 1] = dataclasses.replace(conf.layers[idx + 1],
                                                           n_in=n_out)
                reinit.add(idx + 1)

        # appended layers: infer n_in from the current tail
        from .layers.base import resolve_ff_size
        from .inputs import InputTypeFeedForward
        itype = conf.input_type
        if itype is None and conf.layers:
            n_in0 = getattr(conf.layers[0], "n_in", None)
            if n_in0:
                itype = InputTypeFeedForward(n_in0)
        if itype is not None:
            for i, l in enumerate(conf.layers):
                pre = conf.preprocessor(i)
                if pre is not None:
                    itype = pre.output_type(itype)
                itype = l.output_type(itype)
        for layer in self._added_layers:
            layer = copy.deepcopy(layer)
            if getattr(layer, "n_in", "absent") is None and itype is not None:
                layer.n_in = resolve_ff_size(itype)
            conf.layers.append(layer)
            reinit.add(len(conf.layers) - 1)
            if itype is not None:
                itype = layer.output_type(itype)

        if self._freeze_until is not None:
            for i in range(min(self._freeze_until + 1, len(conf.layers))):
                conf.layers[i] = dataclasses.replace(conf.layers[i], frozen=True)
        if self._fine_tune is not None:
            self._fine_tune.apply_to(conf)

        new_net = MultiLayerNetwork(conf).init()
        # carry over surviving parameters; re-initialized layers keep fresh init
        final_params = list(new_net.params)
        final_state = list(new_net.state)
        for i in range(min(len(params), len(conf.layers))):
            if i not in reinit:
                final_params[i] = params[i]
                if i < len(state):
                    final_state[i] = state[i]
        new_net.params = tuple(final_params)
        new_net.state = tuple(final_state)
        new_net.opt_state = new_net.updater.init(new_net.params)
        return new_net


class TransferLearningHelper:
    """Featurization: run inputs through the frozen front once, train only the
    unfrozen tail (reference TransferLearningHelper)."""

    def __init__(self, net: MultiLayerNetwork, frozen_until: Optional[int] = None):
        self.net = net
        if frozen_until is None:
            frozen = [i for i, l in enumerate(net.layers) if getattr(l, "frozen", False)]
            frozen_until = max(frozen) if frozen else -1
        self.frozen_until = frozen_until
        self._featurize_fn = jax.jit(
            lambda params, state, x: net.apply_fn(params, state, x, train=False,
                                                  to_layer=self.frozen_until)[0][-1])

    def featurize(self, features):
        """Map raw inputs to the frozen boundary's activations."""
        if self.frozen_until < 0:
            return jnp.asarray(features)
        return self._featurize_fn(self.net.params, self.net.state,
                                  jnp.asarray(features))

    def unfrozen_network(self) -> MultiLayerNetwork:
        """A standalone net of the unfrozen tail sharing parameter values."""
        conf = copy.deepcopy(self.net.conf)
        cut = self.frozen_until + 1
        conf.layers = conf.layers[cut:]
        conf.input_preprocessors = {str(int(k) - cut): v
                                    for k, v in conf.input_preprocessors.items()
                                    if int(k) >= cut}
        conf.input_type = None
        tail = MultiLayerNetwork(conf)
        tail.params = tuple(self.net.params[cut:])
        tail.state = tuple(self.net.state[cut:])
        tail.opt_state = tail.updater.init(tail.params)
        return tail


class TransferLearningGraph:
    """Transfer learning over a trained ComputationGraph (reference
    TransferLearning.GraphBuilder inner class, TransferLearning.java:
    setFeatureExtractor freezes a vertex and everything upstream of it,
    nOutReplace re-initializes a layer vertex and the direct LayerVertex
    consumers whose fan-in changes, fineTuneConfiguration overrides
    hyperparameters). Surviving vertices keep their trained parameters;
    re-initialized ones get fresh init; the updater state restarts.
    """

    def __init__(self, net):
        self._net = net
        self._freeze_at: Optional[str] = None
        self._replace: Dict[str, Any] = {}
        self._fine_tune: Optional[FineTuneConfiguration] = None

    def fine_tune_configuration(self, ftc: FineTuneConfiguration
                                ) -> "TransferLearningGraph":
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, vertex_name: str) -> "TransferLearningGraph":
        """Freeze ``vertex_name`` and all its ancestors (reference
        setFeatureExtractor: everything up to and including the named vertex
        stops updating)."""
        self._freeze_at = vertex_name
        return self

    def n_out_replace(self, vertex_name: str, n_out: int,
                      weight_init: Optional[str] = None) -> "TransferLearningGraph":
        self._replace[vertex_name] = (n_out, weight_init)
        return self

    def _ancestors(self, conf, name) -> set:
        seen = set()
        stack = [name]
        while stack:
            cur = stack.pop()
            if cur in seen or cur in conf.network_inputs:
                continue
            seen.add(cur)
            stack.extend(conf.vertex_inputs.get(cur, []))
        return seen

    def build(self):
        from .graph.graph import ComputationGraph
        src = self._net
        conf = copy.deepcopy(src.conf)
        reinit = set()

        for name, (n_out, wi) in self._replace.items():
            v = conf.vertices[name]
            if v.layer is None:
                raise ValueError(f"{name!r} is not a layer vertex")
            v.layer_conf = dataclasses.replace(
                v.layer_conf, n_out=n_out,
                weight_init=wi or v.layer_conf.weight_init)
            reinit.add(name)
            # direct LayerVertex consumers: their fan-in changed. Consumers
            # reached THROUGH a pass-through vertex (Merge etc.) would keep a
            # stale n_in and fail deep inside XLA later — reject loudly.
            for cname, ins in conf.vertex_inputs.items():
                if name in ins:
                    cv = conf.vertices[cname]
                    if cv.layer is not None and hasattr(cv.layer_conf, "n_in"):
                        cv.layer_conf = dataclasses.replace(cv.layer_conf,
                                                            n_in=n_out)
                        reinit.add(cname)
                    else:
                        # Merge/ElementWise vertices, and width-dependent
                        # layers without an n_in field (BatchNorm etc.),
                        # would carry stale-width params into XLA — reject
                        # loudly at build time
                        raise ValueError(
                            f"n_out_replace({name!r}): consumer {cname!r} "
                            f"cannot have its fan-in adjusted automatically "
                            f"(only layers with an n_in field are supported) "
                            f"— restructure or replace that consumer "
                            f"explicitly")

        if self._freeze_at is not None:
            if self._freeze_at not in conf.vertices:
                raise ValueError(f"Unknown vertex {self._freeze_at!r}")
            for name in self._ancestors(conf, self._freeze_at):
                v = conf.vertices.get(name)
                if v is not None and v.layer is not None:
                    v.layer_conf = dataclasses.replace(v.layer_conf, frozen=True)

        if self._fine_tune is not None:
            ft = self._fine_tune
            if ft.updater is not None:
                conf.updater = ft.updater
            if ft.seed is not None:
                conf.seed = ft.seed
            for v in conf.vertices.values():
                if v.layer is not None and not getattr(v.layer_conf, "frozen", False):
                    ft.apply_to_layer(v.layer_conf)

        new_net = ComputationGraph(conf).init()
        final_params = list(new_net.params)
        final_state = list(new_net.state)
        # REAL copies (not shared buffers): both nets' jitted train steps
        # donate their inputs, so sharing would let training one net delete
        # the other's arrays (same reason ComputationGraph.clone copies)
        _copy = lambda a: jnp.array(a, copy=True)
        for i, name in enumerate(new_net.vertex_names):
            if name not in reinit and i < len(src.params):
                src_idx = src.vertex_names.index(name)
                final_params[i] = jax.tree.map(_copy, src.params[src_idx])
                final_state[i] = jax.tree.map(_copy, src.state[src_idx])
        new_net.params = tuple(final_params)
        new_net.state = tuple(final_state)
        new_net.opt_state = new_net.updater.init(new_net.params)
        return new_net
