"""EarlyStoppingParallelTrainer: early stopping over data-parallel fitting.

Reference: parallelism/EarlyStoppingParallelTrainer.java (373 LoC) — the
EarlyStoppingTrainer loop where each epoch's fitting runs through
ParallelWrapper instead of the single-device solver. Here that is literally
the composition: same termination/saver/score machinery, epochs delegated to
``ParallelWrapper.fit`` over the mesh.
"""
from __future__ import annotations

from typing import Optional

from ..parallel.data_parallel import ParallelWrapper
from .early_stopping import (EarlyStoppingConfiguration, EarlyStoppingResult,
                             EarlyStoppingTrainer)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator,
                 *, mesh=None, workers: Optional[int] = None,
                 averaging_frequency: int = 1,
                 training_mode: str = "shared_gradients",
                 average_updaters: bool = True,
                 gradient_accumulator=None):
        super().__init__(config, net, train_iterator)
        self.wrapper = ParallelWrapper(
            net, mesh=mesh, workers=workers,
            averaging_frequency=averaging_frequency,
            training_mode=training_mode, average_updaters=average_updaters,
            gradient_accumulator=gradient_accumulator)
        # route the epoch fits through the wrapper: the base trainer calls
        # net.fit(iterator=..., epochs=1); shim it (reference wraps the model
        # in ParallelWrapper and drives fit() on it, :112-140)
        self._orig_fit = net.fit

    def fit(self) -> EarlyStoppingResult:
        net = self.net
        wrapper = self.wrapper

        def pw_fit(data=None, labels=None, *, epochs=1, iterator=None, **kw):
            wrapper.fit(iterator, epochs=epochs)
            return net

        net.fit = pw_fit
        try:
            return super().fit()
        finally:
            net.fit = self._orig_fit
