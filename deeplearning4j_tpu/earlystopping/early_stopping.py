"""Early stopping: config, termination conditions, model savers, trainer.

Reference: earlystopping/* — EarlyStoppingConfiguration,
termination/{MaxEpochsTerminationCondition, MaxTimeIterationTerminationCondition,
MaxScoreIterationTerminationCondition, InvalidScoreIterationTerminationCondition,
ScoreImprovementEpochTerminationCondition, BestScoreEpochTerminationCondition},
saver/{InMemoryModelSaver, LocalFileModelSaver},
trainer/BaseEarlyStoppingTrainer.java:76 (fit() loop).
"""
from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import numpy as np


# ----------------------------------------------------------------- score calc
class DataSetLossCalculator:
    """Average loss over a held-out iterator (reference
    earlystopping/scorecalc/DataSetLossCalculator)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total, n = 0.0, 0
        for ds in self.iterator:
            total += net.score(dataset=ds) * ds.num_examples()
            n += ds.num_examples()
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        return total / n if (self.average and n) else total


# ------------------------------------------------------- epoch-level condits
class MaxEpochsTerminationCondition:
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs without (min-delta) improvement."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self._since = 0

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        if improved:
            self._since = 0
        else:
            self._since += 1
        return self._since > self.patience


class BestScoreEpochTerminationCondition:
    def __init__(self, best_expected_score: float):
        self.best_expected_score = best_expected_score

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        return score <= self.best_expected_score


# --------------------------------------------------- iteration-level condits
class MaxTimeIterationTerminationCondition:
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start: Optional[float] = None

    def initialize(self):
        self._start = time.monotonic()

    def terminate(self, score: float) -> bool:
        return (time.monotonic() - self._start) > self.max_seconds


class MaxScoreIterationTerminationCondition:
    """Stop immediately if the score explodes past a bound."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def initialize(self):
        pass

    def terminate(self, score: float) -> bool:
        return score > self.max_score


class InvalidScoreIterationTerminationCondition:
    """Stop on NaN/Inf score — the reference's closest thing to failure
    detection (SURVEY.md §5.3)."""

    def initialize(self):
        pass

    def terminate(self, score: float) -> bool:
        return math.isnan(score) or math.isinf(score)


# ---------------------------------------------------------------- savers
class InMemoryModelSaver:
    def __init__(self):
        self.best = None
        self.latest = None

    def save_best_model(self, net, score):
        self.best = net.clone()

    def save_latest_model(self, net, score):
        self.latest = net.clone()

    def get_best_model(self):
        return self.best

    def get_latest_model(self):
        return self.latest


class LocalFileModelSaver:
    """Save best/latest model zips in a directory (reference
    saver/LocalFileModelSaver)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, kind):
        return os.path.join(self.directory, f"{kind}Model.bin")

    def save_best_model(self, net, score):
        from ..util.serialization import write_model
        write_model(net, self._path("best"))

    def save_latest_model(self, net, score):
        from ..util.serialization import write_model
        write_model(net, self._path("latest"))

    def get_best_model(self):
        from ..util.serialization import restore_model
        return restore_model(self._path("best"))

    def get_latest_model(self):
        from ..util.serialization import restore_model
        return restore_model(self._path("latest"))


# ---------------------------------------------------------------- config
@dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any = None
    model_saver: Any = field(default_factory=InMemoryModelSaver)
    epoch_termination_conditions: List[Any] = field(default_factory=list)
    iteration_termination_conditions: List[Any] = field(default_factory=list)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    score_vs_epoch: dict
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any


class EarlyStoppingTrainer:
    """Reference trainer/BaseEarlyStoppingTrainer.java:76 fit() loop."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator):
        self.config = config
        self.net = net
        self.train_iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        best_score, best_epoch = float("inf"), -1
        scores = {}
        epoch = 0
        reason, details = "EpochTerminationCondition", ""
        from ..optimize.listeners import TrainingListener

        class _IterGuard(TrainingListener):
            def __init__(self):
                self.tripped = None

            def iteration_done(self, model, iteration, score):
                if not cfg.iteration_termination_conditions:
                    return
                # a genuine per-step host-value consumer: ONE readback per
                # iteration, shared across conditions. Train with
                # steps_per_dispatch=1 when conditions must act between
                # individual steps — under a fused K-step window listeners
                # fire after the window, so termination is window-granular.
                from ..optimize.listeners import score_to_float
                s = score_to_float(score)
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(s):
                        self.tripped = c
                        raise _StopTraining()

        guard = _IterGuard()
        saved_listeners = list(self.net.listeners)
        self.net.set_listeners(*(saved_listeners + [guard]))
        try:
            while True:
                try:
                    self.net.fit(iterator=self.train_iterator, epochs=1)
                except _StopTraining:
                    reason = "IterationTerminationCondition"
                    details = type(guard.tripped).__name__
                    break
                if epoch % cfg.evaluate_every_n_epochs == 0:
                    score = (cfg.score_calculator.calculate_score(self.net)
                             if cfg.score_calculator else self.net.score)
                    scores[epoch] = float(score)
                    improved = score < best_score
                    if improved:
                        best_score, best_epoch = float(score), epoch
                        cfg.model_saver.save_best_model(self.net, score)
                    if cfg.save_last_model:
                        cfg.model_saver.save_latest_model(self.net, score)
                    stop = False
                    for c in cfg.epoch_termination_conditions:
                        if c.terminate(epoch, float(score), improved):
                            details = type(c).__name__
                            stop = True
                            break
                    if stop:
                        break
                epoch += 1
        finally:
            self.net.set_listeners(*saved_listeners)
        best_model = cfg.model_saver.get_best_model()
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            score_vs_epoch=scores, best_model_epoch=best_epoch,
            best_model_score=best_score, total_epochs=epoch + 1,
            best_model=best_model or self.net)


class _StopTraining(Exception):
    pass
