from .early_stopping import (BestScoreEpochTerminationCondition,
                             DataSetLossCalculator, EarlyStoppingConfiguration,
                             EarlyStoppingResult, EarlyStoppingTrainer,
                             InMemoryModelSaver,
                             InvalidScoreIterationTerminationCondition,
                             LocalFileModelSaver,
                             MaxEpochsTerminationCondition,
                             MaxScoreIterationTerminationCondition,
                             MaxTimeIterationTerminationCondition,
                             ScoreImprovementEpochTerminationCondition)

__all__ = [n for n in dir() if not n.startswith("_")]
