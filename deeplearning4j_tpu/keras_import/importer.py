"""Keras HDF5 model import.

Reference: deeplearning4j-modelimport — KerasModelImport.java:48-231 (entry
overloads), KerasModel.java:418 (config translation), :510-523 (weight copy),
per-layer translators layers/Keras* (name registry KerasLayer.java:48-70),
Hdf5Archive.java:22-35 (native HDF5 read — h5py here plays the role of the
JavaCPP hdf5 binding; SURVEY.md §2.6.3).

Supports the Keras-1.x-era surface the reference covers (the full
KerasLayer.java:53-70 registry): Sequential and functional Model configs with
Dense, Conv2D(Convolution2D), Conv1D(Convolution1D), MaxPooling1D/2D,
AveragePooling1D/2D, Flatten, Dropout, Activation, BatchNormalization, LSTM,
Embedding, ZeroPadding1D/2D, Merge/Add/Concatenate, GlobalAveragePooling1D/2D,
GlobalMaxPooling1D/2D, TimeDistributed(Dense).
Both 'th' (channels-first) and 'tf' dim orderings; our
runtime layout is NHWC, so 'th' kernels are transposed at import
(the analogue of the reference's TensorFlowCnnToFeedForwardPreProcessor).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from ..nn.conf.config import NeuralNetConfiguration
from ..nn.inputs import InputType
from ..nn.layers import (ActivationLayer, BatchNormalization,
                         Convolution1DLayer, ConvolutionLayer, DenseLayer,
                         DropoutLayer, EmbeddingLayer, GlobalPoolingLayer,
                         LSTM, OutputLayer, Subsampling1DLayer,
                         SubsamplingLayer, ZeroPadding1DLayer,
                         ZeroPaddingLayer)
from ..nn.multilayer import MultiLayerNetwork

_ACT_MAP = {
    "linear": "identity", "relu": "relu", "sigmoid": "sigmoid",
    "softmax": "softmax", "tanh": "tanh", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid", "elu": "elu",
    "selu": "selu",
}

_LOSS_MAP = {
    "categorical_crossentropy": "mcxent", "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mean_absolute_error", "mae": "mean_absolute_error",
    "kullback_leibler_divergence": "kl_divergence", "poisson": "poisson",
    "cosine_proximity": "cosine_proximity", "hinge": "hinge",
    "squared_hinge": "squared_hinge",
    "sparse_categorical_crossentropy": "sparse_mcxent",
}


def _keras_act(cfg, default="identity"):
    a = cfg.get("activation", default) or default
    if a not in _ACT_MAP:
        raise ValueError(f"Unsupported Keras activation {a!r}")
    return _ACT_MAP[a]


def _normalize_loss_entry(loss):
    """One training-config loss entry -> canonical keras snake_case name.
    Handles plain strings and serialized loss OBJECTS ({'class_name': ...,
    'config': {'name': 'mean_squared_error', ...}}) that keras writes when
    the model was compiled with e.g. keras.losses.MeanSquaredError()."""
    if loss is None or isinstance(loss, str):
        return loss
    if isinstance(loss, dict) and "class_name" in loss:
        name = (loss.get("config") or {}).get("name")
        if name:
            return name
        import re
        return re.sub(r"(?<!^)(?=[A-Z])", "_", loss["class_name"]).lower()
    return loss


def _keras_loss(loss: Optional[str], enforce: bool = False) -> str:
    """Map a Keras loss name; unknown -> mcxent fallback (raise when
    enforce_training_config, reference KerasModel enforceTrainingConfig)."""
    loss = _normalize_loss_entry(loss)
    if loss is None:
        return "mcxent"
    if isinstance(loss, str) and loss in _LOSS_MAP:
        return _LOSS_MAP[loss]
    if enforce:
        raise ValueError(f"Unsupported Keras loss {loss!r} "
                         f"(enforce_training_config=True)")
    return "mcxent"


class KerasLayerTranslator:
    """Translate one Keras layer config dict -> our layer conf (or None for
    structural layers like Flatten/InputLayer, which our InputType system
    absorbs)."""

    def __init__(self, dim_ordering: str = "tf", enforce: bool = False):
        self.dim_ordering = dim_ordering
        self.enforce = enforce

    def translate(self, klass: str, cfg: Dict[str, Any], is_output: bool,
                  loss: Optional[str]):
        if klass in ("InputLayer", "Flatten", "Reshape"):
            return None
        if klass == "Dense":
            n_out = cfg.get("output_dim") or cfg.get("units")
            act = _keras_act(cfg)
            if is_output:
                return OutputLayer(n_out=int(n_out), activation=act,
                                   loss=_keras_loss(loss, self.enforce))
            return DenseLayer(n_out=int(n_out), activation=act)
        if klass in ("Convolution2D", "Conv2D"):
            n_out = cfg.get("nb_filter") or cfg.get("filters")
            if "nb_row" in cfg:
                k = (cfg["nb_row"], cfg["nb_col"])
            else:
                k = tuple(cfg["kernel_size"])
            stride = tuple(cfg.get("subsample") or cfg.get("strides") or (1, 1))
            border = cfg.get("border_mode") or cfg.get("padding") or "valid"
            mode = "same" if border == "same" else "truncate"
            return ConvolutionLayer(n_out=int(n_out), kernel_size=k, stride=stride,
                                    convolution_mode=mode, activation=_keras_act(cfg))
        if klass in ("MaxPooling2D", "AveragePooling2D"):
            pt = "max" if klass.startswith("Max") else "avg"
            k = tuple(cfg.get("pool_size") or (2, 2))
            s = tuple(cfg.get("strides") or k)
            border = cfg.get("border_mode") or cfg.get("padding") or "valid"
            return SubsamplingLayer(pooling_type=pt, kernel_size=k, stride=s,
                                    convolution_mode="same" if border == "same"
                                    else "truncate",
                                    avg_pool_include_pad_in_divisor=False)
        if klass in ("GlobalAveragePooling2D", "GlobalMaxPooling2D",
                     "GlobalAveragePooling1D", "GlobalMaxPooling1D"):
            return GlobalPoolingLayer(pooling_type="avg" if "Average" in klass
                                      else "max")
        if klass in ("Convolution1D", "Conv1D"):
            n_out = cfg.get("nb_filter") or cfg.get("filters")
            k = cfg.get("filter_length") or cfg.get("kernel_size")
            if isinstance(k, (list, tuple)):
                k = k[0]
            s = cfg.get("subsample_length") or cfg.get("strides") or 1
            if isinstance(s, (list, tuple)):
                s = s[0]
            border = cfg.get("border_mode") or cfg.get("padding") or "valid"
            if border == "causal":
                raise ValueError("Conv1D padding='causal' is not supported "
                                 "(reference Keras-1 registry has valid/same "
                                 "only, KerasConvolution translator)")
            return Convolution1DLayer(
                n_out=int(n_out), kernel_size=int(k), stride=int(s),
                convolution_mode="same" if border == "same" else "truncate",
                activation=_keras_act(cfg))
        if klass in ("MaxPooling1D", "AveragePooling1D"):
            k = cfg.get("pool_length") or cfg.get("pool_size") or 2
            if isinstance(k, (list, tuple)):
                k = k[0]
            s = cfg.get("stride") or cfg.get("strides") or k
            if isinstance(s, (list, tuple)):
                s = s[0]
            border = cfg.get("border_mode") or cfg.get("padding") or "valid"
            return Subsampling1DLayer(
                pooling_type="max" if klass.startswith("Max") else "avg",
                kernel_size=int(k), stride=int(s),
                convolution_mode="same" if border == "same" else "truncate",
                avg_pool_include_pad_in_divisor=False)
        if klass == "ZeroPadding1D":
            pad = cfg.get("padding", 1)
            if isinstance(pad, (list, tuple)):
                return ZeroPadding1DLayer(padding=tuple(int(v) for v in pad))
            return ZeroPadding1DLayer(padding=int(pad))
        if klass == "TimeDistributed":
            # reference KerasLayer.java:69 LAYER_CLASS_NAME_TIME_DISTRIBUTED_
            # DENSE: only the Dense wrapper is in the registry. Our DenseLayer
            # is natively time-distributed over [B,T,F] (broadcast matmul),
            # so the wrapper dissolves to a DenseLayer.
            inner = cfg.get("layer") or {}
            if inner.get("class_name") != "Dense":
                raise ValueError(
                    f"TimeDistributed({inner.get('class_name')!r}) is not "
                    f"supported (reference covers TimeDistributed(Dense) only)")
            icfg = inner.get("config", {})
            n_out = icfg.get("output_dim") or icfg.get("units")
            if is_output:
                from ..nn.layers import RnnOutputLayer
                return RnnOutputLayer(n_out=int(n_out),
                                      activation=_keras_act(icfg),
                                      loss=_keras_loss(loss, self.enforce))
            return DenseLayer(n_out=int(n_out), activation=_keras_act(icfg))
        if klass == "Dropout":
            p = cfg.get("p") or cfg.get("rate") or 0.5
            return DropoutLayer(dropout=1.0 - float(p))  # keras p = drop prob
        if klass == "Activation":
            if is_output:
                # final standalone Activation (e.g. Dense(linear) + Activation
                # ('softmax')) becomes the scoring layer, so multi-layer heads
                # import as a proper output layer instead of mis-assigning the
                # loss to the preceding Dense.
                from ..nn.layers import LossLayer
                return LossLayer(activation=_keras_act(cfg),
                                 loss=_keras_loss(loss, self.enforce))
            return ActivationLayer(activation=_keras_act(cfg))
        if klass == "BatchNormalization":
            return BatchNormalization(eps=float(cfg.get("epsilon", 1e-5)),
                                      decay=float(cfg.get("momentum", 0.9)))
        if klass == "ZeroPadding2D":
            pad = cfg.get("padding") or (1, 1)
            if isinstance(pad, (list, tuple)) and len(pad) == 2 and \
                    not isinstance(pad[0], (list, tuple)):
                return ZeroPaddingLayer(padding=tuple(pad))
            (t, b), (l, r) = pad
            return ZeroPaddingLayer(padding=(t, b, l, r))
        if klass == "LSTM":
            n_out = cfg.get("output_dim") or cfg.get("units")
            return LSTM(n_out=int(n_out), activation=_keras_act(cfg, "tanh"),
                        gate_activation=_ACT_MAP.get(
                            cfg.get("inner_activation") or
                            cfg.get("recurrent_activation") or "sigmoid",
                            "sigmoid"))
        if klass == "Embedding":
            return EmbeddingLayer(n_in=int(cfg["input_dim"]),
                                  n_out=int(cfg.get("output_dim") or cfg["units"]))
        raise ValueError(f"Unsupported Keras layer class {klass!r} "
                         f"(reference registry KerasLayer.java:48-70)")


def _input_type_from(cfg: Dict[str, Any], dim_ordering: str):
    shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
    if shape is None:
        return None
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:
        if dim_ordering == "th":
            c, h, w = dims
        else:
            h, w, c = dims
        return InputType.convolutional(h, w, c)
    return None


def _collect_weights(f, layer_names):
    """h5 'model_weights'/<layer>/<param datasets> -> {layer: [arrays]}"""
    g = f["model_weights"] if "model_weights" in f else f
    out = {}
    for name in layer_names:
        if name not in g:
            continue
        lg = g[name]
        wn = [n.decode() if isinstance(n, bytes) else n
              for n in lg.attrs.get("weight_names", [])]
        if len(wn):
            arrays = [np.array(lg[n]) for n in wn]
        else:
            arrays = [np.array(lg[k]) for k in sorted(lg.keys())]
        if arrays:
            out[name] = arrays
    return out


def _convert_lstm_weights(arrays, H):
    """Keras-1 LSTM: 12 arrays (W,U,b per gate, order i,c,f,o in keras1 /
    i,f,c,o in some versions) or Keras-2 fused (W[in,4H], U[H,4H], b[4H],
    gate order i,f,c,o). Our packed order is [i,f,o,g]."""
    if len(arrays) == 3:
        W, U, b = arrays
        def reorder(m):
            i, f, c, o = np.split(m, 4, axis=-1)
            return np.concatenate([i, f, o, c], axis=-1)
        return {"W": reorder(W), "R": reorder(U), "b": reorder(b)}
    if len(arrays) == 12:
        # keras1 order: W_i,U_i,b_i, W_c,U_c,b_c, W_f,U_f,b_f, W_o,U_o,b_o
        Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo = arrays
        return {"W": np.concatenate([Wi, Wf, Wo, Wc], axis=-1),
                "R": np.concatenate([Ui, Uf, Uo, Uc], axis=-1),
                "b": np.concatenate([bi, bf, bo, bc], axis=-1)}
    raise ValueError(f"Unexpected LSTM weight count {len(arrays)}")


def import_keras_sequential_model_and_weights(path: str, *, enforce_training_config=False
                                              ) -> MultiLayerNetwork:
    """Reference KerasModelImport.importKerasSequentialModelAndWeights."""
    import h5py
    with h5py.File(path, "r") as f:
        model_cfg, loss = _read_model_config(f, path)
        if isinstance(loss, dict) and "class_name" not in loss:
            loss = next(iter(loss.values()), None)   # single-output: any entry
        elif isinstance(loss, (list, tuple)):
            loss = loss[0] if loss else None
        if model_cfg.get("class_name") != "Sequential":
            raise ValueError("Use import_keras_model_and_weights for functional models")
        layer_cfgs = model_cfg["config"]
        if isinstance(layer_cfgs, dict):
            layer_cfgs = layer_cfgs["layers"]

        dim_ordering = _detect_dim_ordering(layer_cfgs)
        tr = KerasLayerTranslator(dim_ordering, enforce=enforce_training_config)
        confs, keras_names, keras_classes = [], [], []
        itype = None
        for i, lc in enumerate(layer_cfgs):
            cfg = lc.get("config", {})
            if itype is None:
                it = _input_type_from(cfg, dim_ordering)
                if it is not None:
                    itype = it
            is_out = i == len(layer_cfgs) - 1
            conf = tr.translate(lc["class_name"], cfg, is_out, loss)
            if conf is not None:
                confs.append(conf)
                keras_names.append(cfg.get("name") or lc.get("name"))
                keras_classes.append(lc["class_name"])
        b = NeuralNetConfiguration(seed=12345, activation="identity",
                                   weight_init="xavier").list(*confs)
        if itype is not None:
            b = b.set_input_type(itype)
        net = MultiLayerNetwork(b.build()).init()

        weights = _collect_weights(f, [n for n in keras_names if n])
        _copy_weights_mln(net, keras_names, keras_classes, weights, dim_ordering)
    return net


def _assign_layer_arrays(layer, arrays, pdict, sdict, dim_ordering):
    """Write one Keras layer's weight arrays into a (params, state) dict pair
    (reference KerasModel.java:510-523 copyWeightsToModel). Shared by the
    Sequential (MLN) and functional (ComputationGraph) import paths."""
    from ..nn.layers import (BatchNormalization, Convolution1DLayer,
                             ConvolutionLayer, DenseLayer, EmbeddingLayer,
                             LSTM)
    if isinstance(layer, (ConvolutionLayer, Convolution1DLayer)):
        W = arrays[0]
        if W.ndim == 4 and dim_ordering == "th":
            W = W.transpose(2, 3, 1, 0)  # OIHW -> HWIO
        # keras Conv1D kernels are (k, in, out) = our WIO already
        pdict["W"] = np_cast(W, pdict["W"])
        if len(arrays) > 1:
            pdict["b"] = np_cast(arrays[1], pdict["b"])
    elif isinstance(layer, LSTM):
        conv = _convert_lstm_weights(arrays, layer.n_out)
        for k, v in conv.items():
            pdict[k] = np_cast(v, pdict[k])
    elif isinstance(layer, BatchNormalization):
        # keras order: gamma, beta, running_mean, running_var
        pdict["gamma"] = np_cast(arrays[0], pdict["gamma"])
        pdict["beta"] = np_cast(arrays[1], pdict["beta"])
        if len(arrays) >= 4:
            sdict["mean"] = np_cast(arrays[2], sdict["mean"])
            sdict["var"] = np_cast(arrays[3], sdict["var"])
    elif isinstance(layer, (DenseLayer, EmbeddingLayer)):
        pdict["W"] = np_cast(arrays[0], pdict["W"])
        if len(arrays) > 1 and "b" in pdict:
            pdict["b"] = np_cast(arrays[1], pdict["b"])


def _copy_weights_mln(net, keras_names, keras_classes, weights, dim_ordering):
    params = [dict(p) for p in net.params]
    state = [dict(s) for s in net.state]
    for li, (kname, kclass) in enumerate(zip(keras_names, keras_classes)):
        if kname not in weights:
            continue
        _assign_layer_arrays(net.layers[li], weights[kname], params[li],
                             state[li], dim_ordering)
    net.params = tuple(params)
    net.state = tuple(state)
    net.opt_state = net.updater.init(net.params)


def np_cast(src, like):
    import jax.numpy as jnp
    src = np.asarray(src)
    if src.shape != like.shape:
        raise ValueError(f"Weight shape mismatch: keras {src.shape} vs "
                         f"model {like.shape}")
    return jnp.asarray(src, like.dtype)


# --------------------------------------------------------------- functional
def _inbound_names(node) -> List[str]:
    """Extract input layer names from one inbound node, covering both the
    legacy Keras-1/2 format ([["name", node_idx, tensor_idx, {...}], ...])
    and the Keras-3 format ({"args": [{"class_name": "__keras_tensor__",
    "config": {"keras_history": ["name", 0, 0]}}, ...], "kwargs": ...})."""
    names: List[str] = []

    def walk(o):
        if isinstance(o, dict):
            if o.get("class_name") == "__keras_tensor__":
                names.append(o["config"]["keras_history"][0])
            elif "args" in o:
                walk(o["args"])
        elif isinstance(o, (list, tuple)):
            if (len(o) >= 3 and isinstance(o[0], str)
                    and isinstance(o[1], int) and isinstance(o[2], int)):
                names.append(o[0])
            else:
                for v in o:
                    walk(v)

    walk(node)
    return names


def _io_layer_names(entry) -> List[str]:
    """config['input_layers'] / ['output_layers']: either [name, 0, 0] for a
    single tensor or [[name, 0, 0], ...] for several."""
    if not entry:
        return []
    if isinstance(entry[0], str):
        return [entry[0]]
    return [e[0] for e in entry]


def _loss_for_output(loss, out_name: str, out_index: int):
    """Keras training_config loss may be a single loss (str or serialized
    object — applies to all outputs), a dict keyed by output layer name, or a
    positional list."""
    if loss is None or isinstance(loss, str):
        return loss
    if isinstance(loss, dict):
        if "class_name" in loss:  # one serialized loss object for all outputs
            return loss
        return loss.get(out_name)
    if isinstance(loss, (list, tuple)) and out_index < len(loss):
        return loss[out_index]
    return None


def _detect_dim_ordering(layer_cfgs) -> str:
    """'tf' (channels-last) unless a Keras-1 'dim_ordering' key says 'th'.
    Keras-1 'th' files store conv kernels OIHW (transposed at weight copy);
    Keras>=2 'channels_first' models store kernels HWIO regardless, but their
    whole dataflow is NCHW — unsupported against our NHWC runtime, so gate
    clearly instead of importing garbage."""
    for lc in layer_cfgs:
        c = lc.get("config", {})
        if c.get("data_format") == "channels_first":
            raise ValueError(
                "channels_first Keras models are not supported; rebuild the "
                "model with data_format='channels_last' (runtime layout is "
                "NHWC)")
        if "dim_ordering" in c:
            return c["dim_ordering"]
    return "tf"


def _read_model_config(f, path):
    raw = f.attrs.get("model_config")
    if raw is None:
        raise ValueError(f"{path} has no model_config attribute")
    model_cfg = json.loads(raw if isinstance(raw, str) else raw.decode())
    training_cfg = f.attrs.get("training_config")
    loss = None
    if training_cfg is not None:
        tc = json.loads(training_cfg if isinstance(training_cfg, str)
                        else training_cfg.decode())
        loss = tc.get("loss")
    return model_cfg, loss


def import_keras_model_and_weights(path: str, *, enforce_training_config=False):
    """Functional Keras Model -> ComputationGraph with weights copied
    (reference KerasModel.java:418 getComputationGraphConfiguration +
    :510-523 getComputationGraph/copyWeightsToModel). Layers become
    LayerVertex entries in the Keras topological order; merge layers become
    Merge/ElementWise vertices; structural layers (InputLayer/Flatten/
    Reshape) are dissolved, their consumers rewired to the producer — our
    InputType machinery auto-inserts the CNN->FF preprocessor the Flatten
    stood for."""
    import h5py
    from ..nn.conf.config import NeuralNetConfiguration
    from ..nn.graph.graph import ComputationGraph
    from ..nn.graph.vertices import (ElementWiseVertex, LastTimeStepVertex,
                                     MergeVertex)

    with h5py.File(path, "r") as f:
        model_cfg, loss = _read_model_config(f, path)
        if model_cfg.get("class_name") not in ("Model", "Functional"):
            raise ValueError(f"{path} is not a functional Keras model "
                             f"(class {model_cfg.get('class_name')!r})")
        cfg = model_cfg["config"]
        layer_cfgs = cfg["layers"]
        in_names = _io_layer_names(cfg.get("input_layers"))
        out_names = _io_layer_names(cfg.get("output_layers"))

        dim_ordering = _detect_dim_ordering(layer_cfgs)
        tr = KerasLayerTranslator(dim_ordering, enforce=enforce_training_config)

        b = (NeuralNetConfiguration(seed=12345, activation="identity",
                                    weight_init="xavier")
             .graph_builder())
        b.add_inputs(*in_names)

        # name -> resolved vertex name (structural layers dissolve to their
        # producer, like the reference's preprocessor-only KerasLayer merge).
        resolved: Dict[str, str] = {n: n for n in in_names}
        input_types: Dict[str, Any] = {}
        keras_name_of: Dict[str, str] = {}   # vertex name -> keras layer name

        _MERGE = {"Concatenate": "concat", "Merge": None, "Add": "add",
                  "Average": "average", "Maximum": "max", "Subtract": "subtract",
                  "Multiply": "product"}

        for lc in layer_cfgs:
            klass = lc["class_name"]
            c = lc.get("config", {})
            name = c.get("name") or lc.get("name")
            inbound = [n for node in lc.get("inbound_nodes", [])
                       for n in _inbound_names(node)]
            srcs = [resolved[n] for n in inbound]
            if klass == "InputLayer":
                it = _input_type_from(c, dim_ordering)
                if it is not None:
                    input_types[name] = it
                resolved[name] = name
                continue
            if klass in ("Flatten", "Reshape"):
                resolved[name] = srcs[0]
                continue
            if klass in _MERGE:
                mode = _MERGE[klass]
                if klass == "Merge":  # keras-1 Merge(mode=...)
                    m = c.get("mode", "concat")
                    mode = {"sum": "add", "concat": "concat", "mul": "product",
                            "ave": "average", "max": "max"}.get(m)
                    if mode is None:
                        raise ValueError(f"Unsupported Merge mode {m!r}")
                if mode == "concat":
                    b.add_vertex(name, MergeVertex(), *srcs)
                else:
                    b.add_vertex(name, ElementWiseVertex(op=mode), *srcs)
                resolved[name] = name
                keras_name_of[name] = name
                continue
            is_out = name in out_names
            out_loss = _loss_for_output(loss, name, out_names.index(name)) \
                if is_out else None
            conf = tr.translate(klass, c, is_out, out_loss)
            if conf is None:
                resolved[name] = srcs[0]
                continue
            if klass == "LSTM" and not c.get("return_sequences", False):
                # keras LSTM(return_sequences=False) emits [B,H] at the last
                # step; our LSTM emits the whole sequence -> append the
                # LastTimeStep vertex (reference rnn/LastTimeStepVertex).
                b.add_layer(name + "__seq", conf, *srcs)
                b.add_vertex(name, LastTimeStepVertex(), name + "__seq")
                keras_name_of[name + "__seq"] = name
                resolved[name] = name
                continue
            b.add_layer(name, conf, *srcs)
            keras_name_of[name] = name
            resolved[name] = name

        b.set_outputs(*[resolved[n] for n in out_names])
        if len(input_types) == len(in_names):
            b.set_input_types(*[input_types[n] for n in in_names])
        graph = ComputationGraph(b.build()).init()

        weights = _collect_weights(f, list(keras_name_of.values()))
        _copy_weights_cg(graph, keras_name_of, weights, dim_ordering)
    return graph


def _copy_weights_cg(graph, keras_name_of, weights, dim_ordering):
    params = [dict(p) for p in graph.params]
    state = [dict(s) for s in graph.state]
    for vi, vname in enumerate(graph.vertex_names):
        kname = keras_name_of.get(vname)
        if kname is None or kname not in weights:
            continue
        layer = graph.vertices[vi].layer
        if layer is None:
            continue
        _assign_layer_arrays(layer, weights[kname], params[vi], state[vi],
                             dim_ordering)
    graph.params = tuple(params)
    graph.state = tuple(state)
    graph.opt_state = graph.updater.init(graph.params)


def import_keras_model(path: str, *, enforce_training_config=False):
    """Reference KerasModelImport.importKerasModelAndWeights: sniff
    Sequential vs functional."""
    import h5py
    with h5py.File(path, "r") as f:
        raw = f.attrs.get("model_config")
        if raw is None:
            raise ValueError(f"{path}: no model_config")
        cfg = json.loads(raw if isinstance(raw, str) else raw.decode())
    if cfg.get("class_name") == "Sequential":
        return import_keras_sequential_model_and_weights(
            path, enforce_training_config=enforce_training_config)
    return import_keras_model_and_weights(
        path, enforce_training_config=enforce_training_config)
