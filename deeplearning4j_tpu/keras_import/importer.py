"""Keras HDF5 model import.

Reference: deeplearning4j-modelimport — KerasModelImport.java:48-231 (entry
overloads), KerasModel.java:418 (config translation), :510-523 (weight copy),
per-layer translators layers/Keras* (name registry KerasLayer.java:48-70),
Hdf5Archive.java:22-35 (native HDF5 read — h5py here plays the role of the
JavaCPP hdf5 binding; SURVEY.md §2.6.3).

Supports the Keras-1.x-era surface the reference covers: Sequential and
functional Model configs with Dense, Conv2D(Convolution2D), MaxPooling2D,
AveragePooling2D, Flatten, Dropout, Activation, BatchNormalization, LSTM,
Embedding, ZeroPadding2D, Merge/Add/Concatenate, GlobalAveragePooling2D,
GlobalMaxPooling2D. Both 'th' (channels-first) and 'tf' dim orderings; our
runtime layout is NHWC, so 'th' kernels are transposed at import
(the analogue of the reference's TensorFlowCnnToFeedForwardPreProcessor).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from ..nn.conf.config import NeuralNetConfiguration
from ..nn.inputs import InputType
from ..nn.layers import (ActivationLayer, BatchNormalization, ConvolutionLayer,
                         DenseLayer, DropoutLayer, EmbeddingLayer,
                         GlobalPoolingLayer, LSTM, OutputLayer,
                         SubsamplingLayer, ZeroPaddingLayer)
from ..nn.multilayer import MultiLayerNetwork

_ACT_MAP = {
    "linear": "identity", "relu": "relu", "sigmoid": "sigmoid",
    "softmax": "softmax", "tanh": "tanh", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid", "elu": "elu",
    "selu": "selu",
}

_LOSS_MAP = {
    "categorical_crossentropy": "mcxent", "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mean_absolute_error", "mae": "mean_absolute_error",
    "kullback_leibler_divergence": "kl_divergence", "poisson": "poisson",
    "cosine_proximity": "cosine_proximity", "hinge": "hinge",
    "squared_hinge": "squared_hinge",
    "sparse_categorical_crossentropy": "sparse_mcxent",
}


def _keras_act(cfg, default="identity"):
    a = cfg.get("activation", default) or default
    if a not in _ACT_MAP:
        raise ValueError(f"Unsupported Keras activation {a!r}")
    return _ACT_MAP[a]


class KerasLayerTranslator:
    """Translate one Keras layer config dict -> our layer conf (or None for
    structural layers like Flatten/InputLayer, which our InputType system
    absorbs)."""

    def __init__(self, dim_ordering: str = "tf"):
        self.dim_ordering = dim_ordering

    def translate(self, klass: str, cfg: Dict[str, Any], is_output: bool,
                  loss: Optional[str]):
        if klass in ("InputLayer", "Flatten", "Reshape"):
            return None
        if klass == "Dense":
            n_out = cfg.get("output_dim") or cfg.get("units")
            act = _keras_act(cfg)
            if is_output:
                return OutputLayer(n_out=int(n_out), activation=act,
                                   loss=_LOSS_MAP.get(loss or "", "mcxent"))
            return DenseLayer(n_out=int(n_out), activation=act)
        if klass in ("Convolution2D", "Conv2D"):
            n_out = cfg.get("nb_filter") or cfg.get("filters")
            if "nb_row" in cfg:
                k = (cfg["nb_row"], cfg["nb_col"])
            else:
                k = tuple(cfg["kernel_size"])
            stride = tuple(cfg.get("subsample") or cfg.get("strides") or (1, 1))
            border = cfg.get("border_mode") or cfg.get("padding") or "valid"
            mode = "same" if border == "same" else "truncate"
            return ConvolutionLayer(n_out=int(n_out), kernel_size=k, stride=stride,
                                    convolution_mode=mode, activation=_keras_act(cfg))
        if klass in ("MaxPooling2D", "AveragePooling2D"):
            pt = "max" if klass.startswith("Max") else "avg"
            k = tuple(cfg.get("pool_size") or (2, 2))
            s = tuple(cfg.get("strides") or k)
            border = cfg.get("border_mode") or cfg.get("padding") or "valid"
            return SubsamplingLayer(pooling_type=pt, kernel_size=k, stride=s,
                                    convolution_mode="same" if border == "same"
                                    else "truncate")
        if klass in ("GlobalAveragePooling2D", "GlobalMaxPooling2D"):
            return GlobalPoolingLayer(pooling_type="avg" if "Average" in klass
                                      else "max")
        if klass == "Dropout":
            p = cfg.get("p") or cfg.get("rate") or 0.5
            return DropoutLayer(dropout=1.0 - float(p))  # keras p = drop prob
        if klass == "Activation":
            return ActivationLayer(activation=_keras_act(cfg))
        if klass == "BatchNormalization":
            return BatchNormalization(eps=float(cfg.get("epsilon", 1e-5)),
                                      decay=float(cfg.get("momentum", 0.9)))
        if klass == "ZeroPadding2D":
            pad = cfg.get("padding") or (1, 1)
            if isinstance(pad, (list, tuple)) and len(pad) == 2 and \
                    not isinstance(pad[0], (list, tuple)):
                return ZeroPaddingLayer(padding=tuple(pad))
            (t, b), (l, r) = pad
            return ZeroPaddingLayer(padding=(t, b, l, r))
        if klass == "LSTM":
            n_out = cfg.get("output_dim") or cfg.get("units")
            return LSTM(n_out=int(n_out), activation=_keras_act(cfg, "tanh"),
                        gate_activation=_ACT_MAP.get(
                            cfg.get("inner_activation") or
                            cfg.get("recurrent_activation") or "sigmoid",
                            "sigmoid"))
        if klass == "Embedding":
            return EmbeddingLayer(n_in=int(cfg["input_dim"]),
                                  n_out=int(cfg.get("output_dim") or cfg["units"]))
        raise ValueError(f"Unsupported Keras layer class {klass!r} "
                         f"(reference registry KerasLayer.java:48-70)")


def _input_type_from(cfg: Dict[str, Any], dim_ordering: str):
    shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
    if shape is None:
        return None
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:
        if dim_ordering == "th":
            c, h, w = dims
        else:
            h, w, c = dims
        return InputType.convolutional(h, w, c)
    return None


def _collect_weights(f, layer_names):
    """h5 'model_weights'/<layer>/<param datasets> -> {layer: [arrays]}"""
    g = f["model_weights"] if "model_weights" in f else f
    out = {}
    for name in layer_names:
        if name not in g:
            continue
        lg = g[name]
        wn = [n.decode() if isinstance(n, bytes) else n
              for n in lg.attrs.get("weight_names", [])]
        if len(wn):
            arrays = [np.array(lg[n]) for n in wn]
        else:
            arrays = [np.array(lg[k]) for k in sorted(lg.keys())]
        if arrays:
            out[name] = arrays
    return out


def _convert_lstm_weights(arrays, H):
    """Keras-1 LSTM: 12 arrays (W,U,b per gate, order i,c,f,o in keras1 /
    i,f,c,o in some versions) or Keras-2 fused (W[in,4H], U[H,4H], b[4H],
    gate order i,f,c,o). Our packed order is [i,f,o,g]."""
    if len(arrays) == 3:
        W, U, b = arrays
        def reorder(m):
            i, f, c, o = np.split(m, 4, axis=-1)
            return np.concatenate([i, f, o, c], axis=-1)
        return {"W": reorder(W), "R": reorder(U), "b": reorder(b)}
    if len(arrays) == 12:
        # keras1 order: W_i,U_i,b_i, W_c,U_c,b_c, W_f,U_f,b_f, W_o,U_o,b_o
        Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo = arrays
        return {"W": np.concatenate([Wi, Wf, Wo, Wc], axis=-1),
                "R": np.concatenate([Ui, Uf, Uo, Uc], axis=-1),
                "b": np.concatenate([bi, bf, bo, bc], axis=-1)}
    raise ValueError(f"Unexpected LSTM weight count {len(arrays)}")


def import_keras_sequential_model_and_weights(path: str, *, enforce_training_config=False
                                              ) -> MultiLayerNetwork:
    """Reference KerasModelImport.importKerasSequentialModelAndWeights."""
    import h5py
    with h5py.File(path, "r") as f:
        raw = f.attrs.get("model_config")
        if raw is None:
            raise ValueError(f"{path} has no model_config attribute")
        model_cfg = json.loads(raw if isinstance(raw, str) else raw.decode())
        training_cfg = f.attrs.get("training_config")
        loss = None
        if training_cfg is not None:
            tc = json.loads(training_cfg if isinstance(training_cfg, str)
                            else training_cfg.decode())
            loss = tc.get("loss")
        if model_cfg.get("class_name") != "Sequential":
            raise ValueError("Use import_keras_model_and_weights for functional models")
        layer_cfgs = model_cfg["config"]
        if isinstance(layer_cfgs, dict):
            layer_cfgs = layer_cfgs["layers"]

        dim_ordering = "tf"
        for lc in layer_cfgs:
            if "dim_ordering" in lc.get("config", {}):
                dim_ordering = lc["config"]["dim_ordering"]
                break
        tr = KerasLayerTranslator(dim_ordering)
        confs, keras_names, keras_classes = [], [], []
        itype = None
        for i, lc in enumerate(layer_cfgs):
            cfg = lc.get("config", {})
            if itype is None:
                it = _input_type_from(cfg, dim_ordering)
                if it is not None:
                    itype = it
            is_out = i == len(layer_cfgs) - 1
            conf = tr.translate(lc["class_name"], cfg, is_out, loss)
            if conf is not None:
                confs.append(conf)
                keras_names.append(cfg.get("name") or lc.get("name"))
                keras_classes.append(lc["class_name"])
        b = NeuralNetConfiguration(seed=12345, activation="identity",
                                   weight_init="xavier").list(*confs)
        if itype is not None:
            b = b.set_input_type(itype)
        net = MultiLayerNetwork(b.build()).init()

        weights = _collect_weights(f, [n for n in keras_names if n])
        _copy_weights_mln(net, keras_names, keras_classes, weights, dim_ordering)
    return net


def _copy_weights_mln(net, keras_names, keras_classes, weights, dim_ordering):
    params = [dict(p) for p in net.params]
    state = [dict(s) for s in net.state]
    for li, (kname, kclass) in enumerate(zip(keras_names, keras_classes)):
        if kname not in weights:
            continue
        arrays = weights[kname]
        layer = net.layers[li]
        from ..nn.layers import (BatchNormalization, ConvolutionLayer,
                                 DenseLayer, EmbeddingLayer, LSTM, OutputLayer)
        if isinstance(layer, (ConvolutionLayer,)):
            W = arrays[0]
            if W.ndim == 4 and dim_ordering == "th":
                W = W.transpose(2, 3, 1, 0)  # OIHW -> HWIO
            params[li]["W"] = np_cast(W, params[li]["W"])
            if len(arrays) > 1:
                params[li]["b"] = np_cast(arrays[1], params[li]["b"])
        elif isinstance(layer, LSTM):
            conv = _convert_lstm_weights(arrays, layer.n_out)
            for k, v in conv.items():
                params[li][k] = np_cast(v, params[li][k])
        elif isinstance(layer, BatchNormalization):
            # keras order: gamma, beta, running_mean, running_var
            params[li]["gamma"] = np_cast(arrays[0], params[li]["gamma"])
            params[li]["beta"] = np_cast(arrays[1], params[li]["beta"])
            if len(arrays) >= 4:
                state[li]["mean"] = np_cast(arrays[2], state[li]["mean"])
                state[li]["var"] = np_cast(arrays[3], state[li]["var"])
        elif isinstance(layer, (DenseLayer, OutputLayer, EmbeddingLayer)):
            params[li]["W"] = np_cast(arrays[0], params[li]["W"])
            if len(arrays) > 1 and "b" in params[li]:
                params[li]["b"] = np_cast(arrays[1], params[li]["b"])
    import jax.numpy as jnp
    net.params = tuple(params)
    net.state = tuple(state)
    net.opt_state = net.updater.init(net.params)


def np_cast(src, like):
    import jax.numpy as jnp
    src = np.asarray(src)
    if src.shape != like.shape:
        raise ValueError(f"Weight shape mismatch: keras {src.shape} vs "
                         f"model {like.shape}")
    return jnp.asarray(src, like.dtype)


def import_keras_model(path: str):
    """Reference KerasModelImport.importKerasModelAndWeights: sniff
    Sequential vs functional."""
    import h5py
    with h5py.File(path, "r") as f:
        raw = f.attrs.get("model_config")
        if raw is None:
            raise ValueError(f"{path}: no model_config")
        cfg = json.loads(raw if isinstance(raw, str) else raw.decode())
    if cfg.get("class_name") == "Sequential":
        return import_keras_sequential_model_and_weights(path)
    raise NotImplementedError("Functional Keras model import lands next round "
                              "(reference KerasModel.java:418)")
