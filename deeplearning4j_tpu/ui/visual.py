"""Visual listeners + renderers: conv-activation grids, model-graph view,
t-SNE page.

Reference:
- deeplearning4j-ui/.../ConvolutionalIterationListener.java — every N
  iterations, renders each convolutional layer's activation maps as a grid
  image for the UI.
- FlowIterationListener.java + deeplearning4j-play TrainModule model tab
  (TrainModule.java:94-110) — the model-graph/flow view: the network DAG
  drawn with per-layer boxes.
- deeplearning4j-play `tsne` module — serves a 2-D scatter page of t-SNE
  coordinates.

TPU-first reshape: activations for a report come from ONE jitted forward
over a fixed sample batch (the training step itself is a fused XLA program;
its intermediates are not observable without re-running the forward — same
stance as StatsListener.collect_activation_stats). Images are rendered
host-side with PIL into base64 PNGs stored as ordinary JSON update records,
so every storage backend (memory / file / remote router) carries them and
the dashboard inlines them with data: URIs.
"""
from __future__ import annotations

import base64
import html as _html
import io
import math
from typing import Any, Dict, List, Optional

import numpy as np

from ..optimize.listeners import TrainingListener
from .storage import InMemoryStatsStorage, StatsStorage


# ------------------------------------------------------------ image helpers
def activation_grid_png(act: np.ndarray, max_channels: int = 16,
                        upscale: int = 1) -> str:
    """[H, W, C] activation -> base64 PNG of a sqrt-ish channel grid
    (reference ConvolutionalIterationListener's per-layer grid image).
    Each channel is min-max normalized to 8-bit grayscale."""
    from PIL import Image

    act = np.asarray(act)
    if act.ndim != 3:
        raise ValueError(f"expected [H,W,C] activation, got {act.shape}")
    H, W, C = act.shape
    C = min(C, max_channels)
    cols = int(math.ceil(math.sqrt(C)))
    rows = int(math.ceil(C / cols))
    pad = 1
    canvas = np.zeros((rows * (H + pad) + pad, cols * (W + pad) + pad),
                      np.uint8)
    for c in range(C):
        a = act[:, :, c].astype(np.float64)
        lo, hi = float(a.min()), float(a.max())
        img = ((a - lo) / (hi - lo) * 255.0 if hi > lo
               else np.zeros_like(a)).astype(np.uint8)
        r, col = divmod(c, cols)
        y0 = pad + r * (H + pad)
        x0 = pad + col * (W + pad)
        canvas[y0:y0 + H, x0:x0 + W] = img
    im = Image.fromarray(canvas, "L")
    if upscale > 1:
        im = im.resize((im.width * upscale, im.height * upscale),
                       Image.NEAREST)
    buf = io.BytesIO()
    im.save(buf, "PNG")
    return base64.b64encode(buf.getvalue()).decode("ascii")


class ConvolutionalIterationListener(TrainingListener):
    """Render conv-layer activation grids into the StatsStorage every
    ``frequency`` iterations (reference ConvolutionalIterationListener).

    ``sample``: one input batch (the FIRST example's activations are
    rendered). Works for MultiLayerNetwork (feed_forward list) and
    ComputationGraph (feed_forward dict); every 4-D [B,H,W,C] activation is
    treated as a conv layer output.
    """

    def __init__(self, sample, storage: Optional[StatsStorage] = None,
                 frequency: int = 10, session_id: Optional[str] = None,
                 worker_id: str = "worker_0", max_channels: int = 16,
                 max_layers: int = 8):
        import uuid
        self.storage = storage if storage is not None else InMemoryStatsStorage()
        # only example 0's activations are rendered — don't pay a full-batch
        # forward per report
        self.sample = np.asarray(sample)[:1]
        self.frequency = max(1, frequency)
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id
        self.max_channels = max_channels
        self.max_layers = max_layers

    def _named_activations(self, model) -> List[tuple]:
        acts = model.feed_forward(self.sample)
        if isinstance(acts, dict):
            named = list(acts.items())
        else:
            named = [(f"layer_{i}", a) for i, a in enumerate(acts)]
        return [(n, np.asarray(a)) for n, a in named
                if getattr(a, "ndim", 0) == 4]

    def iteration_done(self, model, iteration: int, score):
        if iteration % self.frequency != 0:
            return
        images: Dict[str, str] = {}
        for name, act in self._named_activations(model)[:self.max_layers]:
            images[name] = activation_grid_png(act[0], self.max_channels)
        if images:
            self.storage.put_update(self.session_id, self.worker_id, {
                "iteration": int(iteration),
                "conv_activations": images,
            })


# ------------------------------------------------------------- model graph
def _graph_layout(names: List[str], inputs_of: Dict[str, List[str]],
                  network_inputs: List[str]):
    """Longest-path depth per node -> columns of boxes."""
    depth = {n: 0 for n in network_inputs}
    for n in names:                      # names are topo-ordered
        ins = [i for i in inputs_of.get(n, [])]
        depth[n] = 1 + max((depth.get(i, 0) for i in ins), default=0)
    cols: Dict[int, List[str]] = {}
    for n in network_inputs + list(names):
        cols.setdefault(depth[n], []).append(n)
    return depth, cols


def render_model_graph_svg(conf) -> str:
    """SVG DAG of a network configuration (reference FlowIterationListener /
    TrainModule model tab). Accepts a ComputationGraphConfiguration (full
    DAG) or a MultiLayerConfiguration (rendered as a chain)."""
    if hasattr(conf, "vertex_names"):          # ComputationGraph
        names = list(conf.vertex_names)
        inputs_of = {n: list(conf.vertex_inputs[n]) for n in names}
        net_inputs = list(conf.network_inputs)
        outputs = set(conf.network_outputs)

        def label(n):
            if n in net_inputs:
                return "Input"
            v = conf.vertices[n]
            layer = getattr(v, "layer", None)
            return type(layer).__name__ if layer is not None else type(v).__name__
    else:                                      # MultiLayerConfiguration chain
        names = [f"{i}: {type(l).__name__}" for i, l in enumerate(conf.layers)]
        inputs_of = {names[i]: ([names[i - 1]] if i else ["input"])
                     for i in range(len(names))}
        net_inputs = ["input"]
        outputs = {names[-1]} if names else set()

        def label(n):
            return "Input" if n == "input" else n.split(": ", 1)[1]

    depth, cols = _graph_layout(names, inputs_of, net_inputs)
    BOX_W, BOX_H, XGAP, YGAP = 148, 34, 50, 14
    pos = {}
    max_rows = max(len(v) for v in cols.values()) if cols else 1
    height = max_rows * (BOX_H + YGAP) + YGAP + 20
    for d in sorted(cols):
        col_nodes = cols[d]
        y0 = (height - len(col_nodes) * (BOX_H + YGAP)) / 2
        for i, n in enumerate(col_nodes):
            pos[n] = (10 + d * (BOX_W + XGAP), y0 + i * (BOX_H + YGAP))
    width = 10 + (max(depth.values(), default=0) + 1) * (BOX_W + XGAP)

    parts = [f'<svg width="{width}" height="{height:.0f}" '
             f'xmlns="http://www.w3.org/2000/svg">'
             '<defs><marker id="arr" markerWidth="8" markerHeight="8" '
             'refX="7" refY="3" orient="auto"><path d="M0,0 L7,3 L0,6 z" '
             'fill="#94a3b8"/></marker></defs>']
    for n in names:
        for i in inputs_of.get(n, []):
            if i not in pos or n not in pos:
                continue
            x1, y1 = pos[i][0] + BOX_W, pos[i][1] + BOX_H / 2
            x2, y2 = pos[n][0], pos[n][1] + BOX_H / 2
            parts.append(f'<path d="M{x1:.0f},{y1:.0f} C{x1+25:.0f},{y1:.0f} '
                         f'{x2-25:.0f},{y2:.0f} {x2:.0f},{y2:.0f}" fill="none" '
                         f'stroke="#94a3b8" marker-end="url(#arr)"/>')
    for n, (x, y) in pos.items():
        is_in = n in net_inputs
        is_out = n in outputs
        fill = "#dbeafe" if is_in else ("#dcfce7" if is_out else "#f8fafc")
        parts.append(f'<rect x="{x:.0f}" y="{y:.0f}" width="{BOX_W}" '
                     f'height="{BOX_H}" rx="6" fill="{fill}" '
                     f'stroke="#64748b"/>')
        disp = n if len(str(n)) <= 18 else str(n)[:17] + "…"
        parts.append(f'<text x="{x+6:.0f}" y="{y+14:.0f}" font-size="10" '
                     f'fill="#0f172a">{_html.escape(str(disp))}</text>')
        parts.append(f'<text x="{x+6:.0f}" y="{y+27:.0f}" font-size="9" '
                     f'fill="#64748b">{_html.escape(label(n))}</text>')
    parts.append("</svg>")
    return "".join(parts)


def render_model_graph(conf, path: str) -> str:
    """Write the model-graph SVG to ``path``; returns the path."""
    with open(path, "w") as f:
        f.write(render_model_graph_svg(conf))
    return path


# ------------------------------------------------------------------- t-SNE
def render_tsne_page(coords, labels=None, *, title: str = "t-SNE",
                     width: int = 760, height: int = 640) -> str:
    """HTML page with an SVG scatter of 2-D embedding coordinates
    (reference deeplearning4j-play `tsne` module page). ``coords``: [N, 2];
    ``labels``: optional N strings/ints used for color groups + text."""
    coords = np.asarray(coords, np.float64)
    if coords.ndim != 2 or coords.shape[1] != 2:
        raise ValueError(f"expected [N,2] coords, got {coords.shape}")
    labels = list(labels) if labels is not None else [None] * len(coords)
    groups = sorted({str(l) for l in labels if l is not None})
    palette = ["#2563eb", "#dc2626", "#059669", "#d97706", "#7c3aed",
               "#0891b2", "#be185d", "#4d7c0f", "#64748b", "#1e40af"]
    color_of = {g: palette[i % len(palette)] for i, g in enumerate(groups)}
    lo = coords.min(axis=0)
    hi = coords.max(axis=0)
    span = np.where(hi - lo > 0, hi - lo, 1.0)
    pad = 30
    parts = [f'<svg width="{width}" height="{height}" '
             f'xmlns="http://www.w3.org/2000/svg">']
    for (x, y), l in zip(coords, labels):
        sx = pad + (x - lo[0]) / span[0] * (width - 2 * pad)
        sy = pad + (1 - (y - lo[1]) / span[1]) * (height - 2 * pad)
        c = color_of.get(str(l), "#334155")
        parts.append(f'<circle cx="{sx:.1f}" cy="{sy:.1f}" r="3" fill="{c}" '
                     f'fill-opacity="0.75"/>')
        if l is not None and len(coords) <= 200:
            parts.append(f'<text x="{sx+4:.1f}" y="{sy+3:.1f}" font-size="9" '
                         f'fill="#475569">{_html.escape(str(l))}</text>')
    legend = "".join(
        f'<span style="color:{color_of[g]}">&#9679;</span> '
        f'{_html.escape(g)} &nbsp; ' for g in groups[:12])
    parts.append("</svg>")
    return (f"<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{_html.escape(title)}</title></head>"
            f"<body style=\"font-family:sans-serif\"><h1>{_html.escape(title)}"
            f"</h1><div>{legend}</div>{''.join(parts)}</body></html>")


def render_tsne(coords, path: str, labels=None, **kw) -> str:
    with open(path, "w") as f:
        f.write(render_tsne_page(coords, labels, **kw))
    return path
