"""StatsStorage SPI + in-memory and file-backed implementations.

Reference: deeplearning4j-ui-parent/deeplearning4j-ui-model/src/main/java/org/
deeplearning4j/api/storage/StatsStorage.java (SPI: listSessionIDs,
getAllUpdatesAfter, getStaticInfo, listeners) with InMemoryStatsStorage and
FileStatsStorage (MapDB) as the stock backends.

TPU-first reshape: records are plain JSON-able dicts (the reference's SBE
binary encoding existed to cross the JVM/Play boundary; here the dashboard
consumes JSON directly). The file backend is append-only JSON-lines, so a
training run can stream to disk and a dashboard process can tail it.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class StatsStorageEvent:
    """Posted to registered listeners (reference StatsStorageEvent.java)."""

    NEW_SESSION = "new_session"
    NEW_WORKER = "new_worker"
    POST_STATIC = "post_static"
    POST_UPDATE = "post_update"

    def __init__(self, kind: str, session_id: str, worker_id: str,
                 timestamp: float):
        self.kind = kind
        self.session_id = session_id
        self.worker_id = worker_id
        self.timestamp = timestamp


class StatsStorage:
    """Abstract storage for training stats (reference StatsStorage.java SPI)."""

    def __init__(self):
        self._listeners: List[Callable[[StatsStorageEvent], None]] = []
        self._lock = threading.RLock()

    # ------------------------------------------------------------- write side
    def put_static_info(self, session_id: str, worker_id: str,
                        info: Dict[str, Any]) -> None:
        raise NotImplementedError

    def put_update(self, session_id: str, worker_id: str,
                   update: Dict[str, Any]) -> None:
        raise NotImplementedError

    # -------------------------------------------------------------- read side
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def list_worker_ids(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def get_static_info(self, session_id: str,
                        worker_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def get_updates(self, session_id: str, worker_id: str,
                    since_iteration: int = -1) -> List[Dict[str, Any]]:
        """All updates with iteration > since_iteration, ordered by iteration
        (reference getAllUpdatesAfter)."""
        raise NotImplementedError

    def get_latest_update(self, session_id: str,
                          worker_id: str) -> Optional[Dict[str, Any]]:
        ups = self.get_updates(session_id, worker_id)
        return ups[-1] if ups else None

    # -------------------------------------------------------------- listeners
    def register_listener(self, cb: Callable[[StatsStorageEvent], None]):
        self._listeners.append(cb)

    def _notify(self, kind: str, session_id: str, worker_id: str):
        ev = StatsStorageEvent(kind, session_id, worker_id, time.time())
        for cb in list(self._listeners):
            cb(ev)

    def close(self):
        pass


class InMemoryStatsStorage(StatsStorage):
    """Reference InMemoryStatsStorage.java — dict-backed, test/dev default."""

    def __init__(self):
        super().__init__()
        self._static: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._updates: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}

    def put_static_info(self, session_id, worker_id, info):
        with self._lock:
            new_session = not any(s == session_id for s, _ in self._static)
            self._static[(session_id, worker_id)] = dict(info)
        if new_session:
            self._notify(StatsStorageEvent.NEW_SESSION, session_id, worker_id)
        self._notify(StatsStorageEvent.POST_STATIC, session_id, worker_id)

    def put_update(self, session_id, worker_id, update):
        with self._lock:
            self._updates.setdefault((session_id, worker_id), []).append(dict(update))
        self._notify(StatsStorageEvent.POST_UPDATE, session_id, worker_id)

    def list_session_ids(self):
        with self._lock:
            keys = set(s for s, _ in self._static) | set(s for s, _ in self._updates)
        return sorted(keys)

    def list_worker_ids(self, session_id):
        with self._lock:
            keys = set(w for s, w in self._static if s == session_id)
            keys |= set(w for s, w in self._updates if s == session_id)
        return sorted(keys)

    def get_static_info(self, session_id, worker_id):
        with self._lock:
            return self._static.get((session_id, worker_id))

    def get_updates(self, session_id, worker_id, since_iteration=-1):
        with self._lock:
            ups = list(self._updates.get((session_id, worker_id), []))
        return [u for u in ups if u.get("iteration", 0) > since_iteration]


class FileStatsStorage(StatsStorage):
    """Append-only JSON-lines file storage (capability of the reference's
    MapDB-backed FileStatsStorage.java, in a tail-able text format).

    Each line: {"kind": "static"|"update", "session": .., "worker": ..,
    "data": {...}}. Reads re-scan the file, so an independent dashboard
    process sees a live training run's appends.
    """

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # touch so readers don't race a missing file
        if not os.path.exists(path):
            with open(path, "a"):
                pass

    def _append(self, rec: Dict[str, Any]):
        with self._lock:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")

    def _scan(self):
        with self._lock:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail write from a live run

    def put_static_info(self, session_id, worker_id, info):
        known = session_id in self.list_session_ids()
        self._append({"kind": "static", "session": session_id,
                      "worker": worker_id, "data": info})
        if not known:
            self._notify(StatsStorageEvent.NEW_SESSION, session_id, worker_id)
        self._notify(StatsStorageEvent.POST_STATIC, session_id, worker_id)

    def put_update(self, session_id, worker_id, update):
        self._append({"kind": "update", "session": session_id,
                      "worker": worker_id, "data": update})
        self._notify(StatsStorageEvent.POST_UPDATE, session_id, worker_id)

    def list_session_ids(self):
        return sorted({r["session"] for r in self._scan()})

    def list_worker_ids(self, session_id):
        return sorted({r["worker"] for r in self._scan()
                       if r["session"] == session_id})

    def get_static_info(self, session_id, worker_id):
        out = None
        for r in self._scan():
            if (r["kind"] == "static" and r["session"] == session_id
                    and r["worker"] == worker_id):
                out = r["data"]  # last write wins
        return out

    def get_updates(self, session_id, worker_id, since_iteration=-1):
        out = [r["data"] for r in self._scan()
               if (r["kind"] == "update" and r["session"] == session_id
                   and r["worker"] == worker_id)]
        return [u for u in out if u.get("iteration", 0) > since_iteration]


class SqliteStatsStorage(StatsStorage):
    """Indexed SQLite backend (reference ui/storage/sqlite/
    J7FileStatsStorage / the sqlite storage module): durable, queryable by
    (session, worker, iteration) with an index, safe for a separate
    dashboard process to read while a training run writes (WAL mode).
    Records are stored as JSON text — same dict records as every other
    backend."""

    def __init__(self, path: str):
        super().__init__()
        import sqlite3
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS static_info ("
                " session TEXT NOT NULL, worker TEXT NOT NULL,"
                " data TEXT NOT NULL, PRIMARY KEY (session, worker))")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS updates ("
                " session TEXT NOT NULL, worker TEXT NOT NULL,"
                " iteration INTEGER NOT NULL, data TEXT NOT NULL)")
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_updates"
                " ON updates (session, worker, iteration)")
            self._conn.commit()

    def put_static_info(self, session_id, worker_id, info):
        with self._lock:
            known = session_id in self.list_session_ids()
            self._conn.execute(
                "INSERT OR REPLACE INTO static_info VALUES (?, ?, ?)",
                (session_id, worker_id, json.dumps(info)))
            self._conn.commit()
        if not known:
            self._notify(StatsStorageEvent.NEW_SESSION, session_id, worker_id)
        self._notify(StatsStorageEvent.POST_STATIC, session_id, worker_id)

    def put_update(self, session_id, worker_id, update):
        with self._lock:
            self._conn.execute(
                "INSERT INTO updates VALUES (?, ?, ?, ?)",
                (session_id, worker_id, int(update.get("iteration", 0)),
                 json.dumps(update)))
            self._conn.commit()
        self._notify(StatsStorageEvent.POST_UPDATE, session_id, worker_id)

    def list_session_ids(self):
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT session FROM static_info "
                "UNION SELECT DISTINCT session FROM updates").fetchall()
        return sorted(r[0] for r in rows)

    def list_worker_ids(self, session_id):
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT worker FROM static_info WHERE session=? "
                "UNION SELECT DISTINCT worker FROM updates WHERE session=?",
                (session_id, session_id)).fetchall()
        return sorted(r[0] for r in rows)

    def get_static_info(self, session_id, worker_id):
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM static_info WHERE session=? AND worker=?",
                (session_id, worker_id)).fetchone()
        return json.loads(row[0]) if row else None

    def get_updates(self, session_id, worker_id, since_iteration=-1):
        with self._lock:
            rows = self._conn.execute(
                "SELECT data FROM updates WHERE session=? AND worker=? AND "
                "iteration>? ORDER BY iteration, rowid",
                (session_id, worker_id, since_iteration)).fetchall()
        return [json.loads(r[0]) for r in rows]

    def close(self):
        with self._lock:
            self._conn.close()


class RemoteStatsStorageRouter(StatsStorage):
    """Client-side router POSTing every record to a remote TrainingUIServer's
    /collect endpoint (reference core/api/storage/impl/
    RemoteUIStatsStorageRouter.java + the Play RemoteReceiverModule, which
    queues asynchronously with bounded retries). Writes are ASYNC: a
    background thread drains a bounded queue with per-record retries;
    transport failures never reach (or block) the training loop — dropped
    records are counted in ``dropped``. ``flush()`` waits for the queue to
    drain (tests / shutdown). Only the write half of the StatsStorage SPI is
    functional — reads go to the server's own storage."""

    def __init__(self, url: str, timeout: float = 10.0, queue_size: int = 256,
                 max_retries: int = 3, retry_delay: float = 0.2):
        super().__init__()
        import queue as _queue
        import threading as _threading
        self.url = url.rstrip("/") + "/collect"
        self.timeout = timeout
        self.max_retries = max_retries
        self.retry_delay = retry_delay
        self.dropped = 0
        self._q: "_queue.Queue" = _queue.Queue(maxsize=queue_size)
        self._worker = _threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def _drain(self):
        import time as _time
        while True:
            payload = self._q.get()
            ok = False
            for attempt in range(self.max_retries):
                try:
                    self._post(payload)
                    ok = True
                    break
                except Exception:
                    _time.sleep(self.retry_delay * (attempt + 1))
            if not ok:
                self.dropped += 1
            self._q.task_done()

    def _post(self, payload):
        import json as _json
        import urllib.request
        req = urllib.request.Request(
            self.url, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            r.read()

    def _enqueue(self, payload):
        import queue as _queue
        try:
            self._q.put_nowait(payload)
        except _queue.Full:
            self.dropped += 1        # back-pressure: drop, never block fit()

    def flush(self):
        self._q.join()

    def put_static_info(self, session_id, worker_id, info):
        self._enqueue({"kind": "static", "session_id": session_id,
                       "worker_id": worker_id, "data": info})

    def put_update(self, session_id, worker_id, update):
        self._enqueue({"kind": "update", "session_id": session_id,
                       "worker_id": worker_id, "data": update})

    def list_session_ids(self):
        return []

    def list_worker_ids(self, session_id):
        return []

    def get_static_info(self, session_id, worker_id):
        return None

    def get_updates(self, session_id, worker_id, since_iteration=-1):
        return []
