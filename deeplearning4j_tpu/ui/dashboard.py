"""Training dashboard: standalone HTML artifact + live stdlib HTTP server.

Reference: deeplearning4j-ui-parent/deeplearning4j-play (UIServer.getInstance()
.attach(statsStorage) serving the train overview: score chart, param/update
ratios, histograms, system tab). The capability is reproduced with zero
dependencies: the page is a single self-contained HTML file (inline JSON +
hand-rolled SVG charts), and `TrainingUIServer` serves a live re-rendered
copy from any StatsStorage with auto-refresh.
"""
from __future__ import annotations

import html
import http.server
import json
import math
import threading
from typing import List, Optional

from . import i18n
from .storage import StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>deeplearning4j_tpu — training</title>
{refresh}
<style>
 body {{ font-family: -apple-system, Segoe UI, Helvetica, Arial, sans-serif;
        margin: 24px; background: #fafafa; color: #1a1a1a; }}
 h1 {{ font-size: 20px; }} h2 {{ font-size: 15px; margin: 18px 0 6px; }}
 .card {{ background: #fff; border: 1px solid #e3e3e3; border-radius: 8px;
          padding: 12px 16px; margin-bottom: 16px; }}
 table {{ border-collapse: collapse; font-size: 13px; }}
 td, th {{ padding: 3px 10px; border-bottom: 1px solid #eee; text-align: left; }}
 svg text {{ font-size: 10px; fill: #666; }}
 .meta {{ color: #666; font-size: 12px; }}
</style></head><body>
<h1>{t_pagetitle} <span class="meta">{t_session} {session} · {t_worker} {worker}</span></h1>
{nav}
<div class="card"><h2>{t_model}</h2>{static_table}</div>
<div class="card"><h2>{t_score}</h2>{score_chart}</div>
<div class="card"><h2>{t_throughput}</h2>{speed_chart}</div>
<div class="card"><h2>{t_parammag}</h2>{param_chart}</div>
<div class="card"><h2>{t_ratio}</h2>{ratio_chart}</div>
{performance_card}
{telemetry_card}
{fleet_card}
{hist_cards}
{activation_cards}
{graph_card}
<script type="application/json" id="stats-data">{data_json}</script>
</body></html>
"""


def _svg_line_chart(series: List[tuple], width=720, height=220, logy=False):
    """series: [(label, [(x, y), ...])]. Delegates to the component DSL's
    ChartLine (ui/components.py) — one palette/scale/legend implementation
    for the whole package; non-finite points are dropped there."""
    from .components import ChartLine
    pts_all = [p for _, pts in series for p in pts]
    if not pts_all:
        return "<p class='meta'>no data yet</p>"
    if not any(p[1] is not None and math.isfinite(p[1]) for p in pts_all):
        return "<p class='meta'>no finite data</p>"
    chart = ChartLine(
        x=[[p[0] for p in pts] for _, pts in series],
        y=[[p[1] for p in pts] for _, pts in series],
        series_names=[label for label, _ in series],
        width=width, height=height)
    return chart.render()


def _svg_histogram(hist: dict, width=340, height=120):
    """hist: {counts, lo, hi}. Delegates to the DSL's ChartHistogram."""
    from .components import ChartHistogram
    counts = hist.get("counts", [])
    if not counts:
        return ""
    lo, hi = hist.get("lo", 0.0), hist.get("hi", 1.0)
    n = len(counts)
    w = (hi - lo) / n if n else 1.0
    return ChartHistogram(
        lower_bounds=[lo + i * w for i in range(n)],
        upper_bounds=[lo + (i + 1) * w for i in range(n)],
        y=[float(c) for c in counts], width=width, height=height).render()


def _render_telemetry_card(title: str) -> str:
    """Runtime-telemetry card from the process-wide telemetry registry
    (telemetry/): recompile count, prefetch stall, serving p99 and the
    rest of the counters/gauges/span histograms — rendered on the train
    overview so existing TrainingUIServer users see the new signals with
    zero code changes. Empty registry (or disabled telemetry) renders
    nothing."""
    from ..telemetry import get_registry
    snap = get_registry().snapshot()
    counters, gauges = snap["counters"], snap["gauges"]
    hists = snap["histograms"]
    if not (counters or gauges or hists):
        return ""
    # headline signals first: the ones the tentpoles name
    headline = []
    if "jax.compiles" in counters:
        headline.append(("XLA compiles", counters["jax.compiles"]))
    # SLO watchdog (telemetry/slo.py): breached objectives by name, plus
    # the lifetime breach count and the flight-recorder evidence trail
    breached = sorted(n[len("slo."):-len(".breached")]
                      for n, g in gauges.items()
                      if n.startswith("slo.") and n.endswith(".breached")
                      and g["value"])
    if breached:
        headline.append(("SLO BREACHED", ", ".join(breached)))
    if "slo.breaches" in counters:
        headline.append(("SLO breaches (lifetime)", counters["slo.breaches"]))
    if "flightrec.dumps" in counters:
        headline.append(("flight-recorder dumps",
                         counters["flightrec.dumps"]))
    if "training_watch.unhealthy" in counters:
        headline.append(("training unhealthy steps",
                         counters["training_watch.unhealthy"]))
    pw = hists.get("prefetch.wait_ms")
    if pw:
        headline.append(("prefetch stall p95 (ms)", round(pw["p95"], 3)))
    for name, h in sorted(hists.items()):
        if name.startswith("serving.") and name.endswith(".latency_ms"):
            model = name[len("serving."):-len(".latency_ms")]
            headline.append((f"serving p99 [{model}] (ms)",
                             round(h["p99"], 3)))
    # generation prefix-cache economics (ISSUE 14): the hit rate is the
    # headline — it is the prefill work the pool sharing saved
    for name, g in sorted(gauges.items()):
        if name.startswith("generation.") and \
                name.endswith(".prefix_hit_rate"):
            model = name[len("generation."):-len(".prefix_hit_rate")]
            headline.append((f"prefix-cache hit rate [{model}]",
                             round(g["value"], 4)))
    rows = "".join(
        f"<tr><th>{html.escape(str(k))}</th><td>{html.escape(str(v))}</td></tr>"
        for k, v in headline)
    rows += "".join(
        f"<tr><th>{html.escape(n)}</th><td>{v}</td></tr>"
        for n, v in sorted(counters.items()))
    rows += "".join(
        f"<tr><th>{html.escape(n)}</th><td>{round(g['value'], 4)}"
        f" <span class='meta'>(max {round(g['max'], 4)})</span></td></tr>"
        for n, g in sorted(gauges.items()))
    hrows = "".join(
        f"<tr><th>{html.escape(n)}</th><td>{round(h['p50'], 3)}</td>"
        f"<td>{round(h['p95'], 3)}</td><td>{round(h['p99'], 3)}</td>"
        f"<td>{h['count']}</td></tr>"
        for n, h in sorted(hists.items()))
    hist_table = (
        "<table><tr><th></th><th>p50</th><th>p95</th><th>p99</th>"
        "<th>count</th></tr>" + hrows + "</table>") if hrows else ""
    return (f"<div class='card'><h2>{title}</h2>"
            f"<table>{rows}</table>{hist_table}</div>")


def _render_fleet_card(title: str) -> str:
    """Fleet card from the gauges the FleetCollector publishes into the
    local registry (``fleet.replica.<rid>.*`` — per-replica prefix-cache
    hit rate, queue depth, decode-slot occupancy) plus the fleet SLO
    burn-rate gauges the collector-made watchdog writes (``slo.<name>.
    burn_rate.*``). No collector running (no such gauges) renders
    nothing — a single-process dashboard keeps its old page."""
    from ..telemetry import get_registry
    reg = get_registry()
    if not reg.enabled:
        return ""
    prefix = "fleet.replica."
    per: dict = {}
    for name, g in reg.gauges_matching(prefix):
        rest = name[len(prefix):]
        rid, _, metric = rest.partition(".")
        if rid and metric:
            per.setdefault(rid, {})[metric] = g.value
    if not per:
        return ""
    rows = "".join(
        f"<tr><td>{html.escape(rid)}</td>"
        f"<td>{round(m_.get('prefix_hit_rate', 0.0), 4)}</td>"
        f"<td>{round(m_.get('queue_depth', 0.0), 1)}</td>"
        f"<td>{round(m_.get('slot_occupancy', 0.0), 4)}</td></tr>"
        for rid, m_ in sorted(per.items()))
    table = ("<table><tr><th>replica</th><th>prefix hit</th>"
             "<th>queue</th><th>occupancy</th></tr>" + rows + "</table>")
    burn_rows = "".join(
        f"<tr><th>{html.escape(name[len('slo.'):])}</th>"
        f"<td>{round(g.value, 3)}</td></tr>"
        for name, g in sorted(reg.gauges_matching("slo.")
                              ) if ".burn_rate." in name)
    burn_table = (f"<table>{burn_rows}</table>" if burn_rows else "")
    return (f"<div class='card'><h2>{title}</h2>{table}{burn_table}</div>")


def _render_kernels_table(reg, snap, heading: str) -> str:
    """Per-kernel rows for the Performance card (ISSUE 17): which impl is
    live (fused / interpret / fallback), the block choice actually in use
    (an autotuned decision when one is cached for this rig, else the
    hand-tuned default), and measured-vs-roofline from the
    ``perf.kernels.<name>.*`` gauges — below-bound kernels flagged."""
    kernels = snap.get("kernels") or {}
    if not kernels:
        return ""

    def _g(name):
        g = reg.gauge_if_exists(name)
        return g.value if g is not None else None

    rows = []
    for name in sorted(kernels):
        k = kernels[name]
        choice = k.get("default_choice")
        src = "default"
        for rec in (k.get("autotune") or {}).values():
            if rec.get("choice"):
                choice, src = rec["choice"], "autotuned"
                break
        blocks = ("x".join(str(v) for v in choice) if choice else "-") \
            + (f" ({src})" if choice else "")
        base = f"perf.kernels.{name}"
        ratio = _g(f"{base}.vs_roofline")
        below = _g(f"{base}.below_roofline")
        if ratio:
            vs = f"{ratio:.2f}x bound"
            if below:
                vs += " &#9888;"          # below-roofline warning sign
        else:
            vs = "-"
        impl = k.get("impl", "?")
        if not k.get("enabled", True):
            impl += " (killed)"
        rows.append(f"<tr><td>{html.escape(name)}</td>"
                    f"<td>{html.escape(impl)}</td>"
                    f"<td>{html.escape(blocks)}</td>"
                    f"<td>{vs}</td></tr>")
    return (f"<h3>{heading}</h3>"
            "<table><tr><th>kernel</th><th>impl</th><th>blocks</th>"
            "<th>vs roofline</th></tr>" + "".join(rows) + "</table>")


def _render_performance_card(title: str, kernels_heading: str = "Kernels") -> str:
    """Performance-observability card (telemetry/perf.py + memprof.py):
    per-program MFU/roofline rows from the cost index, the step-time
    decomposition, the live-memory top-K and — when BENCH_r*.json files
    are present in the working directory — the baseline-delta headline.
    Empty cost index AND empty decomposition renders nothing (a training
    run that predates the perf layer keeps its old page)."""
    from ..telemetry import get_registry
    from ..telemetry.perf import (PerfBaseline, baseline_deltas,
                                  get_cost_index, perf_snapshot)
    reg = get_registry()
    if not reg.enabled:
        return ""
    snap = perf_snapshot(reg, get_cost_index())
    programs = snap.get("programs") or []
    decomp = snap.get("step_decomposition") or {}
    if not programs and not decomp:
        return ""
    # headline: the best live MFU + a baseline delta when one is known
    headline = []
    with_mfu = [r for r in programs if r.get("mfu") is not None]
    if with_mfu:
        best = max(with_mfu, key=lambda r: r["mfu"])
        headline.append(("best MFU",
                         f"{best['mfu']:.2%} ({html.escape(best['path'])},"
                         f" {best['roofline']}-bound)"))
    try:
        baseline = PerfBaseline.load_trajectory(".")
        for d in baseline_deltas(baseline, reg):
            if d.get("ratio"):
                headline.append(
                    (f"vs baseline [{html.escape(d['row'])}]",
                     f"{d['ratio']:.2f}x of {html.escape(str(d['baseline_file']))}"))
    except Exception:           # pragma: no cover - defensive
        pass
    hrows = "".join(
        f"<tr><th>{k}</th><td>{v}</td></tr>" for k, v in headline)
    def _cell(v, pct=False):
        if v is None:
            return "-"
        return f"{v:.2%}" if pct else str(round(v, 4))

    prog_rows = "".join(
        f"<tr><td>{html.escape(str(r['path']))}</td>"
        f"<td>{r['roofline']}</td>"
        f"<td>{_cell(r['step_ms'])}</td>"
        f"<td>{_cell(r['achieved_tflops'])}</td>"
        f"<td>{_cell(r['mfu'], pct=True)}</td></tr>"
        for r in programs)
    prog_table = ("<table><tr><th>program</th><th>bound</th>"
                  "<th>step ms</th><th>TFLOP/s</th><th>MFU</th></tr>"
                  + prog_rows + "</table>") if programs else ""
    drows = "".join(
        f"<tr><th>{html.escape(k)}</th><td>{v['p50']}</td>"
        f"<td>{v['p95']}</td><td>{v['mean']}</td></tr>"
        for k, v in decomp.items() if isinstance(v, dict) and "p50" in v)
    decomp_table = ("<table><tr><th></th><th>p50 ms</th><th>p95 ms</th>"
                    "<th>mean ms</th></tr>" + drows + "</table>") \
        if drows else ""
    mem = snap.get("memory") or {}
    mrows = "".join(
        f"<tr><td>{html.escape('x'.join(str(d) for d in g['shape']) or '()')}"
        f"</td><td>{html.escape(g['dtype'])}</td>"
        f"<td>{html.escape(str(g['owner']))}</td><td>{g['count']}</td>"
        f"<td>{g['total_bytes']}</td></tr>"
        for g in (mem.get("top") or [])[:8])
    mem_table = ("<table><tr><th>shape</th><th>dtype</th><th>owner</th>"
                 "<th>count</th><th>bytes</th></tr>" + mrows + "</table>") \
        if mrows else ""
    kern_table = _render_kernels_table(reg, snap, kernels_heading)
    return (f"<div class='card'><h2>{title}</h2>"
            f"<table>{hrows}</table>{prog_table}{kern_table}"
            f"{decomp_table}{mem_table}</div>")


def render_dashboard_html(storage: StatsStorage, session_id: Optional[str] = None,
                          worker_id: Optional[str] = None,
                          auto_refresh_sec: int = 0,
                          lang: Optional[str] = None) -> str:
    """One overview page. Multi-session: a nav bar links every session id
    (and each session's workers) via ?session=&worker=; ``lang`` renders
    all chrome through ui/i18n (reference TrainModule.java:94-110 serves
    the same via DefaultI18N + per-language resources)."""
    def m(key):
        return i18n.get_message(key, lang)

    sessions = storage.list_session_ids()
    if session_id is None:
        session_id = sessions[-1] if sessions else ""
    workers = storage.list_worker_ids(session_id) if session_id else []
    if worker_id is None:
        worker_id = workers[0] if workers else ""
    static = storage.get_static_info(session_id, worker_id) or {}
    updates = storage.get_updates(session_id, worker_id)

    rows = "".join(f"<tr><th>{html.escape(str(k))}</th>"
                   f"<td>{html.escape(str(v))}</td></tr>"
                   for k, v in static.items() if k != "param_names")
    static_table = f"<table>{rows}</table>" if rows else "<p class='meta'>–</p>"

    score_pts = [(u["iteration"], u.get("score")) for u in updates
                 if "score" in u]
    speed_pts = [(u["iteration"], u.get("iterations_per_sec")) for u in updates
                 if "iterations_per_sec" in u]
    # per-param mean-magnitude series
    pnames = sorted({n for u in updates for n in u.get("params", {})})
    param_series = [(n, [(u["iteration"], u["params"][n]["meanmag"])
                         for u in updates if n in u.get("params", {})])
                    for n in pnames[:10]]
    ratio_series = []
    for n in pnames[:10]:
        pts = []
        for u in updates:
            if n in u.get("params", {}) and n in u.get("updates", {}):
                pm = u["params"][n]["meanmag"]
                um = u["updates"][n]["meanmag"]
                if pm > 0 and um > 0:
                    pts.append((u["iteration"], math.log10(um / pm)))
        if pts:
            ratio_series.append((n, pts))

    hist_cards = ""
    last_with_hist = next((u for u in reversed(updates)
                           if any("histogram" in d
                                  for d in u.get("params", {}).values())), None)
    if last_with_hist:
        cells = []
        for n, d in list(last_with_hist["params"].items())[:12]:
            if "histogram" in d:
                cells.append(f"<div style='display:inline-block;margin:4px'>"
                             f"<div class='meta'>{n}</div>"
                             f"{_svg_histogram(d['histogram'])}</div>")
        hist_cards = (f"<div class='card'><h2>{m('train.histograms')} "
                      f"(iteration {last_with_hist['iteration']})</h2>"
                      + "".join(cells) + "</div>")

    # conv-activation image grids (reference ConvolutionalIterationListener;
    # posted by ui/visual.ConvolutionalIterationListener as base64 PNGs)
    activation_cards = ""
    last_with_acts = next((u for u in reversed(updates)
                           if u.get("conv_activations")), None)
    if last_with_acts:
        cells = "".join(
            f"<div style='display:inline-block;margin:6px;vertical-align:top'>"
            f"<div class='meta'>{html.escape(str(n))}</div>"
            f"<img src='data:image/png;base64,{b64}' "
            f"style='image-rendering:pixelated;border:1px solid #ddd'/></div>"
            for n, b64 in last_with_acts["conv_activations"].items())
        activation_cards = (
            f"<div class='card'><h2>{m('train.activations')} (iteration "
            f"{last_with_acts['iteration']})</h2>{cells}</div>")

    # model-graph view (reference FlowIterationListener / TrainModule model
    # tab) — rendered from the config JSON the StatsListener posts
    graph_card = ""
    cfg_json = static.get("model_config_json")
    if cfg_json:
        try:
            from ..nn.conf import serde
            from .visual import render_model_graph_svg
            svg = render_model_graph_svg(serde.from_json(cfg_json))
            graph_card = (f"<div class='card'><h2>{m('train.graph')}</h2>"
                          f"<div style='overflow-x:auto'>{svg}</div></div>")
        except (KeyError, ValueError, TypeError) as e:
            graph_card = (f"<div class='card'><h2>{m('train.graph')}</h2>"
                          f"<p class='meta'>unrenderable: "
                          f"{html.escape(str(e))}</p></div>")

    refresh = (f'<meta http-equiv="refresh" content="{auto_refresh_sec}">'
               if auto_refresh_sec else "")

    # multi-session nav: every session (workers of the current one) plus a
    # language switcher — the TrainModule session-selection capability
    from urllib.parse import urlencode

    def _link(label, q, current):
        style = "font-weight:bold" if current else ""
        return (f"<a style='{style}' href='?{urlencode(q)}'>"
                f"{html.escape(str(label))}</a>")

    def _q(sid, wid=None, lg=None):
        q = {"session": sid}
        if wid:
            q["worker"] = wid
        if lg or lang:
            q["lang"] = lg or lang
        return q

    nav = ""
    if sessions:
        sess_links = " · ".join(
            _link(s_, _q(s_), s_ == session_id) for s_ in sessions)
        worker_links = " · ".join(
            _link(w, _q(session_id, w), w == worker_id) for w in workers)
        lang_links = " · ".join(
            _link(lg, _q(session_id, worker_id, lg), lg == (lang or "en"))
            for lg in i18n.languages())
        nav = (f"<div class='card meta'><b>{m('train.sessions')}:</b> "
               f"{sess_links}"
               + (f" &nbsp;|&nbsp; <b>{m('train.worker')}:</b> {worker_links}"
                  if len(workers) > 1 else "")
               + f" &nbsp;|&nbsp; <b>{m('train.language')}:</b> {lang_links}"
               "</div>")

    return _PAGE.format(
        refresh=refresh, session=html.escape(session_id or "–", quote=True),
        worker=html.escape(worker_id or "–", quote=True),
        nav=nav,
        t_pagetitle=m("train.pagetitle"), t_session=m("train.session"),
        t_worker=m("train.worker"), t_model=m("train.model"),
        t_score=m("train.score"), t_throughput=m("train.throughput"),
        t_parammag=m("train.parammag"), t_ratio=m("train.ratio"),
        static_table=static_table,
        score_chart=_svg_line_chart([("score", score_pts)]),
        speed_chart=_svg_line_chart([("it/s", speed_pts)]),
        param_chart=_svg_line_chart(param_series),
        ratio_chart=_svg_line_chart(ratio_series),
        performance_card=_render_performance_card(
            m("train.performance"), kernels_heading=m("train.kernels")),
        telemetry_card=_render_telemetry_card(m("train.telemetry")),
        fleet_card=_render_fleet_card(m("train.fleet")),
        hist_cards=hist_cards,
        activation_cards=activation_cards,
        graph_card=graph_card,
        data_json=json.dumps({"session": session_id, "worker": worker_id,
                              "n_updates": len(updates)}),
    )


def render_dashboard(storage: StatsStorage, path: str,
                     session_id: Optional[str] = None,
                     worker_id: Optional[str] = None) -> str:
    """Write the dashboard artifact to `path`; returns the path."""
    html = render_dashboard_html(storage, session_id, worker_id)
    with open(path, "w") as f:
        f.write(html)
    return path


class TrainingUIServer:
    """Live dashboard over a StatsStorage (reference UIServer.getInstance();
    play framework replaced by the stdlib ThreadingHTTPServer — the page is
    re-rendered per request and auto-refreshes).
    """

    _instance = None

    @classmethod
    def get_instance(cls) -> "TrainingUIServer":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self, port: int = 0):
        self._storages: List[StatsStorage] = []
        self._port = port
        self._httpd = None
        self._thread = None

    def attach(self, storage: StatsStorage):
        self._storages.append(storage)
        return self

    def detach(self, storage: StatsStorage):
        self._storages.remove(storage)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> int:
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                if not server._storages:
                    body = b"<html><body>no storage attached</body></html>"
                else:
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    sid = q.get("session", [None])[0]
                    wid = q.get("worker", [None])[0]
                    lng = q.get("lang", [None])[0]
                    body = render_dashboard_html(
                        server._storages[-1], sid, wid,
                        auto_refresh_sec=5, lang=lng).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 — remote stats receiver
                # reference RemoteReceiverModule: other processes POST their
                # stats records here (RemoteUIStatsStorageRouter client side
                # is RemoteStatsStorageRouter in ui/storage.py)
                if self.path != "/collect" or not server._storages:
                    self.send_error(404)
                    return
                from ..util.httpjson import read_json, write_json
                try:
                    rec = read_json(self)
                    store = server._storages[-1]
                    if rec.get("kind") == "static":
                        store.put_static_info(rec["session_id"],
                                              rec["worker_id"], rec["data"])
                    else:
                        store.put_update(rec["session_id"], rec["worker_id"],
                                         rec["data"])
                    write_json(self, 200, {"ok": True})
                except Exception as e:
                    write_json(self, 400, {"error": str(e)})

            def log_message(self, *a):  # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", self._port),
                                                      Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if TrainingUIServer._instance is self:
            TrainingUIServer._instance = None
