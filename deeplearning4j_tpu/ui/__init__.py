"""Observability tier: stats collection, storage, dashboard (reference
deeplearning4j-ui-parent)."""
from .dashboard import TrainingUIServer, render_dashboard, render_dashboard_html
from .stats import StatsListener, StatsUpdateConfiguration
from .storage import (FileStatsStorage, InMemoryStatsStorage, StatsStorage,
                      StatsStorageEvent)

__all__ = [
    "StatsListener", "StatsUpdateConfiguration", "StatsStorage",
    "InMemoryStatsStorage", "FileStatsStorage", "StatsStorageEvent",
    "render_dashboard", "render_dashboard_html", "TrainingUIServer",
]
