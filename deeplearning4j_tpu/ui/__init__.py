"""Observability tier: stats collection, storage, dashboard (reference
deeplearning4j-ui-parent)."""
from .components import (ChartHistogram, ChartHorizontalBar, ChartLine,
                         ChartScatter, ChartStackedArea, ChartTimeline,
                         Component, ComponentDiv, ComponentTable,
                         ComponentText, DecoratorAccordion, render_html,
                         training_report)
from .components import from_json as component_from_json
from .dashboard import TrainingUIServer, render_dashboard, render_dashboard_html
from .stats import StatsListener, StatsUpdateConfiguration
from .storage import (FileStatsStorage, InMemoryStatsStorage,
                      SqliteStatsStorage, StatsStorage,
                      StatsStorageEvent)
from .visual import (ConvolutionalIterationListener, activation_grid_png,
                     render_model_graph, render_model_graph_svg,
                     render_tsne, render_tsne_page)

__all__ = [
    "StatsListener", "StatsUpdateConfiguration", "StatsStorage",
    "InMemoryStatsStorage", "FileStatsStorage", "SqliteStatsStorage",
    "StatsStorageEvent",
    "render_dashboard", "render_dashboard_html", "TrainingUIServer",
    "ConvolutionalIterationListener", "activation_grid_png",
    "render_model_graph", "render_model_graph_svg", "render_tsne",
    "render_tsne_page",
    "Component", "ComponentDiv", "ComponentTable", "ComponentText",
    "ChartLine", "ChartScatter", "ChartHistogram", "ChartHorizontalBar",
    "ChartStackedArea", "ChartTimeline", "DecoratorAccordion",
    "render_html", "component_from_json", "training_report",
]
