"""Train-UI internationalization.

Reference: deeplearning4j-ui-parent/deeplearning4j-play
.../i18n/DefaultI18N.java + the per-language ``dl4j_i18n/*.properties``
resources that TrainModule serves (TrainModule.java:94-110 renders every
page element through I18N.getMessage). Same contract here: a key/value
message table per language, English fallback for missing keys, and a
process-wide default language the dashboard uses when the request doesn't
pick one (``?lang=``).

The reference ships en/de/ja/ko/ru/zh; the same six are provided for every
string the dashboard renders.
"""
from __future__ import annotations

from typing import Dict

_EN = {
    "train.pagetitle": "Training overview",
    "train.session": "session",
    "train.worker": "worker",
    "train.sessions": "Sessions",
    "train.language": "Language",
    "train.model": "Model",
    "train.score": "Score vs. iteration",
    "train.throughput": "Throughput (iterations/sec)",
    "train.parammag": "Mean magnitudes: parameters",
    "train.ratio": "Update : parameter ratio (log10)",
    "train.histograms": "Parameter histograms",
    "train.activations": "Convolutional activations",
    "train.graph": "Model graph",
    "train.nodata": "no data yet",
    "train.telemetry": "Runtime telemetry",
    "train.performance": "Performance (MFU / roofline / memory)",
    "train.kernels": "Kernels (impl / blocks / roofline)",
    "train.fleet": "Serving fleet (replicas / SLO burn)",
}

_MESSAGES: Dict[str, Dict[str, str]] = {
    "en": _EN,
    "de": {
        "train.pagetitle": "Trainingsübersicht",
        "train.session": "Sitzung",
        "train.worker": "Worker",
        "train.sessions": "Sitzungen",
        "train.language": "Sprache",
        "train.model": "Modell",
        "train.score": "Score pro Iteration",
        "train.throughput": "Durchsatz (Iterationen/Sek.)",
        "train.parammag": "Mittlere Beträge: Parameter",
        "train.ratio": "Update-zu-Parameter-Verhältnis (log10)",
        "train.histograms": "Parameter-Histogramme",
        "train.activations": "Konvolutions-Aktivierungen",
        "train.graph": "Modellgraph",
        "train.nodata": "noch keine Daten",
        "train.telemetry": "Laufzeit-Telemetrie",
        "train.performance": "Leistung (MFU / Roofline / Speicher)",
        "train.kernels": "Kernel (Implementierung / Blöcke / Roofline)",
        "train.fleet": "Serving-Flotte (Replikate / SLO-Burn)",
    },
    "ja": {
        "train.pagetitle": "トレーニング概要",
        "train.session": "セッション",
        "train.worker": "ワーカー",
        "train.sessions": "セッション一覧",
        "train.language": "言語",
        "train.model": "モデル",
        "train.score": "スコア対イテレーション",
        "train.throughput": "スループット（イテレーション/秒）",
        "train.parammag": "パラメータの平均絶対値",
        "train.ratio": "更新とパラメータの比率 (log10)",
        "train.histograms": "パラメータのヒストグラム",
        "train.activations": "畳み込み活性化",
        "train.graph": "モデルグラフ",
        "train.nodata": "データなし",
        "train.telemetry": "ランタイムテレメトリ",
        "train.performance": "パフォーマンス（MFU / ルーフライン / メモリ）",
        "train.kernels": "カーネル（実装 / ブロック / ルーフライン）",
        "train.fleet": "サービングフリート（レプリカ / SLOバーン）",
    },
    "ko": {
        "train.pagetitle": "훈련 개요",
        "train.session": "세션",
        "train.worker": "워커",
        "train.sessions": "세션 목록",
        "train.language": "언어",
        "train.model": "모델",
        "train.score": "반복별 스코어",
        "train.throughput": "처리량 (반복/초)",
        "train.parammag": "파라미터 평균 크기",
        "train.ratio": "업데이트 대 파라미터 비율 (log10)",
        "train.histograms": "파라미터 히스토그램",
        "train.activations": "합성곱 활성화",
        "train.graph": "모델 그래프",
        "train.nodata": "데이터 없음",
        "train.telemetry": "런타임 텔레메트리",
        "train.performance": "성능 (MFU / 루프라인 / 메모리)",
        "train.kernels": "커널 (구현 / 블록 / 루프라인)",
        "train.fleet": "서빙 플릿 (레플리카 / SLO 번)",
    },
    "ru": {
        "train.pagetitle": "Обзор обучения",
        "train.session": "сессия",
        "train.worker": "воркер",
        "train.sessions": "Сессии",
        "train.language": "Язык",
        "train.model": "Модель",
        "train.score": "Ошибка по итерациям",
        "train.throughput": "Производительность (итераций/с)",
        "train.parammag": "Средние модули: параметры",
        "train.ratio": "Отношение обновления к параметру (log10)",
        "train.histograms": "Гистограммы параметров",
        "train.activations": "Свёрточные активации",
        "train.graph": "Граф модели",
        "train.nodata": "данных пока нет",
        "train.telemetry": "Телеметрия выполнения",
        "train.performance": "Производительность (MFU / roofline / память)",
        "train.kernels": "Ядра (реализация / блоки / roofline)",
        "train.fleet": "Флот обслуживания (реплики / расход SLO)",
    },
    "zh": {
        "train.pagetitle": "训练概览",
        "train.session": "会话",
        "train.worker": "工作节点",
        "train.sessions": "会话列表",
        "train.language": "语言",
        "train.model": "模型",
        "train.score": "得分随迭代变化",
        "train.throughput": "吞吐量（迭代/秒）",
        "train.parammag": "参数平均幅值",
        "train.ratio": "更新与参数比值 (log10)",
        "train.histograms": "参数直方图",
        "train.activations": "卷积激活",
        "train.graph": "模型图",
        "train.nodata": "暂无数据",
        "train.telemetry": "运行时遥测",
        "train.performance": "性能（MFU / 屋顶线 / 内存）",
        "train.kernels": "内核（实现 / 块 / 屋顶线）",
        "train.fleet": "服务集群（副本 / SLO 消耗）",
    },
}

_DEFAULT = "en"


def languages():
    """Supported language codes (the reference's six)."""
    return sorted(_MESSAGES)


def set_default_language(lang: str):
    """DefaultI18N.setDefaultLanguage equivalent."""
    global _DEFAULT
    if lang not in _MESSAGES:
        raise ValueError(f"unsupported language {lang!r}; "
                         f"available: {languages()}")
    _DEFAULT = lang


def get_message(key: str, lang: str = None) -> str:
    """DefaultI18N.getMessage: requested language, English fallback, key
    itself as the last resort (the reference renders the raw key too)."""
    table = _MESSAGES.get(lang or _DEFAULT, _EN)
    return table.get(key) or _EN.get(key) or key
