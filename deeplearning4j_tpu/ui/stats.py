"""StatsListener: per-iteration training statistics -> StatsStorage.

Reference: deeplearning4j-ui-parent/deeplearning4j-ui-model/src/main/java/org/
deeplearning4j/ui/stats/BaseStatsListener.java:234-406 (iterationDone collects
score, timings, memory, param/update/activation stats + histograms keyed by
sessionID/typeID/workerID) configured via StatsUpdateConfiguration.

TPU-first reshape: all tensor statistics for a report are computed ON DEVICE
in one jitted program over the whole param pytree (mean/stdev/mean-magnitude/
min/max/histogram per named leaf) and fetched with a single host transfer —
the reference's per-array host loops would serialize against the TPU stream.
Update stats are the param delta since the previous report (normalized per
iteration); the jitted train step donates its input buffers, so a cheap
on-device snapshot is taken at each report boundary.
"""
from __future__ import annotations

import os
import socket
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..optimize.listeners import TrainingListener
from .storage import InMemoryStatsStorage, StatsStorage


@dataclass
class StatsUpdateConfiguration:
    """What to collect, how often (reference StatsUpdateConfiguration /
    DefaultStatsUpdateConfiguration)."""
    report_frequency: int = 1
    collect_score: bool = True
    collect_timing: bool = True
    collect_memory: bool = True
    collect_param_stats: bool = True
    collect_update_stats: bool = True
    collect_activation_stats: bool = False
    collect_histograms: bool = False
    histogram_bins: int = 20
    collect_learning_rates: bool = True


def _named_leaves(params) -> List[Any]:
    """Flatten a param pytree into [(name, leaf)] with stable readable names
    (e.g. '0/W', 'conv1/b')."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            elif hasattr(p, "name"):
                parts.append(str(p.name))
            else:
                parts.append(str(p))
        out.append(("/".join(parts), leaf))
    return out


class StatsListener(TrainingListener):
    """Collects training stats into a StatsStorage every `report_frequency`
    iterations. Attach with `net.set_listeners(StatsListener(storage))`, then
    render with `deeplearning4j_tpu.ui.render_dashboard(storage, path=...)`
    or serve live with `TrainingUIServer`.
    """

    def __init__(self, storage: Optional[StatsStorage] = None,
                 config: Optional[StatsUpdateConfiguration] = None,
                 session_id: Optional[str] = None,
                 worker_id: str = "worker_0",
                 activation_sample=None):
        self.storage = storage if storage is not None else InMemoryStatsStorage()
        self.config = config or StatsUpdateConfiguration()
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id
        # Optional sample batch: when collect_activation_stats is on, a jitted
        # forward over this batch yields per-layer activation mean-magnitudes.
        # (The training pass itself is one fused XLA program; its
        # intermediates are not observable without re-running the forward.)
        self.activation_sample = activation_sample
        self._static_posted = False
        self._stats_fn = None
        self._act_fn = None
        self._upd_fn = None
        self._prev_snapshot = None
        self._prev_snapshot_iter = None
        self._last_report_time = None
        self._iters_since_report = 0

    # ---------------------------------------------------------------- helpers
    def _build_stats_fn(self, params):
        bins = self.config.histogram_bins
        with_hist = self.config.collect_histograms

        def stats(p):
            out = {}
            for name, leaf in _named_leaves(p):
                x = leaf.astype(jnp.float32).reshape(-1)
                d = {"mean": jnp.mean(x), "stdev": jnp.std(x),
                     "meanmag": jnp.mean(jnp.abs(x)),
                     "min": jnp.min(x), "max": jnp.max(x)}
                if with_hist:
                    counts, edges = jnp.histogram(x, bins=bins)
                    d["hist_counts"] = counts
                    d["hist_lo"] = edges[0]
                    d["hist_hi"] = edges[-1]
                out[name] = d
            return out

        return jax.jit(stats)

    def _param_stats(self, params) -> Dict[str, Dict[str, Any]]:
        if self._stats_fn is None:
            self._stats_fn = self._build_stats_fn(params)
        dev = self._stats_fn(params)
        host = jax.device_get(dev)
        out = {}
        for name, d in host.items():
            rec = {k: float(v) for k, v in d.items() if not k.startswith("hist")}
            if "hist_counts" in d:
                rec["histogram"] = {"counts": np.asarray(d["hist_counts"]).tolist(),
                                    "lo": float(d["hist_lo"]),
                                    "hi": float(d["hist_hi"])}
            out[name] = rec
        return out

    def _update_stats(self, params, iteration) -> Optional[Dict[str, Any]]:
        """Mean-magnitude of (params - snapshot)/iters since the last report —
        the per-iteration update scale the reference reports from updater
        output (BaseStatsListener.java:383-394)."""
        if self._prev_snapshot is None:
            return None
        iters = max(iteration - self._prev_snapshot_iter, 1)

        if self._upd_fn is None:
            # Built once and cached; ``iters`` is a traced argument so the
            # compiled program is reused across reports (a fresh closure per
            # report would force an XLA recompile every iteration).
            def upd(p, prev, n_iters):
                out = {}
                named_now = _named_leaves(p)
                named_prev = dict(_named_leaves(prev))
                for name, leaf in named_now:
                    d = (leaf.astype(jnp.float32) - named_prev[name].astype(jnp.float32))
                    d = d.reshape(-1) / n_iters
                    out[name] = {"meanmag": jnp.mean(jnp.abs(d)),
                                 "mean": jnp.mean(d), "stdev": jnp.std(d)}
                return out
            self._upd_fn = jax.jit(upd)

        host = jax.device_get(self._upd_fn(params, self._prev_snapshot,
                                           jnp.float32(iters)))
        return {n: {k: float(v) for k, v in d.items()} for n, d in host.items()}

    def _snapshot(self, params):
        # Copy so the solver's buffer donation can't invalidate the snapshot.
        self._prev_snapshot = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), params)

    def _activation_stats(self, model) -> Optional[Dict[str, Any]]:
        x = self.activation_sample
        if x is None or not hasattr(model, "feed_forward"):
            return None
        if self._act_fn is None:
            def act(params, state, xx):
                acts, _ = model.apply_fn(params, state, xx, train=False)
                return [jnp.mean(jnp.abs(a.astype(jnp.float32))) for a in acts]
            self._act_fn = jax.jit(act)
        try:
            mags = jax.device_get(self._act_fn(model.params, model.state,
                                               jnp.asarray(x)))
        except TypeError:  # model without (params, state, x) apply signature
            return None
        return {f"layer_{i}": float(m) for i, m in enumerate(mags)}

    @staticmethod
    def _memory_stats() -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        try:
            import resource
        except ImportError:   # non-POSIX platform
            pass
        else:
            out["host_rss_mb"] = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024.0
        try:
            ms = jax.local_devices()[0].memory_stats()
        except (AttributeError, NotImplementedError, RuntimeError):
            # backends without PJRT memory stats (e.g. CPU) either raise or
            # have no memory_stats(); anything else is a real bug — surface it
            ms = None
        if ms:
            out["device_bytes_in_use"] = int(ms.get("bytes_in_use", 0))
            out["device_bytes_limit"] = int(ms.get("bytes_limit", 0))
        return out

    def _post_static(self, model):
        dev = jax.devices()
        info = {
            "model_class": type(model).__name__,
            "num_params": int(getattr(model, "num_params", lambda: 0)()),
            "backend": dev[0].platform if dev else "unknown",
            "device_kind": getattr(dev[0], "device_kind", "?") if dev else "?",
            "device_count": len(dev),
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "start_time": time.time(),
            "param_names": [n for n, _ in _named_leaves(model.params)],
        }
        # config JSON powers the dashboard's model-graph view (reference
        # TrainModule model tab renders from the stored config)
        conf = getattr(model, "conf", None)
        if conf is not None and hasattr(conf, "to_json"):
            info["model_config_json"] = conf.to_json()
        self.storage.put_static_info(self.session_id, self.worker_id, info)
        self._static_posted = True

    # ----------------------------------------------------------- listener API
    def iteration_done(self, model, iteration: int, score):
        if not self._static_posted:
            self._post_static(model)
        self._iters_since_report += 1
        if iteration % self.config.report_frequency != 0:
            return
        now = time.time()
        update: Dict[str, Any] = {"iteration": int(iteration), "timestamp": now}
        if self.config.collect_score:
            update["score"] = float(score)
        if self.config.collect_timing and self._last_report_time is not None:
            dt = max(now - self._last_report_time, 1e-9)
            update["iterations_per_sec"] = self._iters_since_report / dt
            update["ms_per_iteration"] = 1000.0 * dt / self._iters_since_report
        if self.config.collect_memory:
            update["memory"] = self._memory_stats()
        if self.config.collect_param_stats:
            update["params"] = self._param_stats(model.params)
        if self.config.collect_update_stats:
            us = self._update_stats(model.params, iteration)
            if us is not None:
                update["updates"] = us
            self._snapshot(model.params)
            self._prev_snapshot_iter = iteration
        if self.config.collect_activation_stats:
            acts = self._activation_stats(model)
            if acts is not None:
                update["activations"] = acts
        if self.config.collect_learning_rates:
            upd = getattr(model, "updater", None)
            if upd is not None and hasattr(upd, "layer_confs"):
                lrs = {}
                for i, c in enumerate(upd.layer_confs):
                    rule = upd.rule_for(c)
                    # rules without a schedule surface (e.g. NoOp) are skipped;
                    # a broken schedule raising inside lr() must propagate
                    if hasattr(rule, "lr"):
                        lrs[str(i)] = float(rule.lr(iteration))
                if lrs:
                    update["learning_rates"] = lrs
        self.storage.put_update(self.session_id, self.worker_id, update)
        self._last_report_time = now
        self._iters_since_report = 0
