"""Report-component DSL: a JSON-serializable chart/table/text tree.

Reference: deeplearning4j-ui-components — Component.java subtypes tagged by
``componentType`` and rendered by the UI (chart/ChartLine.java,
ChartScatter.java, ChartHistogram.java, ChartHorizontalBar.java,
ChartStackedArea.java, ChartTimeline.java, table/ComponentTable.java,
text/ComponentText.java, component/ComponentDiv.java,
decorator/DecoratorAccordion.java). The reference renders these client-side
(dl4j-ui.js); here ``render_html`` produces self-contained SVG/HTML
server-side — no JS dependency — and the dashboard serves assembled pages.

Build a tree, serialize with ``to_json`` (type-tagged, round-trips through
``from_json``), render with ``render_html``:

    page = ComponentDiv(components=[
        ComponentText("Training report", size=18),
        ChartLine(title="score", x=[steps], y=[scores], series_names=["loss"]),
        ComponentTable(header=["metric", "value"], content=[["acc", "0.97"]]),
    ])
    html = render_html(page)
"""
from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Type

_COMPONENT_REGISTRY: Dict[str, Type] = {}

_COLORS = ["#2563eb", "#dc2626", "#059669", "#d97706", "#7c3aed",
           "#0891b2", "#be185d", "#4d7c0f", "#b91c1c", "#1e40af"]


def _register(cls):
    _COMPONENT_REGISTRY[cls.__name__] = cls
    return cls


def _esc(s) -> str:
    # quotes too: rendered text lands inside single-quoted HTML attributes
    # (style/color), where an unescaped quote is an attribute breakout
    return (str(s).replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;").replace('"', "&quot;")
            .replace("'", "&#39;"))


def _finite(v) -> bool:
    return v is not None and math.isfinite(v)


@dataclass
class Component:
    """Base: every component serializes with a ``component_type`` tag
    (reference Component.java / Jackson @JsonTypeInfo)."""

    def to_dict(self) -> dict:
        d = {"component_type": type(self).__name__}
        for k, v in self.__dict__.items():
            if isinstance(v, Component):
                d[k] = v.to_dict()
            elif isinstance(v, list) and v and isinstance(v[0], Component):
                d[k] = [c.to_dict() for c in v]
            else:
                d[k] = v
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def render(self) -> str:
        raise NotImplementedError


def from_dict(d: dict) -> Component:
    kind = d.get("component_type")
    cls = _COMPONENT_REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"Unknown component type {kind!r}; known: "
                         f"{sorted(_COMPONENT_REGISTRY)}")
    kwargs = {}
    for k, v in d.items():
        if k == "component_type":
            continue
        if isinstance(v, dict) and "component_type" in v:
            v = from_dict(v)
        elif isinstance(v, list) and v and isinstance(v[0], dict) \
                and "component_type" in v[0]:
            v = [from_dict(c) for c in v]
        kwargs[k] = v
    return cls(**kwargs)


def from_json(s: str) -> Component:
    return from_dict(json.loads(s))


def render_html(component: Component, *, standalone: bool = True) -> str:
    """Render a component tree to HTML (a full document by default)."""
    body = component.render()
    if not standalone:
        return body
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<style>body{font-family:system-ui,sans-serif;margin:16px}"
            "svg text{font-size:9px;fill:#555}"
            "table{border-collapse:collapse}"
            "td,th{border:1px solid #ddd;padding:4px 8px;font-size:13px}"
            "th{background:#f3f4f6}"
            "details{margin:6px 0;border:1px solid #ddd;border-radius:4px;"
            "padding:4px 8px}summary{cursor:pointer;font-weight:600}"
            "</style></head><body>" + body + "</body></html>")


# --------------------------------------------------------------- chart base
def _chart_frame(title, width, height, inner):
    parts = [f"<div class='chart'>"]
    if title:
        parts.append(f"<div style='font-weight:600;font-size:13px;"
                     f"margin:4px 0'>{_esc(title)}</div>")
    parts.append(f'<svg width="{width}" height="{height}" '
                 f'xmlns="http://www.w3.org/2000/svg">{inner}</svg></div>')
    return "".join(parts)


def _scales(xs, ys, width, height, pad=40):
    # one nan/inf score must not poison the whole chart (same contract as
    # the dashboard renderer): scale over the finite values only
    xs = [v for v in xs if _finite(v)]
    ys = [v for v in ys if _finite(v)]
    x0, x1 = (min(xs), max(xs)) if xs else (0.0, 1.0)
    y0, y1 = (min(ys), max(ys)) if ys else (0.0, 1.0)
    if x1 == x0:
        x1 = x0 + 1
    if y1 == y0:
        y1 = y0 + (abs(y0) if y0 else 1) * 0.1 + 1e-12
    W, H = width - pad - 10, height - 30

    def sx(x):
        return pad + (x - x0) / (x1 - x0) * W

    def sy(y):
        return 5 + (1 - (y - y0) / (y1 - y0)) * H
    return sx, sy, (x0, x1, y0, y1), (pad, W, H)


def _grid(sx, sy, lims, dims, width, height):
    x0, x1, y0, y1 = lims
    pad, W, H = dims
    parts = []
    for i in range(5):
        gy = 5 + i * H / 4
        val = y1 - i * (y1 - y0) / 4
        parts.append(f'<line x1="{pad}" y1="{gy:.1f}" x2="{width-10}" '
                     f'y2="{gy:.1f}" stroke="#eee"/>')
        parts.append(f'<text x="2" y="{gy+3:.1f}">{val:.3g}</text>')
    parts.append(f'<text x="{pad}" y="{height-5}">{x0:g}</text>')
    parts.append(f'<text x="{width-60}" y="{height-5}">{x1:g}</text>')
    return parts


def _legend(names, width, height):
    parts, lx = [], 44
    if len(names) > 1:
        for i, nm in enumerate(names):
            c = _COLORS[i % len(_COLORS)]
            parts.append(f'<rect x="{lx}" y="{height-24}" width="8" '
                         f'height="8" fill="{c}"/>')
            parts.append(f'<text x="{lx+11}" y="{height-16}">{_esc(nm)}</text>')
            lx += 11 + 7 * len(str(nm)) + 14
    return parts


# ------------------------------------------------------------------- charts
@_register
@dataclass
class ChartLine(Component):
    """Multi-series line chart (reference chart/ChartLine.java)."""
    title: str = ""
    x: List[List[float]] = field(default_factory=list)   # per series
    y: List[List[float]] = field(default_factory=list)
    series_names: List[str] = field(default_factory=list)
    width: int = 640
    height: int = 240

    def render(self) -> str:
        xs = [v for s in self.x for v in s]
        ys = [v for s in self.y for v in s]
        sx, sy, lims, dims = _scales(xs, ys, self.width, self.height)
        parts = _grid(sx, sy, lims, dims, self.width, self.height)
        for i, (xr, yr) in enumerate(zip(self.x, self.y)):
            c = _COLORS[i % len(_COLORS)]
            pts = " ".join(f"{sx(a):.1f},{sy(b):.1f}" for a, b in zip(xr, yr)
                           if _finite(a) and _finite(b))
            parts.append(f'<polyline fill="none" stroke="{c}" '
                         f'stroke-width="1.5" points="{pts}"/>')
        parts += _legend(self.series_names, self.width, self.height)
        return _chart_frame(self.title, self.width, self.height,
                            "".join(parts))


@_register
@dataclass
class ChartScatter(Component):
    """Scatter chart (reference chart/ChartScatter.java)."""
    title: str = ""
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    series_names: List[str] = field(default_factory=list)
    width: int = 640
    height: int = 240

    def render(self) -> str:
        xs = [v for s in self.x for v in s]
        ys = [v for s in self.y for v in s]
        sx, sy, lims, dims = _scales(xs, ys, self.width, self.height)
        parts = _grid(sx, sy, lims, dims, self.width, self.height)
        for i, (xr, yr) in enumerate(zip(self.x, self.y)):
            c = _COLORS[i % len(_COLORS)]
            for a, b in zip(xr, yr):
                if not (_finite(a) and _finite(b)):
                    continue
                parts.append(f'<circle cx="{sx(a):.1f}" cy="{sy(b):.1f}" '
                             f'r="2.5" fill="{c}" fill-opacity="0.7"/>')
        parts += _legend(self.series_names, self.width, self.height)
        return _chart_frame(self.title, self.width, self.height,
                            "".join(parts))


@_register
@dataclass
class ChartHistogram(Component):
    """Histogram: explicit bin edges + counts (reference
    chart/ChartHistogram.java lowerBounds/upperBounds/yValues)."""
    title: str = ""
    lower_bounds: List[float] = field(default_factory=list)
    upper_bounds: List[float] = field(default_factory=list)
    y: List[float] = field(default_factory=list)
    width: int = 640
    height: int = 200

    def render(self) -> str:
        # drop non-finite bins entirely (same contract as ChartLine)
        bins = [(lo, hi, cnt) for lo, hi, cnt in
                zip(self.lower_bounds, self.upper_bounds, self.y)
                if _finite(lo) and _finite(hi) and _finite(cnt)]
        xs = [b[0] for b in bins] + [b[1] for b in bins]
        ys = [0.0] + [b[2] for b in bins]
        sx, sy, lims, dims = _scales(xs, ys, self.width, self.height)
        parts = _grid(sx, sy, lims, dims, self.width, self.height)
        for lo, hi, cnt in bins:
            x0p, x1p = sx(lo), sx(hi)
            parts.append(
                f'<rect x="{x0p:.1f}" y="{sy(cnt):.1f}" '
                f'width="{max(x1p-x0p-1, 1):.1f}" '
                f'height="{max(sy(0)-sy(cnt), 0):.1f}" fill="#2563eb"/>')
        return _chart_frame(self.title, self.width, self.height,
                            "".join(parts))


@_register
@dataclass
class ChartHorizontalBar(Component):
    """Named horizontal bars (reference chart/ChartHorizontalBar.java)."""
    title: str = ""
    labels: List[str] = field(default_factory=list)
    values: List[float] = field(default_factory=list)
    width: int = 640
    height: int = 0            # 0 -> auto from row count

    def render(self) -> str:
        n = len(self.values)
        height = self.height or (24 * n + 30)
        vmax = max([abs(v) for v in self.values] or [1.0]) or 1.0
        pad, W = 110, self.width - 120
        parts = []
        for i, (lab, v) in enumerate(zip(self.labels, self.values)):
            yy = 8 + i * 24
            w = abs(v) / vmax * W
            parts.append(f'<text x="4" y="{yy+12}">{_esc(lab)}</text>')
            parts.append(f'<rect x="{pad}" y="{yy}" width="{w:.1f}" '
                         f'height="16" fill="{_COLORS[i % len(_COLORS)]}"/>')
            parts.append(f'<text x="{pad+w+4:.1f}" y="{yy+12}">{v:.4g}</text>')
        return _chart_frame(self.title, self.width, height, "".join(parts))


@_register
@dataclass
class ChartStackedArea(Component):
    """Stacked area chart (reference chart/ChartStackedArea.java): shared x,
    one y-series per band, cumulatively stacked."""
    title: str = ""
    x: List[float] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    series_names: List[str] = field(default_factory=list)
    width: int = 640
    height: int = 240

    def render(self) -> str:
        # a non-finite value in ANY band poisons the whole stacked column
        # (bands accumulate), so drop those columns entirely; ragged bands
        # truncate to the shortest (a mid-update dashboard feed)
        n = min([len(self.x)] + [len(band) for band in self.y]) \
            if self.y else 0
        cols = [t for t in range(n)
                if _finite(self.x[t]) and all(_finite(band[t])
                                              for band in self.y)]
        if not cols or not self.y:
            return _chart_frame(self.title, self.width, self.height, "")
        x = [self.x[t] for t in cols]
        bands = [[band[t] for t in cols] for band in self.y]
        stacked = []
        run = [0.0] * len(x)
        for band in bands:
            run = [a + b for a, b in zip(run, band)]
            stacked.append(list(run))
        sx, sy, lims, dims = _scales(x, [0.0] + stacked[-1],
                                     self.width, self.height)
        parts = _grid(sx, sy, lims, dims, self.width, self.height)
        prev = [0.0] * len(x)
        for i, top in enumerate(stacked):
            c = _COLORS[i % len(_COLORS)]
            fwd = [f"{sx(a):.1f},{sy(b):.1f}" for a, b in zip(x, top)]
            back = [f"{sx(a):.1f},{sy(b):.1f}"
                    for a, b in zip(reversed(x), reversed(prev))]
            parts.append(f'<polygon fill="{c}" fill-opacity="0.55" '
                         f'stroke="{c}" points="{" ".join(fwd + back)}"/>')
            prev = top
        parts += _legend(self.series_names, self.width, self.height)
        return _chart_frame(self.title, self.width, self.height,
                            "".join(parts))


@_register
@dataclass
class ChartTimeline(Component):
    """Lanes of [start, end, label] entries (reference
    chart/ChartTimeline.java TimelineEntry rows)."""
    title: str = ""
    lane_names: List[str] = field(default_factory=list)
    lane_entries: List[List[List]] = field(default_factory=list)
    # lane_entries[lane] = [[start_ms, end_ms, label], ...]
    width: int = 640

    def render(self) -> str:
        n = len(self.lane_entries)
        height = 28 * n + 36
        times = [t for lane in self.lane_entries for e in lane
                 for t in (e[0], e[1])]
        t0, t1 = (min(times), max(times)) if times else (0.0, 1.0)
        if t1 == t0:
            t1 = t0 + 1
        pad, W = 90, self.width - 100
        parts = []
        for i, (nm, lane) in enumerate(zip(self.lane_names,
                                           self.lane_entries)):
            yy = 8 + i * 28
            parts.append(f'<text x="4" y="{yy+14}">{_esc(nm)}</text>')
            for j, entry in enumerate(lane):
                s, e = entry[0], entry[1]
                lab = entry[2] if len(entry) > 2 else ""
                x0p = pad + (s - t0) / (t1 - t0) * W
                wpx = max((e - s) / (t1 - t0) * W, 1.5)
                c = _COLORS[j % len(_COLORS)]
                parts.append(f'<rect x="{x0p:.1f}" y="{yy}" '
                             f'width="{wpx:.1f}" height="20" fill="{c}" '
                             f'fill-opacity="0.8"/>')
                if lab:
                    parts.append(f'<text x="{x0p+2:.1f}" y="{yy+14}">'
                                 f'{_esc(lab)}</text>')
        parts.append(f'<text x="{pad}" y="{height-6}">{t0:g}</text>')
        parts.append(f'<text x="{self.width-60}" y="{height-6}">{t1:g}</text>')
        return _chart_frame(self.title, self.width, height, "".join(parts))


# ------------------------------------------------------------- table / text
@_register
@dataclass
class ComponentTable(Component):
    """Header + rows (reference table/ComponentTable.java)."""
    header: List[str] = field(default_factory=list)
    content: List[List] = field(default_factory=list)

    def render(self) -> str:
        parts = ["<table>"]
        if self.header:
            parts.append("<tr>" + "".join(f"<th>{_esc(h)}</th>"
                                          for h in self.header) + "</tr>")
        for row in self.content:
            parts.append("<tr>" + "".join(f"<td>{_esc(v)}</td>"
                                          for v in row) + "</tr>")
        parts.append("</table>")
        return "".join(parts)


@_register
@dataclass
class ComponentText(Component):
    """Styled text (reference text/ComponentText.java)."""
    text: str = ""
    size: int = 13
    bold: bool = False
    color: str = "#111"

    def render(self) -> str:
        w = "600" if self.bold else "400"
        return (f"<div style='font-size:{int(self.size)}px;font-weight:{w};"
                f"color:{_esc(self.color)};margin:4px 0'>"
                f"{_esc(self.text)}</div>")


# --------------------------------------------------------- div / decorator
@_register
@dataclass
class ComponentDiv(Component):
    """Container laying out children vertically (reference
    component/ComponentDiv.java)."""
    components: List[Component] = field(default_factory=list)
    style: str = ""

    def render(self) -> str:
        inner = "".join(c.render() for c in self.components)
        st = f" style='{_esc(self.style)}'" if self.style else ""
        return f"<div{st}>{inner}</div>"


@_register
@dataclass
class DecoratorAccordion(Component):
    """Collapsible section (reference decorator/DecoratorAccordion.java);
    rendered as <details>/<summary> — no JS needed."""
    title: str = ""
    components: List[Component] = field(default_factory=list)
    default_collapsed: bool = True

    def render(self) -> str:
        inner = "".join(c.render() for c in self.components)
        op = "" if self.default_collapsed else " open"
        return (f"<details{op}><summary>{_esc(self.title)}</summary>"
                f"{inner}</details>")


# ------------------------------------------------- stats -> report assembly
def training_report(storage, session_id: Optional[str] = None,
                    worker_id: Optional[str] = None) -> ComponentDiv:
    """Assemble a component-tree training report from a StatsStorage
    session — the DSL's load-bearing consumer (the reference builds the
    same kind of report pages from its components; train/module.js renders
    them). Returns a ComponentDiv; ``render_html`` it or serialize with
    ``to_json`` for a remote renderer."""
    sessions = storage.list_session_ids()
    if session_id is None:
        session_id = sessions[-1] if sessions else ""
    workers = storage.list_worker_ids(session_id) if session_id else []
    if worker_id is None:
        worker_id = workers[0] if workers else ""
    static = storage.get_static_info(session_id, worker_id) or {}
    updates = storage.get_updates(session_id, worker_id)

    kids: List[Component] = [
        ComponentText(f"Training report — session {session_id}",
                      size=18, bold=True)]
    if static:
        kids.append(ComponentTable(
            header=["property", "value"],
            content=[[k, str(v)] for k, v in sorted(static.items())
                     if k != "param_names"]))
    score = [(u["iteration"], u["score"]) for u in updates if "score" in u]
    if score:
        kids.append(ChartLine(title="score vs iteration",
                              x=[[p[0] for p in score]],
                              y=[[p[1] for p in score]],
                              series_names=["score"]))
    pnames = sorted({n for u in updates for n in u.get("params", {})})
    if pnames:
        series_x, series_y = [], []
        for n in pnames[:10]:
            pts = [(u["iteration"], u["params"][n]["meanmag"])
                   for u in updates if n in u.get("params", {})]
            series_x.append([p[0] for p in pts])
            series_y.append([p[1] for p in pts])
        kids.append(DecoratorAccordion(
            title="parameter mean magnitudes",
            components=[ChartLine(title="", x=series_x, y=series_y,
                                  series_names=pnames[:10])]))
    # histograms from the latest update, when collected
    if updates:
        last = updates[-1]
        hists = []
        for n, d in sorted(last.get("params", {}).items()):
            h = d.get("histogram")
            if h:
                counts = h["counts"]
                lo, hi = h["lo"], h["hi"]
                width = (hi - lo) / max(len(counts), 1)
                hists.append(ChartHistogram(
                    title=n,
                    lower_bounds=[lo + i * width
                                  for i in range(len(counts))],
                    upper_bounds=[lo + (i + 1) * width
                                  for i in range(len(counts))],
                    y=[float(c) for c in counts], height=140))
        if hists:
            kids.append(DecoratorAccordion(title="parameter histograms",
                                           components=hists))
    return ComponentDiv(components=kids)
