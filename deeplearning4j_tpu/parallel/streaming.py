"""Streaming training-data ingestion route.

Reference: dl4j-streaming streaming/routes/CamelKafkaRouteBuilder.java — the
Camel route that subscribes a Kafka topic of serialized NDArrays and feeds
them into training — plus the Spark-streaming glue. The TPU-native reshape
drops the Camel/Kafka transports (no broker in this stack) and keeps the
capability: a bounded in-process topic that any producer (HTTP POST, a
thread, a socket reader) publishes DataSets into, exposed as a standard
``DataSetIterator`` so ``net.fit(iterator)`` / ParallelWrapper consume a
LIVE stream with back-pressure. The serving half of dl4j-streaming
(DL4jServeRouteBuilder) lives in parallel/model_server.py.

Composition:
  topic = StreamingDataSetIterator(capacity=64)
  srv = StreamingIngestServer(topic).start()      # POST /publish
  net.fit(iterator=topic)                         # blocks on the stream
  ...producers POST {"features": [...], "labels": [...]} ...
  topic.end_of_stream()                           # drain + stop the epoch

``net.fit(iterator=topic)`` routes the stream through a
DevicePrefetchIterator (datasets/prefetch.py): batches are shipped
host->device on a background thread while the previous step computes.
Back-pressure is PRESERVED end to end — the prefetcher holds at most
``depth`` shipped batches (plus one in flight), its producer thread blocks
on that bounded queue, stops pulling from this topic, and publishers block
on the topic's own ``capacity`` exactly as without prefetch. To land
batches pre-sharded for data-parallel consumption:
``topic.prefetch(depth=2, sharding=data_sharding(mesh))``.
"""
from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from ..datasets.dataset import DataSet, DataSetIterator


class StreamingDataSetIterator(DataSetIterator):
    """Bounded-queue topic of DataSets (the Kafka-topic analogue).

    Producers call :meth:`publish` (blocking when the queue is full — the
    back-pressure Kafka gives via the broker); the training loop iterates,
    blocking until data arrives, and the iteration ends when
    :meth:`end_of_stream` is called and the queue drains (or after
    ``timeout`` seconds with no data, if set).
    """

    _TICK = 0.05   # close-signal poll interval for a blocked consumer

    def __init__(self, capacity: int = 64, timeout: Optional[float] = None):
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self.timeout = timeout
        self._closed = threading.Event()
        self.published = 0
        self.consumed = 0

    # ------------------------------------------------------------- producer
    def publish(self, features, labels, features_mask=None, labels_mask=None,
                block: bool = True, timeout: Optional[float] = None) -> bool:
        """Enqueue one minibatch. Returns False if the stream is closed or
        the queue stayed full past ``timeout`` (non-blocking publish). A
        publish racing :meth:`end_of_stream` may still be delivered — every
        batch this method accepted (returned True) IS consumed, because the
        consumer drains the queue before honoring the close."""
        if self._closed.is_set():
            return False
        ds = DataSet(np.asarray(features, np.float32),
                     np.asarray(labels, np.float32),
                     None if features_mask is None else np.asarray(features_mask),
                     None if labels_mask is None else np.asarray(labels_mask))
        try:
            self._q.put(ds, block=block, timeout=timeout)
        except queue.Full:
            return False
        self.published += 1
        return True

    def end_of_stream(self):
        """Close the topic: consumers drain what's queued, then stop.
        Never blocks (no sentinel occupies queue capacity — the close is an
        event the consumer polls between gets)."""
        self._closed.set()

    # ------------------------------------------------------------- consumer
    def __iter__(self):
        idle = 0.0
        while True:
            try:
                item = self._q.get(timeout=self._TICK)
            except queue.Empty:
                if self._closed.is_set():
                    return        # closed AND drained
                idle += self._TICK
                if self.timeout is not None and idle >= self.timeout:
                    return        # idle timeout: end the epoch
                continue
            idle = 0.0
            self.consumed += 1
            yield item

    def reset(self):
        # a stream has no beginning to rewind to; epochs>1 over a live
        # stream just keep consuming (reference Kafka-consumer semantics)
        pass


class StreamingIngestServer:
    """HTTP front door for the topic (the Camel HTTP/Kafka endpoint
    analogue): POST /publish {"features": [[...]], "labels": [[...]]} feeds
    training; GET /stats reports counters; POST /end closes the stream."""

    def __init__(self, topic: StreamingDataSetIterator, port: int = 0,
                 host: str = "127.0.0.1"):
        self.topic = topic
        self.host = host
        self._port = port
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> "StreamingIngestServer":
        import http.server
        from ..util.httpjson import read_json, write_json
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):   # noqa: N802
                if self.path == "/stats":
                    write_json(self, 200, {
                        "published": server.topic.published,
                        "consumed": server.topic.consumed,
                        "queued": server.topic._q.qsize(),
                        "closed": server.topic._closed.is_set()})
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                try:
                    if self.path == "/publish":
                        req = read_json(self)
                        ok = server.topic.publish(
                            req["features"], req["labels"],
                            req.get("features_mask"), req.get("labels_mask"),
                            block=False)
                        write_json(self, 200 if ok else 503,
                                   {"ok": ok,
                                    **({} if ok else
                                       {"error": "stream closed or full"})})
                    elif self.path == "/end":
                        server.topic.end_of_stream()
                        write_json(self, 200, {"ok": True})
                    else:
                        self.send_error(404)
                except (KeyError, ValueError, TypeError) as e:
                    write_json(self, 400, {"error": str(e)})

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer((self.host, self._port),
                                                      Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
