"""Model-serving HTTP route (legacy single-model path).

Reference: dl4j-streaming streaming/routes/DL4jServeRouteBuilder.java — the
Camel/Kafka serving route that feeds incoming arrays to a model and publishes
predictions. Stdlib HTTP replaces the Camel plumbing; batched inference rides
ParallelInference (reference ParallelInference.BATCHED), so concurrent
requests coalesce into one device batch.

For production serving (shape-bucketed batching, AOT-warmed programs,
admission control, multi-model hot-swap) use
``deeplearning4j_tpu.serving.ServingHTTPServer``.

Endpoints (JSON):
  POST /predict {"features": [[...], ...]}       -> {"output": [[...], ...]}
  GET  /health                                   -> {"status": "ok"|"draining",
                                                     "queue_depth": N, ...}
Status codes: malformed JSON / bad feature payload -> 400; model or
device-side failure -> 500; draining -> 503.
"""
from __future__ import annotations

import threading

import numpy as np

from .inference import ParallelInference


class ModelServingServer:
    def __init__(self, net, port: int = 0, host: str = "127.0.0.1",
                 batched: bool = True, max_batch: int = 64):
        self.net = net
        self.host = host
        self._port = port
        self._pi = (ParallelInference(net, batch_limit=max_batch)
                    if batched else None)
        self._httpd = None
        self._thread = None
        self._count = 0
        self._count_lock = threading.Lock()
        self._draining = False

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> int:
        import http.server
        server = self

        from ..util.httpjson import read_json, write_json

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):   # noqa: N802
                if self.path == "/health":
                    depth = (server._pi.queue_depth
                             if server._pi is not None else 0)
                    body = {"status": ("draining" if server._draining
                                       else "ok"),
                            "draining": server._draining,
                            "queue_depth": depth,
                            "model": type(server.net).__name__,
                            "requests_served": server._count}
                    write_json(self, 503 if server._draining else 200, body)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                if self.path != "/predict":
                    self.send_error(404)
                    return
                if server._draining:
                    write_json(self, 503, {"error": "server is draining"})
                    return
                try:            # parse/validate phase: caller's fault -> 400
                    req = read_json(self)
                    x = np.asarray(req["features"], np.float32)
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                try:            # inference phase: server's fault -> 500
                    if server._pi is not None:
                        out = server._pi.output(x)
                    else:
                        out = server.net.output(x)
                except Exception as e:
                    # a request that slipped past the drain check and was
                    # failed by the shutdown is a routine drain, not a 500
                    if server._draining:
                        write_json(self, 503, {"error": "server is draining"})
                    else:
                        write_json(self, 500, {"error": str(e)})
                    return
                with server._count_lock:   # handler threads race here
                    server._count += 1
                write_json(self, 200, {"output": np.asarray(out).tolist()})

            def log_message(self, *a):
                pass

        import http.server as hs
        self._httpd = hs.ThreadingHTTPServer((self.host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        # drain first: new requests see 503 while in-flight ones finish or
        # are failed by the ParallelInference shutdown (never left hanging)
        self._draining = True
        if self._pi is not None:
            self._pi.shutdown()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
