"""Model-serving HTTP route.

Reference: dl4j-streaming streaming/routes/DL4jServeRouteBuilder.java — the
Camel/Kafka serving route that feeds incoming arrays to a model and publishes
predictions. Stdlib HTTP replaces the Camel plumbing; batched inference rides
ParallelInference (reference ParallelInference.BATCHED), so concurrent
requests coalesce into one device batch.

Endpoints (JSON):
  POST /predict {"features": [[...], ...]}       -> {"output": [[...], ...]}
  GET  /health                                   -> {"status": "ok", ...}
"""
from __future__ import annotations

import json
import threading
from typing import Optional

import numpy as np

from .inference import ParallelInference


class ModelServingServer:
    def __init__(self, net, port: int = 0, host: str = "127.0.0.1",
                 batched: bool = True, max_batch: int = 64):
        self.net = net
        self.host = host
        self._port = port
        self._pi = (ParallelInference(net, batch_limit=max_batch)
                    if batched else None)
        self._httpd = None
        self._thread = None
        self._count = 0
        self._count_lock = threading.Lock()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> int:
        import http.server
        server = self

        from ..util.httpjson import read_json, write_json

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):   # noqa: N802
                if self.path == "/health":
                    write_json(self, 200, {"status": "ok",
                                           "model": type(server.net).__name__,
                                           "requests_served": server._count})
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                if self.path != "/predict":
                    self.send_error(404)
                    return
                try:
                    req = read_json(self)
                    x = np.asarray(req["features"], np.float32)
                    if server._pi is not None:
                        out = server._pi.output(x)
                    else:
                        out = server.net.output(x)
                    with server._count_lock:   # handler threads race here
                        server._count += 1
                    write_json(self, 200, {"output": np.asarray(out).tolist()})
                except Exception as e:
                    write_json(self, 400, {"error": str(e)})

            def log_message(self, *a):
                pass

        import http.server as hs
        self._httpd = hs.ThreadingHTTPServer((self.host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._pi is not None:
            self._pi.shutdown()
