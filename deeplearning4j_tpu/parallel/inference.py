"""ParallelInference: multi-request inference serving.

Reference: parallelism/ParallelInference.java:33 — per-device model replicas;
InferenceMode.BATCHED (default, :53) merges concurrent output() callers into
one device batch up to batch_limit (BatchedInferenceObservable); SEQUENTIAL
round-robins.

TPU mapping: one jitted forward over the mesh replaces per-device replicas —
a merged batch is sharded across the 'data' axis, so batching and
multi-device dispatch are the same operation.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class _Request:
    __slots__ = ("x", "event", "result", "error")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None


class ParallelInference:
    def __init__(self, net, *, inference_mode: str = "batched",
                 batch_limit: int = 32, queue_limit: int = 64,
                 max_wait_ms: float = 2.0):
        self.net = net
        self.mode = inference_mode.lower()
        self.batch_limit = batch_limit
        self.max_wait_ms = max_wait_ms
        self._lock = threading.Lock()
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._worker: Optional[threading.Thread] = None
        if self.mode == "batched":
            self._worker = threading.Thread(target=self._dispatch_loop, daemon=True)
            self._worker.start()

    def output(self, x):
        x = np.asarray(x)
        if self.mode != "batched":
            with self._lock:
                return np.asarray(self.net.output(x))
        req = _Request(x)
        self._queue.put(req)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def _dispatch_loop(self):
        while not self._shutdown:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch: List[_Request] = [first]
            total = first.x.shape[0]
            # scoop up whatever else is queued (up to batch_limit examples)
            deadline = self.max_wait_ms / 1000.0
            import time
            t0 = time.monotonic()
            while total < self.batch_limit and (time.monotonic() - t0) < deadline:
                try:
                    r = self._queue.get_nowait()
                    batch.append(r)
                    total += r.x.shape[0]
                except queue.Empty:
                    time.sleep(0.0005)
            try:
                merged = np.concatenate([r.x for r in batch], axis=0)
                out = np.asarray(self.net.output(merged))
                off = 0
                for r in batch:
                    n = r.x.shape[0]
                    r.result = out[off:off + n]
                    off += n
            except Exception as e:  # propagate per-request
                for r in batch:
                    r.error = e
            finally:
                for r in batch:
                    r.event.set()

    def shutdown(self):
        self._shutdown = True
        if self._worker is not None:
            self._worker.join(timeout=1.0)
