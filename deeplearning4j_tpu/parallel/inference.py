"""ParallelInference: multi-request inference serving (legacy path).

Reference: parallelism/ParallelInference.java:33 — per-device model replicas;
InferenceMode.BATCHED (default, :53) merges concurrent output() callers into
one device batch up to batch_limit (BatchedInferenceObservable); SEQUENTIAL
round-robins.

TPU mapping: one jitted forward over the mesh replaces per-device replicas —
a merged batch is sharded across the 'data' axis, so batching and
multi-device dispatch are the same operation.

NOTE: this is the simple dynamic batcher. Every distinct merged batch size
traces a fresh XLA program at request time; for production serving use
``deeplearning4j_tpu.serving.InferenceEngine`` — shape-bucketed batching
with AOT-warmed programs, admission control, deadlines and hot-swap.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np


class _Request:
    __slots__ = ("x", "event", "result", "error")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None


class ParallelInference:
    def __init__(self, net, *, inference_mode: str = "batched",
                 batch_limit: int = 32, queue_limit: int = 64,
                 max_wait_ms: float = 2.0):
        self.net = net
        self.mode = inference_mode.lower()
        self.batch_limit = batch_limit
        self.max_wait_ms = max_wait_ms
        self._lock = threading.Lock()
        self._submit_lock = threading.Lock()
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._worker: Optional[threading.Thread] = None
        if self.mode == "batched":
            self._worker = threading.Thread(target=self._dispatch_loop, daemon=True)
            self._worker.start()

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    def output(self, x):
        x = np.asarray(x)
        if self.mode != "batched":
            if self._shutdown:
                raise RuntimeError("ParallelInference is shut down")
            with self._lock:
                return np.asarray(self.net.output(x))
        req = _Request(x)
        # submit under the lock shutdown() takes, so a request can never
        # slip into the queue after the shutdown drain (it would hang its
        # caller forever — no worker is left to serve it)
        while True:
            with self._submit_lock:
                if self._shutdown:
                    raise RuntimeError("ParallelInference is shut down")
                try:
                    self._queue.put_nowait(req)
                    break
                except queue.Full:
                    pass
            time.sleep(0.0005)        # queue full: wait outside the lock
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def _dispatch_loop(self):
        carry: Optional[_Request] = None   # deferred overflow request
        while True:
            if carry is not None:
                first, carry = carry, None
            else:
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    if self._shutdown:
                        return             # drained: shutdown() failed the rest
                    continue
            batch: List[_Request] = [first]
            total = first.x.shape[0]
            # scoop up whatever else is queued, but NEVER overshoot
            # batch_limit: an overflow request is carried to the next batch
            deadline = self.max_wait_ms / 1000.0
            t0 = time.monotonic()
            while total < self.batch_limit and (time.monotonic() - t0) < deadline:
                try:
                    r = self._queue.get_nowait()
                except queue.Empty:
                    if self._shutdown:
                        break              # drain fast, don't wait the window
                    time.sleep(0.0005)
                    continue
                if total + r.x.shape[0] > self.batch_limit:
                    carry = r              # defer: next batch starts with it
                    break
                batch.append(r)
                total += r.x.shape[0]
            try:
                merged = np.concatenate([r.x for r in batch], axis=0)
                out = np.asarray(self.net.output(merged))
                off = 0
                for r in batch:
                    n = r.x.shape[0]
                    r.result = out[off:off + n]
                    off += n
            except Exception as e:  # propagate per-request
                for r in batch:
                    r.error = e
            finally:
                for r in batch:
                    r.event.set()

    def shutdown(self):
        """Stop the worker and FAIL every request still queued — callers
        blocked in output() get a RuntimeError instead of hanging, and
        later output() calls raise instead of enqueueing to nobody."""
        with self._submit_lock:
            self._shutdown = True
        if self._worker is not None:
            self._worker.join(timeout=2.0)
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            r.error = RuntimeError("ParallelInference shut down before "
                                   "this request was dispatched")
            r.event.set()
