"""Expert parallelism: mixture-of-experts dispatch over a mesh axis.

NET-NEW capability beyond reference parity (SURVEY.md §2.2: the reference
has no expert parallelism). Experts are sharded over the ``expert`` mesh
axis (each device holds n_experts/n_devices expert parameter sets); tokens
are routed to their top-1 expert with capacity-bounded dispatch and exchanged
via ``all_to_all`` — the canonical TPU MoE pattern (dispatch/combine
einsums + ICI all-to-all).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def expert_parallel_apply(expert_fn: Callable, mesh: Mesh,
                          axis: str = "expert", capacity_factor: float = 2.0):
    """Build ``fn(stacked_expert_params, tokens, gate_logits)``.

    - ``expert_fn(params_e, x) -> y``: one expert's computation ([T, D] in,
      [T, D'] out, shape-static).
    - ``stacked_expert_params``: leaves with leading ``n_experts`` axis,
      sharded on ``axis`` (one expert per device in this implementation:
      n_experts == mesh.shape[axis]).
    - ``tokens``: [N, D] replicated; ``gate_logits``: [N, n_experts].

    Top-1 routing with per-expert capacity C = ceil(capacity_factor * N /
    n_experts); overflow tokens are dropped (standard MoE semantics) and
    pass through as zeros, weighted combine restores gate probabilities.
    """
    n = int(mesh.shape[axis])

    def worker(params, tokens, gate_logits):
        params = jax.tree.map(lambda a: a[0], params)   # this device's expert
        N, D = tokens.shape
        cap = int(np.ceil(capacity_factor * N / n))
        probs = jax.nn.softmax(gate_logits, axis=-1)    # [N, E]
        choice = jnp.argmax(probs, axis=-1)             # [N]
        gate = jnp.max(probs, axis=-1)                  # [N]
        # position of each token within its expert's capacity buffer
        onehot = jax.nn.one_hot(choice, n, dtype=jnp.int32)      # [N, E]
        pos = jnp.cumsum(onehot, axis=0) * onehot                # 1-based
        pos_in_expert = jnp.sum(pos, axis=-1) - 1                # [N]
        keep = pos_in_expert < cap
        # dispatch buffer [E, cap, D] built identically on every device
        disp = jnp.zeros((n, cap, D), tokens.dtype)
        disp = disp.at[choice, jnp.clip(pos_in_expert, 0, cap - 1)].add(
            tokens * keep[:, None])
        # all_to_all is unnecessary here because every device computed the
        # full dispatch; each device SELECTS its expert's slab. (With
        # token-sharded inputs this becomes a real all_to_all; the combine
        # below is the psum half of that exchange.)
        idx = jax.lax.axis_index(axis)
        my_slab = disp[idx]                              # [cap, D]
        my_out = expert_fn(params, my_slab)              # [cap, D']
        # combine: scatter my expert's outputs back to token order, psum
        # across experts
        token_idx = jnp.arange(N)
        mine = jnp.logical_and(choice == idx, keep)
        out = jnp.zeros((N, my_out.shape[-1]), my_out.dtype)
        out = out.at[token_idx].add(
            my_out[jnp.clip(pos_in_expert, 0, cap - 1)] * mine[:, None])
        out = jax.lax.psum(out, axis)
        return out * gate[:, None]

    inner = jax.jit(shard_map(worker, mesh=mesh,
                              in_specs=(P(axis), P(), P()), out_specs=P(),
                              check_vma=False))

    def fn(stacked_params, tokens, gate_logits):
        if gate_logits.shape[-1] != n:
            raise ValueError(
                f"gate_logits last dim ({gate_logits.shape[-1]}) must equal "
                f"the expert mesh axis size ({n}) — routing to a nonexistent "
                f"expert would silently zero those tokens")
        for leaf in jax.tree.leaves(stacked_params):
            if leaf.shape[0] != n:
                raise ValueError(
                    f"stacked expert params must have leading dim == mesh "
                    f"axis size ({n}); got {leaf.shape[0]}")
        return inner(stacked_params, tokens, gate_logits)

    return fn


def expert_sharding(mesh: Mesh, axis: str = "expert") -> NamedSharding:
    return NamedSharding(mesh, P(axis))
