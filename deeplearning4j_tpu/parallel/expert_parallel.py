"""Expert parallelism: mixture-of-experts dispatch over a mesh axis.

NET-NEW capability beyond reference parity (SURVEY.md §2.2: the reference
has no expert parallelism). Experts are sharded over the ``expert`` mesh
axis (each device holds n_experts/n_devices expert parameter sets); tokens
are routed to their top-1 expert with capacity-bounded dispatch and exchanged
via ``all_to_all`` — the canonical TPU MoE pattern (dispatch/combine
einsums + ICI all-to-all).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from .mesh import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def expert_parallel_apply(expert_fn: Callable, mesh: Mesh,
                          axis: str = "expert", capacity_factor: float = 2.0,
                          top_k: int = 1):
    """Build ``fn(stacked_expert_params, tokens, gate_logits)``.

    - ``expert_fn(params_e, x) -> y``: one expert's computation ([T, D] in,
      [T, D'] out, shape-static).
    - ``stacked_expert_params``: leaves with leading ``n_experts`` axis,
      sharded on ``axis`` (one expert per device in this implementation:
      n_experts == mesh.shape[axis]).
    - ``tokens``: [N, D] replicated; ``gate_logits``: [N, n_experts].

    Routing is top-``top_k`` (GShard-style) with per-expert capacity
    C = ceil(capacity_factor * top_k * N / n_experts). Capacity slots are
    assigned first-choice-first: every token's choice-0 claims slots before
    any choice-1 does, so second choices absorb the leftover capacity.
    Combine weights are the chosen gate probabilities renormalized over the
    choices that actually fit — a token whose first choice overflowed is
    RE-ROUTED with full weight to its second expert (top_k >= 2 is what
    makes MoE robust to capacity overflow in practice); a token with no
    surviving choice passes through as zeros.
    """
    n = int(mesh.shape[axis])
    if not 1 <= top_k <= n:
        raise ValueError(f"top_k must be in [1, {n}], got {top_k}")

    def worker(params, tokens, gate_logits):
        params = jax.tree.map(lambda a: a[0], params)   # this device's expert
        N, D = tokens.shape
        cap = int(np.ceil(capacity_factor * top_k * N / n))
        probs = jax.nn.softmax(gate_logits, axis=-1)    # [N, E]
        top_p, top_e = jax.lax.top_k(probs, top_k)      # [N, k]
        if top_k == 1:
            # Switch-style: combine with the RAW top prob so the router gets
            # a gradient (renormalizing a single choice would be constant 1)
            gates = top_p
        else:
            gates = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
        # slot assignment, first-choice-first (GShard): choice c's positions
        # start after ALL tokens' earlier-choice claims on that expert
        claimed = jnp.zeros((n,), jnp.int32)
        pos_ck, keep_ck = [], []
        for c in range(top_k):
            onehot = jax.nn.one_hot(top_e[:, c], n, dtype=jnp.int32)  # [N, E]
            pos = jnp.cumsum(onehot, axis=0) * onehot                 # 1-based
            pos_in_expert = jnp.sum(pos, axis=-1) - 1 + claimed[top_e[:, c]]
            pos_ck.append(pos_in_expert)
            keep_ck.append(pos_in_expert < cap)
            claimed = claimed + jnp.sum(onehot, axis=0)
        pos_k = jnp.stack(pos_ck, axis=1)               # [N, k]
        keep_k = jnp.stack(keep_ck, axis=1)             # [N, k]
        # re-route weight mass onto surviving choices (top_k >= 2): a token
        # whose first choice overflowed hands its full weight to the second.
        # Gradients still flow to the router through the surviving probs.
        live = gates * keep_k                           # [N, k]
        if top_k == 1:
            weights = live
        else:
            denom = jnp.maximum(jnp.sum(live, axis=-1, keepdims=True), 1e-9)
            weights = live / denom
        # dispatch buffer [E, cap, D] built identically on every device
        disp = jnp.zeros((n, cap, D), tokens.dtype)
        for c in range(top_k):
            disp = disp.at[top_e[:, c],
                           jnp.clip(pos_k[:, c], 0, cap - 1)].add(
                tokens * keep_k[:, c:c + 1])
        # all_to_all is unnecessary here because every device computed the
        # full dispatch; each device SELECTS its expert's slab. (With
        # token-sharded inputs this becomes a real all_to_all; the combine
        # below is the psum half of that exchange.)
        idx = jax.lax.axis_index(axis)
        my_slab = disp[idx]                              # [cap, D]
        my_out = expert_fn(params, my_slab)              # [cap, D']
        # combine: scatter my expert's outputs back to token order with the
        # re-routed weights, psum across experts
        token_idx = jnp.arange(N)
        out = jnp.zeros((N, my_out.shape[-1]), my_out.dtype)
        for c in range(top_k):
            mine = jnp.logical_and(top_e[:, c] == idx, keep_k[:, c])
            out = out.at[token_idx].add(
                my_out[jnp.clip(pos_k[:, c], 0, cap - 1)]
                * (mine * weights[:, c])[:, None])
        return jax.lax.psum(out, axis)

    inner = jax.jit(shard_map(worker, mesh=mesh,
                              in_specs=(P(axis), P(), P()), out_specs=P(),
                              check_vma=False))

    def fn(stacked_params, tokens, gate_logits):
        if gate_logits.shape[-1] != n:
            raise ValueError(
                f"gate_logits last dim ({gate_logits.shape[-1]}) must equal "
                f"the expert mesh axis size ({n}) — routing to a nonexistent "
                f"expert would silently zero those tokens")
        for leaf in jax.tree.leaves(stacked_params):
            if leaf.shape[0] != n:
                raise ValueError(
                    f"stacked expert params must have leading dim == mesh "
                    f"axis size ({n}); got {leaf.shape[0]}")
        return inner(stacked_params, tokens, gate_logits)

    return fn


def expert_sharding(mesh: Mesh, axis: str = "expert") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def load_balancing_loss(gate_logits: jnp.ndarray, top_k: int = 1) -> jnp.ndarray:
    """Switch/GShard auxiliary load-balancing loss: E * sum_e f_e * P_e,
    where f_e is the fraction of tokens whose top-k choices include expert e
    and P_e the mean routing probability. Minimized (= top_k) at uniform
    routing (f_e = top_k/E, P_e = 1/E); add a small multiple to the training
    loss to keep experts utilized."""
    n = gate_logits.shape[-1]
    probs = jax.nn.softmax(gate_logits, axis=-1)
    _, top_e = jax.lax.top_k(probs, top_k)
    chosen = jnp.sum(jax.nn.one_hot(top_e, n), axis=1)        # [N, E]
    f = jnp.mean(chosen, axis=0)
    p = jnp.mean(probs, axis=0)
    return n * jnp.sum(f * p)
