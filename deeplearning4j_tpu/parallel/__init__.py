from .mesh import data_sharding, make_mesh, replicated, window_sharding
from .data_parallel import ParallelWrapper
from .inference import ParallelInference
from .overlap import (BucketSchedule, GradBucket, build_bucket_schedule,
                      bucketed_pmean, fused_pmean, profile_schedule)
from .zero import ZeroUpdateEngine, is_zero_state, make_zero_resharder
from .tensor_parallel import (MODEL_AXIS, build_param_specs,
                              build_param_shardings, host_gather,
                              model_axis_size, per_replica_bytes,
                              shard_params, sharded_leaf_count)
from .resharding import make_any_resharder, redistribute
from .elastic import ElasticTrainer, RecoveryFailedError
from .faults import (CoordinationError, CoordinationFlake, CorruptCheckpoint,
                     FaultInjector, FaultPlan, KillWorker, PreemptAt,
                     SlowCollective, WorkerLostError)

__all__ = ["data_sharding", "make_mesh", "replicated", "window_sharding",
           "ParallelWrapper", "ParallelInference",
           "BucketSchedule", "GradBucket", "build_bucket_schedule",
           "bucketed_pmean", "fused_pmean", "profile_schedule",
           "ZeroUpdateEngine", "is_zero_state", "make_zero_resharder",
           "MODEL_AXIS", "build_param_specs", "build_param_shardings",
           "host_gather", "model_axis_size", "per_replica_bytes",
           "shard_params", "sharded_leaf_count",
           "make_any_resharder", "redistribute",
           "ElasticTrainer", "RecoveryFailedError",
           "FaultInjector", "FaultPlan", "KillWorker", "SlowCollective",
           "CorruptCheckpoint", "PreemptAt", "CoordinationFlake",
           "WorkerLostError", "CoordinationError"]
