from .mesh import data_sharding, make_mesh, replicated, window_sharding
from .data_parallel import ParallelWrapper
from .inference import ParallelInference
from .overlap import (BucketSchedule, GradBucket, build_bucket_schedule,
                      bucketed_pmean, fused_pmean, profile_schedule)
from .elastic import ElasticTrainer, RecoveryFailedError
from .faults import (CoordinationError, CoordinationFlake, CorruptCheckpoint,
                     FaultInjector, FaultPlan, KillWorker, PreemptAt,
                     SlowCollective, WorkerLostError)

__all__ = ["data_sharding", "make_mesh", "replicated", "window_sharding",
           "ParallelWrapper", "ParallelInference",
           "BucketSchedule", "GradBucket", "build_bucket_schedule",
           "bucketed_pmean", "fused_pmean", "profile_schedule",
           "ElasticTrainer", "RecoveryFailedError",
           "FaultInjector", "FaultPlan", "KillWorker", "SlowCollective",
           "CorruptCheckpoint", "PreemptAt", "CoordinationFlake",
           "WorkerLostError", "CoordinationError"]
