from .mesh import data_sharding, make_mesh, replicated, window_sharding
from .data_parallel import ParallelWrapper
from .inference import ParallelInference
from .overlap import (BucketSchedule, GradBucket, build_bucket_schedule,
                      bucketed_pmean, fused_pmean, profile_schedule)
from .zero import ZeroUpdateEngine, is_zero_state, make_zero_resharder
from .elastic import ElasticTrainer, RecoveryFailedError
from .faults import (CoordinationError, CoordinationFlake, CorruptCheckpoint,
                     FaultInjector, FaultPlan, KillWorker, PreemptAt,
                     SlowCollective, WorkerLostError)

__all__ = ["data_sharding", "make_mesh", "replicated", "window_sharding",
           "ParallelWrapper", "ParallelInference",
           "BucketSchedule", "GradBucket", "build_bucket_schedule",
           "bucketed_pmean", "fused_pmean", "profile_schedule",
           "ZeroUpdateEngine", "is_zero_state", "make_zero_resharder",
           "ElasticTrainer", "RecoveryFailedError",
           "FaultInjector", "FaultPlan", "KillWorker", "SlowCollective",
           "CorruptCheckpoint", "PreemptAt", "CoordinationFlake",
           "WorkerLostError", "CoordinationError"]
