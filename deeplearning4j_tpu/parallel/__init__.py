from .mesh import data_sharding, make_mesh, replicated, window_sharding
from .data_parallel import ParallelWrapper
from .inference import ParallelInference
from .overlap import (BucketSchedule, GradBucket, build_bucket_schedule,
                      bucketed_pmean, fused_pmean, profile_schedule)

__all__ = ["data_sharding", "make_mesh", "replicated", "window_sharding",
           "ParallelWrapper", "ParallelInference",
           "BucketSchedule", "GradBucket", "build_bucket_schedule",
           "bucketed_pmean", "fused_pmean", "profile_schedule"]
