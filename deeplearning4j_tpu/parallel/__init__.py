from .mesh import data_sharding, make_mesh, replicated
from .data_parallel import ParallelWrapper
from .inference import ParallelInference

__all__ = ["data_sharding", "make_mesh", "replicated", "ParallelWrapper",
           "ParallelInference"]
