"""Any-named-sharding → any-named-sharding redistribution.

Generalizes ``parallel.zero.make_zero_resharder`` ("ZeRO flat layouts
saved on n shards → sliced to n' shards") to the full problem: a
checkpoint written under ANY sharded layout — 1-D data meshes, (data,
model) tp meshes, ZeRO flat state, or mixtures — restores onto ANY
other topology. This is what elastic shrunk-mesh recovery, fleet
hot-swap across replica topologies, and future expert-parallel layouts
all reduce to.

Mechanics follow arXiv 2112.01075 (redistribution = gather + re-slice,
expressed over portable collectives):

- same topology: the per-device block restore is a no-op redistribution
  and stays bitwise (``restore_sharded_checkpoint``).
- different topology: leaves are assembled fully on host from the saved
  (start, stop) blocks (the all-gather half,
  ``load_checkpoint_arrays``), then ``device_put`` re-slices each leaf
  onto the target layout (the slice half — on an accelerator backend
  XLA lowers the placement to its collective decomposition; on CPU this
  IS the paper's host-gather fallback).
- ZeRO flat state keeps its specialized resharder (the flat [N, L]
  layout needs bucket-aware re-padding, not naive re-slicing): layouts
  whose manifest carries the ``zero-flat`` block delegate.

``make_any_resharder`` produces the hook
``restore_latest_sharded_checkpoint`` consumes, so every restore path —
DistributedCheckpointer, ElasticTrainer recovery, serving fleet reload
— gains topology portability by passing it through.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..util.distributed_checkpoint import (load_checkpoint_arrays,
                                           restore_sharded_checkpoint)
from .zero import make_zero_resharder


def redistribute(tree: Any, mesh: Mesh, specs: Any) -> Any:
    """Device-side any→any redistribution of a live pytree: place every
    leaf onto ``NamedSharding(mesh, spec)``. On accelerator backends
    XLA decomposes the move into all-gather / all-to-all /
    collective-permute (arXiv 2112.01075); on the CPU test backend the
    same call round-trips through host — the portable fallback. Values
    are unchanged (pure layout)."""
    def per(spec, leaf):
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree.map(per, specs, tree,
                        is_leaf=lambda x: isinstance(x, P))


def _host_reshard(directory: str, step: int, like: Any) -> Any:
    """Host-assembly redistribution: gather every saved leaf fully on
    host, then re-slice onto ``like``'s shardings. Raises (→ the restore
    walks back to an older save) when shapes disagree — which is also
    how a zero-flat save from a DIFFERENT data-axis size surfaces when
    no engine was supplied to interpret it."""
    arrs = load_checkpoint_arrays(directory, step)
    leaves, treedef = jax.tree.flatten(like)
    if len(arrs) != len(leaves):
        raise ValueError(f"checkpoint has {len(arrs)} leaves; 'like' "
                         f"tree has {len(leaves)}")
    out = []
    for i, (leaf, arr) in enumerate(zip(leaves, arrs)):
        target = leaf if isinstance(leaf, jax.Array) \
            else jax.numpy.asarray(leaf)
        if tuple(arr.shape) != tuple(target.shape):
            raise ValueError(
                f"leaf {i}: checkpoint shape {tuple(arr.shape)} vs like "
                f"{tuple(target.shape)} — layout needs a format-aware "
                f"resharder (zero-flat state from a different data-axis "
                f"size?)")
        arr = arr.astype(np.dtype(target.dtype), copy=False)
        out.append(jax.device_put(arr, target.sharding)
                   if hasattr(target, "sharding") else jax.numpy.asarray(arr))
    return jax.tree.unflatten(treedef, out)


def make_any_resharder(zero_engine: Optional[Any] = None):
    """The generalized restore hook for
    ``restore_latest_sharded_checkpoint``: ``(directory, step, like,
    manifest) -> tree``.

    Resolution order per candidate save:

    1. a ``zero-flat`` sharding block with an engine supplied → the
       bucket-aware ZeRO resharder (``None`` from it means the layout
       already matches → fall through to the bitwise path);
    2. the direct per-device block restore — bitwise whenever the save's
       topology matches the current mesh, whatever that topology is;
    3. host gather + re-slice (arXiv 2112.01075 fallback) — any saved
       layout onto any current layout, params bit-identical, at the cost
       of one full host assembly.

    Exceptions propagate to the caller's walk-back loop, so a corrupt or
    uninterpretable newest save falls back to an older one instead of
    aborting recovery."""
    zero_hook = (make_zero_resharder(zero_engine)
                 if zero_engine is not None else None)

    def _reshard(directory: str, step: int, like: Any, manifest: dict):
        layout = (manifest or {}).get("sharding") or {}
        if zero_hook is not None and layout.get("format") == "zero-flat":
            tree = zero_hook(directory, step, like, manifest)
            if tree is not None:
                return tree
        try:
            return restore_sharded_checkpoint(directory, step, like)
        except ValueError:
            # different topology: the saved blocks don't tile the current
            # devices — fall through to the portable gather + re-slice
            pass
        return _host_reshard(directory, step, like)

    return _reshard
