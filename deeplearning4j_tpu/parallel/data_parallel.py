"""Data-parallel training: the TPU-native ParallelWrapper.

Reference: parallelism/ParallelWrapper.java:54 — thread-per-worker data
parallelism with parameter averaging every ``averaging_frequency`` iterations
(:244-250, averageModelsParams :332-361) or SHARED_GRADIENTS mode pushing
per-iteration updates through a GradientsAccumulator; Spark variants
(SURVEY.md §2.2) implement the same two semantics across hosts.

TPU mapping (SURVEY.md §5.8):
- SHARED_GRADIENTS / averaging_frequency=1  ->  per-step synchronous
  all-reduce: ONE jitted train step over a `Mesh`, batch sharded on the
  'data' axis, params replicated; XLA/GSPMD inserts the psum over ICI.
  (This is the reference's gradient-sharing path minus the threshold
  compression, which ICI bandwidth makes unnecessary; see ops/compression
  for the DCN variant.)
- AVERAGING with frequency K>1  ->  faithfully emulated with `shard_map`:
  each device holds ITS OWN params copy, runs K local steps on its shard
  stream, then `pmean`s params (and optionally updater state — reference
  ``averageUpdaters`` flag) across the axis.

Multi-host: the same code runs under `jax.distributed.initialize()`; the mesh
then spans hosts and the collectives ride ICI/DCN — no Aeron, no parameter
server (reference SharedTrainingMaster.java:46-53 is replaced wholesale).
"""
from __future__ import annotations

import functools
import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..datasets.dataset import AsyncDataSetIterator
from ..datasets.prefetch import (BatchWindow, DevicePrefetchIterator,
                                 iter_windows, skip_batches)
from ..optimize.listeners import PerformanceListener, TrainingListener
from ..optimize.solver import cast_feed, train_step_math
from ..telemetry import get_registry, span
from .mesh import (data_sharding, make_mesh, replicated, shard_map,
                   window_sharding)
from .overlap import (DEFAULT_BUCKET_BYTES, build_bucket_schedule,
                      bucketed_pmean, fused_pmean)
from .tensor_parallel import (MODEL_AXIS, build_opt_shardings,
                              build_param_specs, build_param_shardings,
                              model_axis_size, per_replica_bytes)
from .zero import ZeroUpdateEngine, is_zero_state


class ParallelWrapper:
    """API analogue of the reference ParallelWrapper.Builder:

        pw = ParallelWrapper(net, averaging_frequency=3,
                             training_mode="averaging", average_updaters=True)
        pw.fit(iterator, epochs=2)

    ``workers`` is accepted for API familiarity but the device count comes
    from the mesh (every chip is a worker).

    ``prefetch_buffer`` (reference Builder.prefetchBuffer) is the in-flight
    depth of the input pipeline: on the per-step sync path it is the
    DevicePrefetchIterator depth — batches ship host->device PRE-SHARDED on
    the mesh's data axis while the previous step computes; on the K-step
    averaging path it is the host-side prefetch queue (the K-batch stack is
    assembled on host).

    ``steps_per_dispatch=K`` (sync path only): windows of K pre-sharded
    device-resident batches run through ONE jitted lax.scan program —
    bit-identical to K per-step dispatches, one host round-trip per
    window. Ragged remainder windows fall back per-step; the averaging
    path (averaging_frequency>1) is already a fused K-step program and
    ignores this knob.

    ``overlap_sync=True`` (sync path, no accumulator): bucketed
    backward-overlap gradient synchronization (parallel/overlap.py) —
    the grad tree is all-reduced per ~``bucket_bytes`` bucket (small
    leaves densified into one flat psum each, packed in reverse leaf
    order) instead of the monolithic per-leaf post-backward sweep, so
    collectives launch as their gradients are produced and the sync
    dispatches O(buckets) collectives instead of O(leaves). Composes
    with ``steps_per_dispatch`` (the scan body carries the same
    schedule). Bit-identical to the unbucketed path at every bucket
    size (tests/test_overlap_sync.py).

    ``zero_stage=1|2`` (sync path): ZeRO-style cross-replica sharding of
    the weight update (parallel/zero.py, arXiv 2004.13336). Each replica
    applies the updater to only its 1/N flat shard of the grad+param
    tree — updater state is allocated SHARD-SIZED (``net.opt_state``
    becomes the engine's sharded format for the duration; convert back
    with ``gather_opt_state()``) — then all-gathers the updated params.
    Stage 1 all-reduces grads per bucket (the same collectives as
    ``overlap_sync``) and slices; stage 2 reduce-scatters per bucket
    (half the collective bytes). Both are bit-identical to the
    replicated update and compose with ``steps_per_dispatch`` windows
    and the remainder fallback (tests/test_zero.py).

    On every sync path (plain, overlap and zero), a batch whose size
    does not tile the mesh — the end-of-epoch remainder the prefetcher
    ships unsharded — dispatches through a replicated-feed program for
    that step instead of raising the divisibility error; the update is
    identical. The explicit-accumulator path keeps the loud error (its
    per-worker carry has no replicated equivalent).
    """

    def __init__(self, net, *, mesh: Optional[Mesh] = None,
                 mesh_shape: Optional[tuple] = None,
                 workers: Optional[int] = None,
                 averaging_frequency: int = 1, training_mode: str = "shared_gradients",
                 average_updaters: bool = True, prefetch_buffer: int = 2,
                 report_score_after_averaging: bool = True,
                 gradient_accumulator=None, steps_per_dispatch: int = 1,
                 overlap_sync: bool = False,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 zero_stage: int = 0,
                 step_callback=None):
        self.net = net
        devices = jax.devices()
        if mesh is not None and mesh_shape is not None:
            raise ValueError("pass mesh OR mesh_shape, not both")
        if workers is not None and mesh is None:
            devices = devices[:workers]
            if mesh_shape is None:
                mesh = make_mesh((len(devices),), ("data",), devices)
        if mesh_shape is not None:
            # (d,) is the 1-D data mesh; (d, m) adds the Megatron-style
            # model axis (parallel/tensor_parallel.py) — m=1 keeps the
            # axis in the mesh but every program stays bit-identical to
            # the 1-D path (the tp spec table is empty at m=1).
            if len(mesh_shape) == 1:
                mesh = make_mesh(tuple(mesh_shape), ("data",), devices)
            elif len(mesh_shape) == 2:
                mesh = make_mesh(tuple(mesh_shape), ("data", MODEL_AXIS),
                                 devices)
            else:
                raise ValueError(f"mesh_shape must be (d,) or (d, m), "
                                 f"got {mesh_shape}")
        self.mesh = mesh if mesh is not None else make_mesh()
        # batch-divisibility and worker accounting follow the DATA axis
        # only — the model axis replicates the batch
        _sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        self.n = int(_sizes.get("data", self.mesh.devices.size))
        self.m = model_axis_size(self.mesh)
        self.averaging_frequency = max(1, averaging_frequency)
        self.training_mode = training_mode.lower()
        self.average_updaters = average_updaters
        self.prefetch_buffer = prefetch_buffer
        # GradientsAccumulator seam (reference GradientsAccumulator.java SPI;
        # see parallel/accumulation.py). None -> GSPMD-inserted psum.
        self.gradient_accumulator = gradient_accumulator
        if gradient_accumulator is not None and \
                self.training_mode == "averaging" and self.averaging_frequency > 1:
            raise ValueError(
                "gradient_accumulator applies to the per-step gradient-sharing "
                "path (training_mode='shared_gradients'), not K-step parameter "
                "averaging — the reference makes the same split "
                "(ParallelWrapper.TrainingMode AVERAGING vs SHARED_GRADIENTS)")
        if self.m > 1:
            if self.training_mode == "averaging" \
                    and self.averaging_frequency > 1:
                raise ValueError(
                    "model-axis sharding applies to the per-step sync "
                    "path; K-step parameter averaging gives each worker "
                    "its own full param copy, which a model-sharded "
                    "layout cannot represent — use "
                    "training_mode='shared_gradients' on a (data, model) "
                    "mesh")
            if gradient_accumulator is not None:
                raise ValueError(
                    "a GradientsAccumulator ravels the full per-worker "
                    "grad tree, which a model-sharded layout cannot feed "
                    "— drop the accumulator on a (data, model) mesh")
        # Fused K-step dispatch on the sync all-reduce path (the same
        # scan-window program as Solver.fit(steps_per_dispatch=K), with
        # xs/ys landing [K, batch, ...] sharded on the data axis). The
        # explicit-accumulator path keeps per-step dispatch: its combine
        # carry is per-worker state threaded outside the scan.
        if steps_per_dispatch < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        if steps_per_dispatch > 1 and gradient_accumulator is not None:
            raise ValueError(
                "steps_per_dispatch applies to the plain sync all-reduce "
                "path; the GradientsAccumulator path dispatches per step")
        # Bucketed backward-overlap gradient sync (parallel/overlap.py):
        # shard_map step with per-bucket flat psums instead of the GSPMD
        # monolithic post-backward sweep. Orthogonal to the accumulator
        # seam (which owns its own combine) — refuse the combination.
        if overlap_sync and gradient_accumulator is not None:
            raise ValueError(
                "overlap_sync schedules the plain psum exchange in buckets; "
                "a GradientsAccumulator owns its own combine — pick one")
        if overlap_sync and self.training_mode == "averaging" \
                and self.averaging_frequency > 1:
            raise ValueError(
                "overlap_sync applies to the per-step sync all-reduce path; "
                "the K-step averaging path already runs ONE fused variadic "
                "pmean launch per window — it would silently ignore the "
                "bucket schedule")
        self.overlap_sync = overlap_sync
        self.bucket_bytes = bucket_bytes
        self._bucket_schedule = None     # built lazily from net.params
        # ZeRO sharded update (parallel/zero.py): stage 1 = shard the
        # updater state (grads still all-reduced, bucketed), stage 2 =
        # reduce-scatter the grads too. Sync-path only: the K-step
        # averaging path pmeans whole param/state trees (sharded state
        # has no per-worker trajectory to average) and the accumulator
        # owns its own combine.
        if zero_stage not in (0, 1, 2):
            raise ValueError(f"zero_stage must be 0, 1 or 2, "
                             f"got {zero_stage}")
        if zero_stage and gradient_accumulator is not None:
            raise ValueError(
                "zero_stage shards the plain sync update; a "
                "GradientsAccumulator owns its own combine — pick one")
        if zero_stage and self.training_mode == "averaging" \
                and self.averaging_frequency > 1:
            raise ValueError(
                "zero_stage applies to the per-step sync all-reduce "
                "path; the K-step averaging path averages full "
                "per-worker param/state trajectories, which a sharded "
                "updater state cannot represent")
        if zero_stage and overlap_sync:
            raise ValueError(
                "zero_stage already dispatches per-bucket overlapped "
                "collectives (stage 1 is the overlap_sync launch "
                "pattern; stage 2 reduce-scatters the same buckets) — "
                "drop overlap_sync rather than have it silently ignored")
        self.zero_stage = zero_stage
        self._zero_engine = None         # built lazily from net.params
        self.steps_per_dispatch = steps_per_dispatch
        self._acc_state = None
        self._sync_step = None
        self._sync_window_step = None
        # tensor-parallel layout (parallel/tensor_parallel.py), built
        # lazily from net.params: PartitionSpec tree + NamedSharding
        # trees for params and updater state. None until m > 1 asks.
        self._tp_specs = None
        self._tp_param_sh = None
        self._tp_opt_sh = None
        # Replicated-feed programs for sync batches that don't tile the
        # mesh (shard_map AND jit+in_shardings both enforce batch-dim
        # divisibility): the end-of-epoch remainder the prefetcher ships
        # unsharded dispatches through these instead of killing the
        # epoch. Built lazily; the update is identical (the psum over a
        # sharded batch == the replicated full-batch computation).
        self._remainder_step = None
        self._remainder_window_step = None
        self._avg_steps = {}   # keyed by chunk count (remainder batches differ)
        # Supervision seam (parallel/elastic.py): called as
        # step_callback(net, k) AFTER a dispatched item's k iterations are
        # fully accounted (params, iteration_count, listeners all
        # consistent) — the one safe place to raise control-flow out of
        # the epoch (worker-loss, preemption, mode switches, step budget).
        # Raising from a TrainingListener.iteration_done instead would
        # strand iteration_count behind params mid-item.
        self.step_callback = step_callback

    # --------------------------------------------------- tensor-parallel
    def _tp_shardings(self):
        """Param NamedSharding tree for the model axis (Megatron head/
        width split; tensor_parallel.build_param_specs). Layout hints
        only — GSPMD owns the collectives."""
        if self._tp_param_sh is None:
            self._tp_specs = build_param_specs(self.net, self.m)
            self._tp_param_sh = build_param_shardings(self.mesh,
                                                      self._tp_specs)
        return self._tp_param_sh

    def _tp_opt_shardings(self):
        """Updater-state NamedSharding tree mirroring the param specs
        (momentum/velocity slots shard with their param; scalars stay
        replicated). Materializes ``net.opt_state`` if the net has not
        trained yet — the tree's structure is the sharding's shape."""
        if self._tp_opt_sh is None:
            self._tp_shardings()
            if self.net.opt_state is None:
                self.net.opt_state = self.net.updater.init(self.net.params)
            self._tp_opt_sh = build_opt_shardings(
                self.mesh, self._tp_specs, self.net.params,
                self.net.opt_state)
        return self._tp_opt_sh

    def _auto_axes(self):
        """shard_map manual-collective builders go over 'data' only; on a
        2-D mesh the model axis stays GSPMD-managed (auto), so the tp
        layout hints on the jit boundary shard the math inside the
        manual region too."""
        return {"auto": frozenset({MODEL_AXIS})} if self.m > 1 else {}

    def _jit_manual(self, fn, feed_sh, opt_sh=None):
        """jit a shard_map-built step. 1-D path: exactly the historical
        ``jax.jit(fn, donate_argnums=(0, 2))``. 2-D path: the tp layout
        hints ride the jit boundary (params/opt model-sharded at rest,
        feeds on the data axis) so the auto model axis inside the manual
        region inherits them."""
        if self.m == 1:
            return jax.jit(fn, donate_argnums=(0, 2))
        rep = replicated(self.mesh)
        psh = self._tp_shardings()
        osh = opt_sh if opt_sh is not None else self._tp_opt_shardings()
        return jax.jit(fn, donate_argnums=(0, 2),
                       in_shardings=(psh, rep, osh, rep, rep,
                                     feed_sh, feed_sh),
                       out_shardings=(psh, rep, osh, rep))

    # ------------------------------------------------------------- sync path
    def _build_sync_step(self, feed_sharding=None):
        """Per-step all-reduce DP: jit over the mesh, batch sharded.
        ``feed_sharding`` overrides the x/y sharding (the remainder
        program passes replicated)."""
        net = self.net
        mesh = self.mesh

        def step(params, state, opt_state, it, rng, x, y):
            return train_step_math(net, params, state, opt_state, it, rng,
                                   x, y)

        rep = replicated(mesh)
        dsh = feed_sharding if feed_sharding is not None \
            else data_sharding(mesh)
        psh = self._tp_shardings() if self.m > 1 else rep
        osh = self._tp_opt_shardings() if self.m > 1 else rep
        return jax.jit(
            step, donate_argnums=(0, 2),
            in_shardings=(psh, rep, osh, rep, rep, dsh, dsh),
            out_shardings=(psh, rep, osh, rep))

    def _build_sync_window_step(self, feed_sharding=None):
        """K fused sync-DP steps in ONE jitted lax.scan program: xs/ys are
        [K, batch, ...] with the batch dim sharded on the data axis (each
        scan iteration consumes one data-sharded batch; GSPMD inserts the
        same psum as the per-step program), params/opt_state the donated
        carry, per-step losses the ys — bit-identical to K sequential
        ``_build_sync_step`` dispatches."""
        net = self.net
        mesh = self.mesh

        def window_step(params, state, opt_state, it0, base_rng, xs, ys):
            def body(carry, inp):
                params, state, opt_state, it = carry
                x, y = inp
                rng = jax.random.fold_in(base_rng, it)
                new_params, new_state, new_opt, loss = train_step_math(
                    net, params, state, opt_state, it, rng, x, y)
                return (new_params, new_state, new_opt, it + 1), loss

            (params, state, opt_state, _), losses = jax.lax.scan(
                body, (params, state, opt_state, it0), (xs, ys))
            return params, state, opt_state, losses

        rep = replicated(mesh)
        wsh = feed_sharding if feed_sharding is not None \
            else window_sharding(mesh)   # [K, batch, ...]
        psh = self._tp_shardings() if self.m > 1 else rep
        osh = self._tp_opt_shardings() if self.m > 1 else rep
        return jax.jit(
            window_step, donate_argnums=(0, 2),
            in_shardings=(psh, rep, osh, rep, rep, wsh, wsh),
            out_shardings=(psh, rep, osh, rep))

    # -------------------------------------------------- overlapped sync path
    def _grad_schedule(self):
        """Bucket schedule over the param/grad tree (built once; the grad
        tree from value_and_grad shares the params' treedef)."""
        if self._bucket_schedule is None:
            self._bucket_schedule = build_bucket_schedule(
                self.net.params, self.bucket_bytes)
            reg = get_registry()
            if reg.enabled:
                reg.gauge("parallel.bucket_count").set(
                    len(self._bucket_schedule))
        return self._bucket_schedule

    def _build_overlap_step(self):
        """Bucketed backward-overlap sync DP (parallel/overlap.py): each
        worker differentiates its local shard under shard_map, then the
        grad tree is all-reduced per ~bucket_bytes bucket — small leaves
        densified into one flat buffer per bucket (one psum launch each,
        arXiv:1905.04035), buckets packed in reverse leaf order so the
        collectives' data dependences let XLA's latency-hiding scheduler
        start ICI traffic while the backward is still producing earlier
        layers' gradients (arXiv:2004.13336) — vs the GSPMD path's
        monolithic O(leaves) post-backward sweep. State and loss ride ONE
        fused variadic pmean after the updater."""
        net = self.net
        mesh = self.mesh
        schedule = self._grad_schedule()

        def worker_step(params, state, opt_state, it, rng, x, y):
            new_params, new_state, new_opt, loss = train_step_math(
                net, params, state, opt_state, it, rng, x, y,
                grad_sync=lambda g: bucketed_pmean(g, schedule, "data"))
            new_state, loss = fused_pmean((new_state, loss), "data")
            return new_params, new_state, new_opt, loss

        rep, dsh = P(), P("data")
        fn = shard_map(worker_step, mesh=mesh,
                       in_specs=(rep, rep, rep, rep, rep, dsh, dsh),
                       out_specs=(rep, rep, rep, rep), check_vma=False,
                       **self._auto_axes())
        return self._jit_manual(fn, data_sharding(mesh))

    def _build_overlap_window_step(self):
        """K fused steps of the bucketed-overlap sync path in ONE lax.scan
        program: the scan body is ``train_step_math`` with the SAME bucket
        schedule as ``_build_overlap_step`` (the grad_sync seam carries it
        into the fused window structurally), so K fused steps stay
        bit-identical to K per-step overlap dispatches."""
        net = self.net
        mesh = self.mesh
        schedule = self._grad_schedule()

        def window_step(params, state, opt_state, it0, base_rng, xs, ys):
            def body(carry, inp):
                params, state, opt_state, it = carry
                x, y = inp
                rng = jax.random.fold_in(base_rng, it)
                new_params, new_state, new_opt, loss = train_step_math(
                    net, params, state, opt_state, it, rng, x, y,
                    grad_sync=lambda g: bucketed_pmean(g, schedule, "data"))
                new_state, loss = fused_pmean((new_state, loss), "data")
                return (new_params, new_state, new_opt, it + 1), loss

            (params, state, opt_state, _), losses = jax.lax.scan(
                body, (params, state, opt_state, it0), (xs, ys))
            return params, state, opt_state, losses

        rep, wsh = P(), P(None, "data")
        fn = shard_map(window_step, mesh=mesh,
                       in_specs=(rep, rep, rep, rep, rep, wsh, wsh),
                       out_specs=(rep, rep, rep, rep), check_vma=False,
                       **self._auto_axes())
        return self._jit_manual(fn, window_sharding(mesh))

    # --------------------------------------------------- zero sharded path
    def _zero(self) -> ZeroUpdateEngine:
        """The ZeRO engine for this net+mesh (layout built once on host;
        rebuilding only matters when the param structure changes)."""
        if self._zero_engine is None:
            self._zero_engine = ZeroUpdateEngine.from_net(
                self.net, self.mesh, stage=self.zero_stage,
                bucket_bytes=self.bucket_bytes)
        return self._zero_engine

    def gather_opt_state(self):
        """Convert ``net.opt_state`` back to the replicated per-leaf
        format (all-gather on host) — for serialization or for handing
        the net to a non-zero training path. No-op if already
        replicated."""
        if is_zero_state(self.net.opt_state):
            self.net.opt_state = self._zero().unshard_opt_state(
                self.net.opt_state)
        return self.net.opt_state

    def _build_zero_step(self, replicated_feed: bool = False):
        """Sharded-update sync DP (parallel/zero.py): grads combined via
        the engine's grad_sync (stage 1: bucketed all-reduce — the same
        launches as the overlap path; stage 2: per-bucket reduce-scatter
        at half the bytes), the updater applied to THIS worker's 1/N
        flat shard only (opt state enters [N, L] sharded on the data
        axis and stays sharded), updated params all-gathered back to
        replicated. State and loss ride ONE fused variadic pmean."""
        net = self.net
        mesh = self.mesh
        eng = self._zero()

        def worker_step(params, state, opt_state, it, rng, x, y):
            new_params, new_state, new_opt, loss = train_step_math(
                net, params, state, opt_state, it, rng, x, y,
                grad_sync=eng.grad_sync, update_fn=eng.update)
            new_state, loss = fused_pmean((new_state, loss), "data")
            return new_params, new_state, new_opt, loss

        rep = P()
        osh = P("data")                      # [N, L] state shards
        dsh = rep if replicated_feed else P("data")
        # NOTE: no auto model axis here — the engine's axis_index /
        # psum_scatter collectives only lower under a fully-manual
        # region. On a (data, model) mesh the flat update stays sharded
        # d ways over 'data' (replicated across model); params are
        # model-sharded AT REST via the jit boundary and gathered for
        # the step — the at-rest m× memory win composes, the compute
        # inside the zero step does not.
        fn = shard_map(worker_step, mesh=mesh,
                       in_specs=(rep, rep, osh, rep, rep, dsh, dsh),
                       out_specs=(rep, rep, osh, rep), check_vma=False)
        return self._jit_manual(
            fn,
            replicated(mesh) if replicated_feed else data_sharding(mesh),
            opt_sh=NamedSharding(mesh, osh))

    def _build_zero_window_step(self, replicated_feed: bool = False):
        """K fused zero-sharded steps in ONE lax.scan program: the scan
        body is ``train_step_math`` with the SAME engine seams as
        ``_build_zero_step`` (grad_sync + update_fn ride the body
        structurally), opt-state shards in the donated carry — K fused
        steps stay bit-identical to K per-step zero dispatches."""
        net = self.net
        mesh = self.mesh
        eng = self._zero()

        def window_step(params, state, opt_state, it0, base_rng, xs, ys):
            def body(carry, inp):
                params, state, opt_state, it = carry
                x, y = inp
                rng = jax.random.fold_in(base_rng, it)
                new_params, new_state, new_opt, loss = train_step_math(
                    net, params, state, opt_state, it, rng, x, y,
                    grad_sync=eng.grad_sync, update_fn=eng.update)
                new_state, loss = fused_pmean((new_state, loss), "data")
                return (new_params, new_state, new_opt, it + 1), loss

            (params, state, opt_state, _), losses = jax.lax.scan(
                body, (params, state, opt_state, it0), (xs, ys))
            return params, state, opt_state, losses

        rep, osh = P(), P("data")
        wsh = rep if replicated_feed else P(None, "data")
        # fully-manual for the same reason as _build_zero_step
        fn = shard_map(window_step, mesh=mesh,
                       in_specs=(rep, rep, osh, rep, rep, wsh, wsh),
                       out_specs=(rep, rep, osh, rep), check_vma=False)
        return self._jit_manual(
            fn,
            replicated(mesh) if replicated_feed else window_sharding(mesh),
            opt_sh=NamedSharding(mesh, osh))

    def _remainder_step_fn(self):
        """The sync step with x/y REPLICATED: serves batches whose size
        does not tile the mesh — shard_map (overlap path) and
        jit+in_shardings (GSPMD path) both enforce batch-dim
        divisibility, so a 36-sample remainder on an 8-device mesh would
        otherwise kill the epoch. Every device redundantly computes the
        full remainder batch; the update is identical to what a sharded
        dispatch would produce (GSPMD's psum over per-shard partials IS
        the full-batch reduction), matching the contract of the
        prefetcher shipping remainders unsharded and iter_windows
        dropping ragged groups to per-step. The zero path keeps its
        sharded update under the replicated feed (every device computes
        the full-batch grads, the reduce is then a no-op-by-value, the
        shard update and all-gather run as usual)."""
        if self._remainder_step is None:
            self._remainder_step = (
                self._build_zero_step(replicated_feed=True)
                if self.zero_stage else
                self._build_sync_step(feed_sharding=replicated(self.mesh)))
        return self._remainder_step

    def _remainder_window_step_fn(self):
        """Window variant of ``_remainder_step_fn`` (uniformly
        non-divisible batch sizes stack into regular windows too)."""
        if self._remainder_window_step is None:
            self._remainder_window_step = (
                self._build_zero_window_step(replicated_feed=True)
                if self.zero_stage else
                self._build_sync_window_step(
                    feed_sharding=replicated(self.mesh)))
        return self._remainder_window_step

    # ------------------------------------------------------ accumulator path
    def _build_accum_step(self):
        """Sync DP with an explicit GradientsAccumulator combining per-worker
        flat gradients inside shard_map (reference StochasticGradientDescent
        accumulator hook :67-74 + EncodingHandler exchange). The accumulator
        carry (e.g. the threshold-compression residual) is per-worker: global
        shape [n_workers, n_params] sharded on the data axis."""
        net = self.net
        mesh = self.mesh
        acc = self.gradient_accumulator
        from jax.flatten_util import ravel_pytree

        def worker_step(params, state, opt_state, acc_state, it, rng, x, y):
            def lf(p):
                return net.loss_fn(p, state, x, y, train=True, rng=rng)
            (loss, new_state), grads = jax.value_and_grad(lf, has_aux=True)(params)
            flat, unravel = ravel_pytree(grads)
            combined, new_acc = acc.combine(flat, acc_state[0], axis="data")
            # combined grads are identical on every worker, so the updater
            # math (and its replicated state) stays in lockstep
            new_params, new_opt = net.updater.update(unravel(combined),
                                                     opt_state, params, it)
            # state + loss in one variadic pmean bind (vs a per-leaf tree
            # sweep plus a separate scalar launch)
            new_state, loss = fused_pmean((new_state, loss), "data")
            return new_params, new_state, new_opt, new_acc[None], loss

        rep, dsh = P(), P("data")
        fn = shard_map(worker_step, mesh=mesh,
                       in_specs=(rep, rep, rep, dsh, rep, rep, dsh, dsh),
                       out_specs=(rep, rep, rep, dsh, rep),
                       check_vma=False)
        return jax.jit(fn, donate_argnums=(0, 2, 3))

    def _init_acc_state(self, dtype):
        size = int(self.net.num_params())
        per_worker = self.gradient_accumulator.init(size, dtype)
        if isinstance(per_worker, tuple) and per_worker == ():
            # stateless accumulator (PsumAccumulator)
            per_worker = jnp.zeros((0,), dtype)
        return jnp.broadcast_to(per_worker, (self.n,) + per_worker.shape).copy()

    # -------------------------------------------------------- averaging path
    def _build_avg_step(self, replicated_feed: bool = False):
        """K local steps per device, then pmean of params (+updater state):
        the reference's averagingFrequency semantics, one XLA program.

        ``replicated_feed``: serves batches whose size does not tile the
        mesh (e.g. after an elastic recovery shrank the mesh): every
        worker runs the SAME K full-batch steps and the pmean of
        identical trajectories is a no-op — degenerate but well-defined
        averaging, instead of the shard_map divisibility error killing
        the epoch."""
        net = self.net
        mesh = self.mesh
        K = self.averaging_frequency
        avg_upd = self.average_updaters

        def worker_steps(params, state, opt_state, it, rng, xs, ys):
            # params/state/opt live per-device (shard_map gives the local copy;
            # xs/ys: [K, local_batch, ...] — K chunks for K local steps
            def body(carry, inp):
                params, state, opt_state, i = carry
                x, y = inp

                def lf(p):
                    return net.loss_fn(p, state, x, y, train=True,
                                       rng=jax.random.fold_in(rng, i))
                (loss, new_state), grads = jax.value_and_grad(lf, has_aux=True)(params)
                new_params, new_opt = net.updater.update(grads, opt_state, params, it + i)
                return (new_params, new_state, new_opt, i + 1), loss

            (params, state, opt_state, _), losses = jax.lax.scan(
                body, (params, state, opt_state, 0), (xs, ys))
            # parameter averaging across workers (reference :332-361):
            # params, state, (opt_state) and the scalar loss all ride ONE
            # variadic pmean bind instead of three per-leaf tree sweeps
            # plus a scalar launch — same elementwise math, O(1) dispatch
            mean_loss = jnp.mean(losses)
            if avg_upd:
                params, state, opt_state, mean_loss = fused_pmean(
                    (params, state, opt_state, mean_loss), "data")
            else:
                params, state, mean_loss = fused_pmean(
                    (params, state, mean_loss), "data")
            return params, state, opt_state, mean_loss

        rep_spec = P()
        # [K, batch, ...] -> shard batch dim; replicated when it can't tile
        dsh_spec = rep_spec if replicated_feed else P(None, "data")
        fn = shard_map(worker_steps, mesh=mesh,
                       in_specs=(rep_spec, rep_spec, rep_spec, rep_spec, rep_spec,
                                 dsh_spec, dsh_spec),
                       out_specs=(rep_spec, rep_spec, rep_spec, rep_spec),
                       check_vma=False)
        return jax.jit(fn, donate_argnums=(0, 2))

    # ------------------------------------------------------------------- fit
    def fit(self, iterator, epochs: int = 1, *, skip_first_batches: int = 0):
        net = self.net
        if skip_first_batches < 0:
            raise ValueError("skip_first_batches must be >= 0")
        if net.params is None:
            net.init()
        sync = self.training_mode == "shared_gradients" or self.averaging_frequency == 1
        if sync and self.zero_stage:
            # the engine owns the opt-state format: shard a replicated
            # tree on first entry (pure redistribution), validate an
            # already-sharded one against THIS mesh's layout
            self.net.opt_state = self._zero().shard_opt_state(
                self.net.opt_state)
        if sync and self._sync_step is None:
            if self.gradient_accumulator is not None:
                self._sync_step = self._build_accum_step()
            elif self.zero_stage:
                self._sync_step = self._build_zero_step()
            elif self.overlap_sync:
                self._sync_step = self._build_overlap_step()
            else:
                self._sync_step = self._build_sync_step()
        dtype = jnp.dtype(net.conf.dtype)
        base_rng = jax.random.PRNGKey(net.conf.seed + 31337)
        perf = [l for l in net.listeners if isinstance(l, PerformanceListener)]
        if sync:
            # Device prefetch with the mesh's data sharding: batch N+1 is
            # shipped PRE-SHARDED (per-device sub-buffers land directly)
            # while step N computes, so neither the host->device hop nor
            # the GSPMD resharding sits serially inside the step. The
            # K-step averaging path below stacks K host batches into one
            # [K, B, ...] program feed instead, so it keeps the host-side
            # prefetcher. prefetch_buffer < 1 opts out of prefetching
            # (the old host wrapper treated 0 as 'unbounded', which for a
            # device-resident queue would mean unbounded HBM — refuse the
            # footprint, not the caller).
            if isinstance(iterator, DevicePrefetchIterator):
                it_wrapped = iterator
            elif self.prefetch_buffer >= 1:
                it_wrapped = DevicePrefetchIterator(
                    iterator, self.prefetch_buffer, dtype=dtype,
                    sharding=data_sharding(self.mesh))
            else:
                it_wrapped = iterator
            prefetcher = (it_wrapped
                          if isinstance(it_wrapped, DevicePrefetchIterator)
                          else None)
        else:
            # host-side prefetch only: _run_avg stacks K host batches into
            # one [K, B, ...] feed, so a device-resident batch would just
            # round-trip device->host->device. Unwrap a caller-supplied
            # DevicePrefetchIterator to its base for the same reason.
            # prefetch_buffer < 1 opts out of the async wrapper entirely
            # (ElasticTrainer's degraded mode relies on this: a background
            # producer racing a recovery-time iterator reset() would make
            # the resumed data stream nondeterministic, and Queue(0) is
            # UNBOUNDED — it would buffer the whole epoch on host).
            base = (iterator.base
                    if isinstance(iterator, DevicePrefetchIterator)
                    else iterator)
            it_wrapped = (AsyncDataSetIterator(base, self.prefetch_buffer)
                          if self.prefetch_buffer >= 1 else base)
            prefetcher = None

        # historical ParallelWrapper semantics: EVERYTHING to dtype (the
        # Solver path keeps ints instead — see cast_feed)
        def feed(v):
            return cast_feed(v, dtype, keep_ints=False)

        reg = get_registry()
        with span("fit", epochs=epochs, mode=self.training_mode,
                  devices=self.n, net="ParallelWrapper"):
            for epoch in range(epochs):
                with span("epoch", index=epoch):
                    self._fit_epoch(net, it_wrapped, prefetcher, iterator,
                                    feed, dtype, base_rng, perf, sync, reg,
                                    skip=(skip_first_batches
                                          if epoch == 0 else 0))
            if self.m > 1 and reg.enabled:
                # per-replica footprint after the layout hints settled:
                # model-sharded leaves contribute 1/m of their bytes —
                # the ≈m× reduction the tp memory claim gauges
                reg.gauge("parallel.model_axis").set(self.m)
                reg.gauge("parallel.param_bytes_per_replica").set(
                    per_replica_bytes(net.params))
                reg.gauge("parallel.opt_bytes_per_replica").set(
                    per_replica_bytes(net.opt_state))
        return net

    def _fit_epoch(self, net, it_wrapped, prefetcher, iterator, feed, dtype,
                   base_rng, perf, sync, reg, skip: int = 0):
        for l in net.listeners:
            if isinstance(l, TrainingListener):
                l.on_epoch_start(net)
        # mid-epoch resume: batches the checkpointed run already trained
        # are consumed, not dispatched (see Solver._fit_epoch)
        src = skip_batches(it_wrapped, skip) if skip else iter(it_wrapped)
        if sync:
            _t0 = time.perf_counter()
            _etl_prev_total = (prefetcher.total_wait_ms
                               if (skip and prefetcher is not None) else 0.0)
            # hoisted like Solver._fit_epoch: metric name resolution once
            # per epoch, one locked int add per iteration
            _c_iters = reg.counter("train.iterations")
            _c_windows = reg.counter("train.windows")
            # host-side collective accounting on the overlap/zero paths:
            # grad reduce launches (+ param all-gathers on zero) + the
            # fused state/loss launch, per executed step
            _c_coll = reg.counter("parallel.collective_launches")
            if self.zero_stage:
                _n_buckets = self._zero().num_reduce_launches
                _n_coll = self._zero().collectives_per_step + 1
            elif self.overlap_sync:
                _n_buckets = len(self._grad_schedule())
                _n_coll = _n_buckets + 1
            else:
                _n_buckets = _n_coll = 0
            windowed = (self.steps_per_dispatch > 1
                        and self.gradient_accumulator is None)
            stream = (iter_windows(src, self.steps_per_dispatch)
                      if windowed else src)
            for item in stream:
                if prefetcher is not None:
                    etl_ms = prefetcher.total_wait_ms - _etl_prev_total
                    _etl_prev_total = prefetcher.total_wait_ms
                else:
                    etl_ms = (time.perf_counter() - _t0) * 1e3
                if isinstance(item, BatchWindow):
                    if self._sync_window_step is None:
                        self._sync_window_step = (
                            self._build_zero_window_step()
                            if self.zero_stage else
                            self._build_overlap_window_step()
                            if self.overlap_sync
                            else self._build_sync_window_step())
                    k = len(item)
                    with span("window", k=k, iteration=net.iteration_count):
                        xs, ys, _, _ = item.stacked(cast=feed)
                        wstep = self._sync_window_step
                        n_coll = _n_coll
                        if xs.shape[1] % self.n != 0:
                            # batch size doesn't tile the mesh: dispatch
                            # the replicated window program (identical
                            # update) instead of the divisibility error
                            # (the zero remainder keeps its collectives)
                            wstep = self._remainder_window_step_fn()
                            n_coll = _n_coll if self.zero_stage else 0
                        with span("dispatch", k=k, buckets=_n_buckets):
                            (net.params, net.state, net.opt_state,
                             losses) = wstep(
                                net.params, net.state, net.opt_state,
                                jnp.asarray(net.iteration_count, jnp.int32),
                                base_rng, xs, ys)
                        device_ms = max(
                            (time.perf_counter() - _t0) * 1e3 - etl_ms, 0.0)
                        _c_windows.inc()
                        _c_iters.inc(k)
                        if n_coll:
                            _c_coll.inc(k * n_coll)
                        for p in perf:
                            p.note_window(k)
                        for i, d in enumerate(item.datasets):
                            self._notify(perf, d, losses[i],
                                         etl_wait_ms=etl_ms / k,
                                         device_ms=device_ms / k)
                            net.iteration_count += 1
                    if self.step_callback is not None:
                        self.step_callback(net, k)
                    _t0 = time.perf_counter()
                    continue
                ds = item
                # one span per single-step iteration (see Solver._fit_epoch:
                # the step IS the dispatch on this path)
                with span("step", iteration=net.iteration_count):
                    x = feed(ds.features)
                    y = feed(ds.labels)
                    rng = jax.random.fold_in(base_rng, net.iteration_count)
                    it = jnp.asarray(net.iteration_count, jnp.int32)
                    n_coll = _n_coll
                    if self.gradient_accumulator is not None:
                        if self._acc_state is None:
                            self._acc_state = self._init_acc_state(dtype)
                        (net.params, net.state, net.opt_state,
                         self._acc_state, loss) = self._sync_step(
                            net.params, net.state, net.opt_state,
                            self._acc_state, it, rng, x, y)
                    else:
                        step = self._sync_step
                        if x.shape[0] % self.n != 0:
                            # remainder batch: replicated fallback (the
                            # zero remainder keeps its collectives)
                            step = self._remainder_step_fn()
                            n_coll = _n_coll if self.zero_stage else 0
                        net.params, net.state, net.opt_state, loss = \
                            step(net.params, net.state,
                                 net.opt_state, it, rng, x, y)
                    device_ms = max(
                        (time.perf_counter() - _t0) * 1e3 - etl_ms, 0.0)
                    _c_iters.inc()
                    if n_coll:
                        _c_coll.inc(n_coll)
                    self._notify(perf, ds, loss, etl_wait_ms=etl_ms,
                                 device_ms=device_ms)
                    net.iteration_count += 1
                if self.step_callback is not None:
                    self.step_callback(net, 1)
                _t0 = time.perf_counter()
        else:
            # accumulate K batches then run the fused K-step+average program
            buf: List[Any] = []
            for ds in src:
                buf.append(ds)
                if len(buf) == self.averaging_frequency:
                    self._run_avg(buf, base_rng, dtype, perf)
                    buf = []
            if buf:
                self._run_avg(buf, base_rng, dtype, perf)
        for l in net.listeners:
            if isinstance(l, TrainingListener):
                l.on_epoch_end(net)
        if hasattr(iterator, "reset"):
            iterator.reset()

    def _run_avg(self, buf, base_rng, dtype, perf):
        net = self.net
        with span("window", k=len(buf), kind="averaging",
                  iteration=net.iteration_count):
            xs = jnp.stack([jnp.asarray(np.asarray(d.features), dtype) for d in buf])
            ys = jnp.stack([jnp.asarray(np.asarray(d.labels), dtype) for d in buf])
            rng = jax.random.fold_in(base_rng, net.iteration_count)
            # remainder batches (size not tiling the mesh) dispatch the
            # replicated-feed averaging program — same contract as the
            # sync path's remainder fallback
            rem = xs.shape[1] % self.n != 0
            key = (len(buf), rem)
            step = self._avg_steps.get(key)
            if step is None:
                step = self._avg_steps[key] = \
                    self._build_avg_step(replicated_feed=rem)
            with span("dispatch", k=len(buf)):
                net.params, net.state, net.opt_state, loss = step(
                    net.params, net.state, net.opt_state,
                    jnp.asarray(net.iteration_count, jnp.int32), rng, xs, ys)
            reg = get_registry()
            reg.counter("train.windows").inc()
            reg.counter("train.iterations").inc(len(buf))
            for p in perf:
                p.note_window(len(buf))
            for d in buf:
                self._notify(perf, d, loss)
                net.iteration_count += 1
        if self.step_callback is not None:
            self.step_callback(net, len(buf))

    def _notify(self, perf, ds, loss, etl_wait_ms: float = 0.0,
                device_ms: float = 0.0):
        net = self.net
        for p in perf:
            p.note_batch(ds.num_examples(), etl_wait_ms=etl_wait_ms,
                         device_ms=device_ms)
        for l in net.listeners:
            l.iteration_done(net, net.iteration_count, loss)
