"""ZeRO-style cross-replica sharding of the weight update.

Reference: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv 2004.13336). In plain synchronous DP every
replica holds the FULL parameter tree plus the FULL updater state and
applies the identical update N times — for Adam that is 2x params of pure
duplication per chip, the single biggest cap on model size per device.
The fix is to exploit that the post-allreduce gradients are identical
everywhere: give each replica 1/N of the flattened update problem.

    reduce-scatter(grads)  ->  each replica owns the mean gradient for
                               ITS 1/N shard (half the collective bytes
                               of an all-reduce on top)
    local shard update     ->  updater state allocated SHARD-SIZED:
                               ~mesh-size x less optimizer memory
    all-gather(params)     ->  every replica re-materializes the full,
                               identical parameter tree for the forward

"Memory-efficient array redistribution through portable collective
communication" (arXiv 2112.01075) supplies the second half: the shard
layout is plain host metadata (bucket sizes + padding), so state saved on
one mesh shape re-shards onto another by all-gather -> re-slice — which is
what elastic recovery onto a shrunk mesh needs (see
:func:`make_zero_resharder`).

Layout. Leaves are grouped by ``(dtype, update rule, lr multiplier)`` so
every group's flat update is ONE homogeneous elementwise program — no
per-element masks, and therefore trivially bit-identical to the per-leaf
``MultiLayerUpdater.update`` math. Within a group, leaves are packed into
size-targeted buckets by :func:`~.overlap.build_bucket_schedule` (the same
schedule machinery as the overlapped-sync path, so each bucket's
reduce-scatter is an independently launchable collective that XLA can
overlap with the remaining backward). Each bucket is padded to a multiple
of the mesh size; shard ``k`` of a group is the concatenation of row ``k``
of every padded bucket reshaped ``[N, lb]``.

The engine plugs into the ``grad_sync`` + ``update_fn`` seam of
``train_step_math`` (optimize/solver.py) under ``shard_map``, so the fused
``steps_per_dispatch`` scan window carries the exact same sharded update
as the per-step path — structurally, not by convention. Stage 1 keeps the
bucketed all-reduce (identical collectives to ``overlap_sync``) and
slices the local shard; stage 2 replaces it with per-bucket
``psum_scatter`` (half the bytes on the wire). Both are pinned
bit-identical to the replicated update (tests/test_zero.py).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import get_registry, span
from ..telemetry.spans import record_external_span
from .overlap import DEFAULT_BUCKET_BYTES, build_bucket_schedule

__all__ = ["ZeroUpdateEngine", "is_zero_state", "make_zero_resharder",
           "ZERO_STATE_KEY"]

ZERO_STATE_KEY = "_zero_"


def is_zero_state(opt_state: Any) -> bool:
    """True if ``opt_state`` is the engine's sharded flat format (the
    marker is structural — a dict with the single ``_zero_`` key — so the
    tree stays pure arrays and flows through jit/scan/checkpointing)."""
    return isinstance(opt_state, dict) and set(opt_state) == {ZERO_STATE_KEY}


@dataclass(frozen=True)
class _ZeroBucket:
    """One reduce-scatter launch: ``indices`` are global leaf positions
    (params flatten order), packed flat to ``nb`` elements and padded to
    ``n_shards * lb``."""
    indices: Tuple[int, ...]
    sizes: Tuple[int, ...]
    nb: int
    lb: int


@dataclass(frozen=True)
class _ZeroGroup:
    """One homogeneous flat update: every member leaf shares ``dtype``,
    update ``rule`` and ``lr_mult``, so the whole shard updates as one
    elementwise program with a single traced-scalar learning rate."""
    rule: Any
    lr_mult: float
    dtype: Any
    buckets: Tuple[_ZeroBucket, ...]
    length: int                      # local shard elements (incl. padding)
    state_keys: Tuple[str, ...]


def _leaf_meta_from_net(net):
    """Per-leaf (rule-or-None, lr_mult, frozen_rule-or-None) aligned with
    ``jax.tree.leaves(net.params)``, derived from the updater's per-layer
    conf dispatch (``rule_for`` / ``_lr_mult``) via tree paths — the same
    resolution ``MultiLayerUpdater.update`` performs per leaf. A ``None``
    rule marks a frozen layer's leaf (excluded from the sharded update,
    params pass through untouched — the reference FrozenLayer contract);
    its underlying rule is returned separately so unshard can rebuild the
    init-shaped state the replicated format allocates for it."""
    upd = net.updater
    if getattr(upd, "grad_norm", None) not in (None, "none"):
        raise ValueError(
            "zero sharded update does not compose with gradient "
            "normalization: the per-layer norms need every full leaf, "
            "which no replica holds after the reduce-scatter — disable "
            "grad_norm or the zero_stage")
    paths, _ = jax.tree_util.tree_flatten_with_path(net.params)
    rules, mults, frozen = [], [], []
    for path, _leaf in paths:
        li = path[0].idx
        pname = path[1].key
        conf = upd.layer_confs[li]
        if getattr(conf, "frozen", False):
            rules.append(None)
            mults.append(1.0)
            frozen.append(upd.rule_for(conf))
            continue
        rules.append(upd.rule_for(conf))
        mults.append(float(upd._lr_mult(conf, pname)))
        frozen.append(None)
    return rules, mults, frozen


def _index_path(tree, path):
    """Follow a jax key path (SequenceKey/DictKey/GetAttrKey) into a
    pytree."""
    for k in path:
        if hasattr(k, "idx"):
            tree = tree[k.idx]
        elif hasattr(k, "key"):
            tree = tree[k.key]
        else:
            tree = getattr(tree, k.name)
    return tree


class ZeroUpdateEngine:
    """Sharded-update engine over one named mesh axis.

        eng = ZeroUpdateEngine.from_net(net, mesh, stage=2)
        ... inside shard_map:
        train_step_math(..., grad_sync=eng.grad_sync, update_fn=eng.update)

    ``stage=1``: grads are all-reduced per packed bucket (the same
    launch pattern as ``overlap_sync``) and each replica slices its
    shard; only the updater state is shard-sized. ``stage=2``: grads are
    reduce-scattered per bucket (``psum_scatter`` — each replica only
    ever receives its 1/N of the mean gradient, halving collective bytes
    vs the all-reduce). Both stages end in the same all-gather of
    updated params and are bit-identical to the replicated update on the
    test backend."""

    def __init__(self, params, rules, lr_mults, *, n_shards: int,
                 stage: int = 1, bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 axis: str = "data", mesh=None, frozen_rules=None):
        if stage not in (1, 2):
            raise ValueError(f"zero stage must be 1 or 2, got {stage}")
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
        leaves = [l for _, l in paths_leaves]
        if len(rules) != len(leaves) or len(lr_mults) != len(leaves):
            raise ValueError("rules/lr_mults must align with the params "
                             "leaves")
        self.n = int(n_shards)
        self.stage = stage
        self.axis = axis
        self.mesh = mesh
        self.bucket_bytes = bucket_bytes
        self.treedef = treedef
        self.leaf_paths = [p for p, _ in paths_leaves]
        self.leaf_shapes = [tuple(np.shape(l)) for l in leaves]
        self.leaf_dtypes = [jnp.asarray(l).dtype if not hasattr(l, "dtype")
                            else l.dtype for l in leaves]
        # frozen leaves keep their (never-updated) rule so unshard can
        # rebuild the init-shaped state the replicated format holds
        self.frozen_rules = (list(frozen_rules) if frozen_rules is not None
                             else [None] * len(leaves))
        self.groups = self._build_groups(leaves, rules, lr_mults)
        self._publish_gauges()

    @classmethod
    def from_net(cls, net, mesh, *, stage: int = 1,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 axis: str = "data") -> "ZeroUpdateEngine":
        rules, mults, frozen = _leaf_meta_from_net(net)
        # shard over the named axis only: on a (data, model) mesh the
        # update is sharded d ways along 'data' and replicated across
        # the model axis (identical to the 1-D layout on a 1-D mesh)
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        n_shards = int(sizes.get(axis, mesh.devices.size))
        return cls(net.params, rules, mults, n_shards=n_shards,
                   stage=stage, bucket_bytes=bucket_bytes, axis=axis,
                   mesh=mesh, frozen_rules=frozen)

    # ----------------------------------------------------------- layout
    def _build_groups(self, leaves, rules, lr_mults) -> Tuple[_ZeroGroup, ...]:
        order: List[tuple] = []
        members: Dict[tuple, List[int]] = {}
        for i, (rule, mult) in enumerate(zip(rules, lr_mults)):
            if rule is None:        # frozen: params pass through untouched
                continue
            key = (self.leaf_dtypes[i], rule, mult)
            if key not in members:
                members[key] = []
                order.append(key)
            members[key].append(i)
        groups = []
        for key in order:
            dtype, rule, mult = key
            idxs = members[key]
            sched = build_bucket_schedule([leaves[i] for i in idxs],
                                          self.bucket_bytes)
            buckets = []
            for b in sched.buckets:
                gidx = tuple(idxs[j] for j in b.indices)
                sizes = tuple(int(np.prod(self.leaf_shapes[i], dtype=np.int64))
                              for i in gidx)
                nb = sum(sizes)
                lb = -(-nb // self.n)        # ceil
                buckets.append(_ZeroBucket(gidx, sizes, nb, lb))
            length = sum(b.lb for b in buckets)
            state_keys = tuple(sorted(
                rule.init_one(jnp.zeros((1,), dtype)).keys()))
            groups.append(_ZeroGroup(rule, mult, dtype, tuple(buckets),
                                     length, state_keys))
        return tuple(groups)

    @property
    def num_reduce_launches(self) -> int:
        """Collective launches in the grad sync phase of one step (one
        per bucket, both stages)."""
        return sum(len(g.buckets) for g in self.groups)

    @property
    def collectives_per_step(self) -> int:
        """reduce launches + one all-gather per group (the fused
        state/loss pmean is the caller's extra launch)."""
        return self.num_reduce_launches + len(self.groups)

    @property
    def shard_state_bytes(self) -> int:
        """Per-replica updater-state bytes under sharding (the number the
        zero_sharded_update bench row reports against the replicated
        allocation)."""
        return sum(g.length * g.dtype.itemsize * len(g.state_keys)
                   for g in self.groups)

    @property
    def replicated_state_bytes(self) -> int:
        """What the same updater state costs per replica unsharded."""
        return sum(sum(b.nb for b in g.buckets) * g.dtype.itemsize
                   * len(g.state_keys) for g in self.groups)

    @property
    def gathered_bytes(self) -> int:
        """Bytes all-gathered per step (padded param shards, all groups)."""
        return sum(g.length * self.n * g.dtype.itemsize for g in self.groups)

    def _publish_gauges(self) -> None:
        reg = get_registry()
        if reg.enabled:
            reg.gauge("zero.shard_bytes").set(float(self.shard_state_bytes))
            reg.gauge("zero.gathered_bytes").set(float(self.gathered_bytes))
            reg.gauge("zero.groups").set(float(len(self.groups)))

    def sharding_meta(self) -> dict:
        """The checkpoint-manifest ``sharding`` block: enough host
        metadata to rebuild the exact shard layout (and to re-shard it
        onto a different mesh size — bucket element counts are
        mesh-size-independent, only ``lb`` padding changes)."""
        return {"format": "zero-flat", "axis": self.axis,
                "num_shards": self.n, "stage": self.stage,
                "bucket_bytes": int(self.bucket_bytes),
                "groups": [{"dtype": str(g.dtype),
                            "state_keys": list(g.state_keys),
                            "bucket_elems": [b.nb for b in g.buckets]}
                           for g in self.groups]}

    def meta_matches(self, meta: Optional[dict]) -> bool:
        """True if a manifest ``sharding`` block describes THIS layout
        (same mesh size and same per-group bucketing) — i.e. the saved
        state restores directly, no re-shard needed."""
        if not meta or meta.get("format") != "zero-flat":
            return False
        mine = self.sharding_meta()
        return (meta.get("num_shards") == mine["num_shards"]
                and meta.get("axis") == mine["axis"]
                and meta.get("groups") == mine["groups"])

    # ------------------------------------------------- traced pack/unpack
    def _pack_bucket(self, b: _ZeroBucket, leaves):
        """Flatten + pad one bucket's leaves to ``[n, lb]``."""
        if len(b.indices) == 1:
            flat = jnp.ravel(leaves[b.indices[0]])
        else:
            flat = jnp.concatenate([jnp.ravel(leaves[i]) for i in b.indices])
        pad = self.n * b.lb - b.nb
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        return flat.reshape(self.n, b.lb)

    def _pack_group_local(self, g: _ZeroGroup, leaves, k):
        """This replica's shard of the group: row ``k`` of every padded
        bucket, concatenated."""
        parts = [jax.lax.dynamic_index_in_dim(self._pack_bucket(b, leaves),
                                              k, 0, keepdims=False)
                 for b in g.buckets]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def _unpack_group(self, g: _ZeroGroup, full, out: list) -> None:
        """Scatter the all-gathered ``[n, length]`` group back into the
        param leaf list (row-major ``[n, lb]`` is exactly the padded
        bucket layout)."""
        off = 0
        for b in g.buckets:
            flat = full[:, off:off + b.lb].reshape(self.n * b.lb)
            pos = 0
            for i, size in zip(b.indices, b.sizes):
                out[i] = flat[pos:pos + size].reshape(self.leaf_shapes[i])
                pos += size
            off += b.lb

    # --------------------------------------------------- the update seam
    def grad_sync(self, grads):
        """The cross-replica gradient combine (must run with ``axis`` in
        scope, i.e. inside shard_map): per-group local mean-gradient
        shards, one collective launch per bucket — each an independent
        collective XLA can start while the backward still computes (the
        overlap_sync scheduling argument, same bucket machinery).
        Stage 1 all-reduces the packed bucket and slices this replica's
        row (full-bytes exchange, as arXiv 2004.13336's baseline
        sharding); stage 2 replaces it with ``psum_scatter`` so each
        replica only ever RECEIVES its 1/N of the mean gradient — half
        the bytes on the wire, elementwise the same reduction (pinned
        bit-identical). Both stages share one packing graph, so the
        backward fuses identically whichever collective is picked."""
        g_leaves, treedef = jax.tree.flatten(grads)
        if treedef != self.treedef:
            raise ValueError("grad tree does not match the zero layout — "
                             "rebuild the engine when the parameter "
                             "structure changes")
        shards = []
        for g in self.groups:
            parts = []
            for b in g.buckets:
                packed = self._pack_bucket(b, g_leaves)
                if self.stage == 1:
                    red = jax.lax.pmean(packed, self.axis)
                    k = jax.lax.axis_index(self.axis)
                    parts.append(jax.lax.dynamic_index_in_dim(
                        red, k, 0, keepdims=False))
                else:
                    parts.append(jax.lax.psum_scatter(
                        packed, self.axis, scatter_dimension=0,
                        tiled=False) / self.n)
            shards.append(parts[0] if len(parts) == 1
                          else jnp.concatenate(parts))
        return tuple(shards)

    def update(self, grads, opt_state, params, step):
        """Drop-in for ``MultiLayerUpdater.update`` under shard_map:
        apply the update rule to THIS replica's shard only (state is
        shard-sized), then all-gather the updated params. ``grads`` is
        whatever :meth:`grad_sync` produced. The per-element math is the
        per-leaf updater math verbatim — same rule, same traced-scalar
        lr, same dtype casts — so the gathered params are bit-identical
        to the replicated path."""
        if not is_zero_state(opt_state):
            raise ValueError(
                "zero update needs the engine's sharded opt state — "
                "convert with shard_opt_state() before dispatch")
        leaves, treedef = jax.tree.flatten(params)
        if treedef != self.treedef:
            raise ValueError("param tree does not match the zero layout — "
                             "rebuild the engine when the parameter "
                             "structure changes")
        st = opt_state[ZERO_STATE_KEY]
        k = jax.lax.axis_index(self.axis)
        out = list(leaves)
        new_st = []
        for gi, g in enumerate(self.groups):
            g_loc = grads[gi]
            p_loc = self._pack_group_local(g, leaves, k)
            s_loc = {key: v[0] for key, v in st[gi].items()}
            lr = g.rule.lr(step, g.lr_mult)
            upd, ns = g.rule.update_one(g_loc, s_loc, lr, step)
            new_loc = p_loc - upd.astype(p_loc.dtype)
            new_st.append({key: ns[key].astype(s_loc[key].dtype)[None]
                           for key in s_loc})
            full = jax.lax.all_gather(new_loc, self.axis, axis=0,
                                      tiled=False)
            self._unpack_group(g, full, out)
        return jax.tree.unflatten(treedef, out), \
            {ZERO_STATE_KEY: tuple(new_st)}

    # ------------------------------------------- host-side state plumbing
    def _sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P(self.axis))

    def _place(self, arr):
        sh = self._sharding()
        return jax.device_put(arr, sh) if sh is not None else jnp.asarray(arr)

    def init_opt_state(self) -> dict:
        """Fresh (zeros) sharded updater state — the ``like`` tree for
        checkpoint restore, and the init for a net that has none yet."""
        groups = []
        for g in self.groups:
            groups.append({key: self._place(
                np.zeros((self.n, g.length), jnp.dtype(g.dtype)))
                for key in g.state_keys})
        return {ZERO_STATE_KEY: tuple(groups)}

    def shard_opt_state(self, opt_state) -> dict:
        """Pack a replicated per-leaf updater-state tree (the
        ``MultiLayerUpdater.init`` format) into the sharded flat format.
        Pure redistribution for every updated leaf —
        ``unshard_opt_state()`` round-trips them bitwise. A frozen leaf's
        state is not stored (the update never touches it; unshard
        rebuilds its init zeros) — NONZERO frozen state is refused
        loudly rather than silently zeroed."""
        if is_zero_state(opt_state):
            self.check_state(opt_state)
            return opt_state
        flat_state = self._leaf_state_list(opt_state)
        for i, fr in enumerate(self.frozen_rules):
            if fr is None:
                continue
            for key, v in flat_state[i].items():
                if np.any(np.asarray(v)):
                    raise ValueError(
                        f"frozen leaf {i} carries nonzero updater state "
                        f"({key!r}); the sharded format does not store "
                        f"frozen state (it is never updated) — zero it "
                        f"or unfreeze the layer before zero_stage "
                        f"training")
        groups = []
        for g in self.groups:
            per_key = {}
            for key in g.state_keys:
                rows = []
                for b in g.buckets:
                    flat = np.concatenate(
                        [np.asarray(flat_state[i][key]).ravel()
                         for i in b.indices])
                    pad = self.n * b.lb - b.nb
                    if pad:
                        flat = np.concatenate(
                            [flat, np.zeros((pad,), flat.dtype)])
                    rows.append(flat.reshape(self.n, b.lb))
                per_key[key] = self._place(np.concatenate(rows, axis=1))
            groups.append(per_key)
        return {ZERO_STATE_KEY: tuple(groups)}

    def unshard_opt_state(self, opt_state):
        """Rebuild the replicated per-leaf state tree from the sharded
        format (all-gather on host): the ``MultiLayerUpdater.init``
        shape. Frozen leaves get their rule's init (zeros) state back —
        the update never touched it, and ``shard_opt_state`` refused any
        nonzero frozen state — so the result serializes/loads like an
        ``updater.init`` tree; stateless leaves stay empty dicts."""
        self.check_state(opt_state)
        flat_state = [None] * len(self.leaf_shapes)
        for gi, g in enumerate(self.groups):
            for key in g.state_keys:
                full = np.asarray(opt_state[ZERO_STATE_KEY][gi][key])
                off = 0
                for b in g.buckets:
                    flat = full[:, off:off + b.lb].reshape(self.n * b.lb)
                    pos = 0
                    for i, size in zip(b.indices, b.sizes):
                        d = flat_state[i] or {}
                        d[key] = jnp.asarray(
                            flat[pos:pos + size].reshape(
                                self.leaf_shapes[i]))
                        flat_state[i] = d
                        pos += size
                    off += b.lb
        for i in range(len(flat_state)):
            if flat_state[i] is not None:
                continue
            fr = self.frozen_rules[i]
            if fr is not None:              # frozen: init-shaped zeros
                flat_state[i] = fr.init_one(
                    jnp.zeros(self.leaf_shapes[i], self.leaf_dtypes[i]))
            else:                           # stateless rule
                flat_state[i] = {}
        # re-nest per-leaf state dicts into the params treedef (each
        # param leaf position holds its state dict)
        return jax.tree.unflatten(self.treedef, flat_state)

    def _leaf_state_list(self, opt_state):
        """Per-param-leaf state dicts, aligned with the params flatten
        order: the replicated format mirrors the params containers with a
        ``{state_key: arr}`` dict at every param-leaf position, so each
        param leaf's PATH indexes its state dict directly. (Flattening
        with an is_leaf predicate instead cannot tell a stateless leaf's
        ``{}`` from a parameterless layer's empty container.)"""
        try:
            out = [_index_path(opt_state, p) for p in self.leaf_paths]
        except (KeyError, IndexError, TypeError) as e:
            raise ValueError(
                "replicated opt state does not align with the zero "
                "layout's param tree — was it built by this net's "
                f"updater.init? ({e})") from e
        if not all(isinstance(s, dict) for s in out):
            raise ValueError(
                "replicated opt state does not align with the zero "
                "layout's param tree: expected a {state_key: array} dict "
                "at every param-leaf position")
        return out

    def check_state(self, opt_state) -> None:
        """Validate a zero-format state against THIS layout (mesh size
        and group lengths) — a state restored for a different mesh must
        be re-sharded, not silently mis-sliced."""
        if not is_zero_state(opt_state):
            raise ValueError("not a zero sharded opt state")
        st = opt_state[ZERO_STATE_KEY]
        if len(st) != len(self.groups):
            raise ValueError(
                f"zero state has {len(st)} groups, layout has "
                f"{len(self.groups)} — re-shard it for this mesh")
        for g, s in zip(self.groups, st):
            if set(s) != set(g.state_keys):
                raise ValueError(
                    f"zero state keys {sorted(s)} != layout "
                    f"{sorted(g.state_keys)}")
            for key, v in s.items():
                if tuple(v.shape) != (self.n, g.length):
                    raise ValueError(
                        f"zero state leaf {key} has shape "
                        f"{tuple(v.shape)}, layout wants "
                        f"{(self.n, g.length)} — state saved on a "
                        f"different mesh size must be re-sharded "
                        f"(make_zero_resharder)")

    def reshard_state_leaf(self, gi: int, old_arr: np.ndarray,
                           old_n: int) -> np.ndarray:
        """Re-slice one group's state array saved on an ``old_n``-shard
        mesh into THIS layout (all-gather -> unpad per old bucket ->
        repad per new bucket) — arXiv 2112.01075's portable
        redistribution, done on host at restore time."""
        g = self.groups[gi]
        old_lbs = [-(-b.nb // old_n) for b in g.buckets]
        if old_arr.shape != (old_n, sum(old_lbs)):
            raise ValueError(
                f"state array shape {old_arr.shape} does not match an "
                f"{old_n}-shard layout of group {gi} "
                f"({(old_n, sum(old_lbs))})")
        rows, off = [], 0
        for b, old_lb in zip(g.buckets, old_lbs):
            flat = old_arr[:, off:off + old_lb].reshape(old_n * old_lb)[:b.nb]
            pad = self.n * b.lb - b.nb
            if pad:
                flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
            rows.append(flat.reshape(self.n, b.lb))
            off += old_lb
        return np.concatenate(rows, axis=1)

    # ----------------------------------------------------------- profiling
    def profile(self, mesh=None, repeats: int = 3) -> dict:
        """Time each bucket's grad collective — THIS stage's collective:
        the stage-2 ``psum_scatter`` (events named ``reduce_scatter``) or
        the stage-1 bucket all-reduce + slice (``grad_allreduce``) — and
        each group's all-gather on the mesh (tiny jitted programs,
        best-of-``repeats``), emit cat="collective" trace events that
        tools/trace2summary.py folds into their own phase buckets, the
        gather half under a ``zero.allgather`` span, and refresh the
        ``zero.*`` gauges. Per-row ``bytes`` is the padded buffer the
        collective actually moves. Host-side tooling for bench/dryrun —
        the training step never calls this."""
        from jax.sharding import PartitionSpec as P
        from .mesh import shard_map
        mesh = mesh if mesh is not None else self.mesh
        if mesh is None:
            raise ValueError("profile() needs a mesh")
        reg = get_registry()
        reduce_name = "reduce_scatter" if self.stage == 2 else \
            "grad_allreduce"

        def scat(x):
            if self.stage == 1:
                red = jax.lax.pmean(x, self.axis)
                k = jax.lax.axis_index(self.axis)
                return jax.lax.dynamic_index_in_dim(red, k, 0)
            return jax.lax.psum_scatter(
                x, self.axis, scatter_dimension=0, tiled=False)[None] / self.n

        def gath(x):
            return jax.lax.all_gather(x[0], self.axis, axis=0, tiled=False)

        # ONE jitted callable per collective flavor, hoisted out of the
        # loops: jax's jit cache then compiles once per distinct
        # (shape, dtype) instead of once per bucket (real schedules
        # repeat bucket shapes — same fix as overlap.profile_schedule)
        jscat = jax.jit(shard_map(scat, mesh=mesh, in_specs=P(),
                                  out_specs=P(self.axis), check_vma=False))
        jgath = jax.jit(shard_map(gath, mesh=mesh, in_specs=P(),
                                  out_specs=P(), check_vma=False))

        def timed(jfn, buf):
            jax.block_until_ready(jfn(buf))
            best = float("inf")
            for _ in range(max(1, repeats)):
                t0 = time.perf_counter()
                jax.block_until_ready(jfn(buf))
                best = min(best, time.perf_counter() - t0)
            return best * 1e3

        rows = {"reduce_scatter": [], "all_gather": []}
        rs_ms = 0.0
        for gi, g in enumerate(self.groups):
            for bi, b in enumerate(g.buckets):
                buf = jnp.zeros((self.n, b.lb), g.dtype)
                ms = timed(jscat, buf)
                rs_ms += ms
                nbytes = self.n * b.lb * g.dtype.itemsize
                rows["reduce_scatter"].append(
                    {"group": gi, "bucket": bi, "bytes": nbytes,
                     "ms": round(ms, 4)})
                record_external_span(reduce_name, ms, cat="collective",
                                     bucket=bi, group=gi, bytes=nbytes)
        ag_ms = 0.0
        with span("zero.allgather", groups=len(self.groups)):
            for gi, g in enumerate(self.groups):
                buf = jnp.zeros((self.n, g.length), g.dtype)
                ms = timed(jgath, buf)
                ag_ms += ms
                rows["all_gather"].append(
                    {"group": gi,
                     "bytes": g.length * self.n * g.dtype.itemsize,
                     "ms": round(ms, 4)})
                record_external_span("all_gather", ms, cat="collective",
                                     group=gi,
                                     bytes=g.length * self.n
                                     * g.dtype.itemsize)
        self._publish_gauges()
        if reg.enabled:
            reg.gauge("zero.reduce_scatter_ms").set(rs_ms)
            reg.gauge("zero.allgather_ms").set(ag_ms)
        return {"reduce_scatter": rows["reduce_scatter"],
                "all_gather": rows["all_gather"],
                "reduce_scatter_ms": round(rs_ms, 4),
                "allgather_ms": round(ag_ms, 4),
                "shard_state_bytes": self.shard_state_bytes,
                "replicated_state_bytes": self.replicated_state_bytes}


def make_zero_resharder(engine: ZeroUpdateEngine):
    """A ``resharder`` for ``restore_latest_sharded_checkpoint``: when a
    checkpoint's manifest ``sharding`` block describes a DIFFERENT mesh
    size than ``engine``'s layout, rebuild the whole tree from the raw
    per-shard blocks on host, re-slicing every zero state array to the
    current layout (all-gather -> re-slice) and re-homing every other
    leaf onto its ``like`` sharding. Returns ``None`` when the saved
    layout already matches (caller restores directly). Needs every shard
    file visible (shared storage) — the elastic single-controller
    deployment this repo targets."""

    def _reshard(directory: str, step: int, like, manifest: dict):
        meta = (manifest or {}).get("sharding")
        if not meta or meta.get("format") != "zero-flat":
            return None
        if engine.meta_matches(meta):
            return None
        mine = engine.sharding_meta()
        if [g["bucket_elems"] for g in meta.get("groups", [])] != \
                [g["bucket_elems"] for g in mine["groups"]]:
            raise ValueError(
                "checkpoint zero layout has different group bucketing "
                "than the current engine (different net or bucket_bytes) "
                "— cannot re-shard")
        from ..util.distributed_checkpoint import load_checkpoint_arrays
        old_n = int(meta["num_shards"])
        leaves_np = load_checkpoint_arrays(directory, step)
        like_leaves, treedef = jax.tree.flatten(like)
        if len(leaves_np) != len(like_leaves):
            raise ValueError(
                f"checkpoint has {len(leaves_np)} leaves; 'like' tree "
                f"has {len(like_leaves)}")
        # zero state leaves appear in group order, one per state key —
        # the only leaves whose shapes legitimately differ from `like`
        expected = [gi for gi, g in enumerate(engine.groups)
                    for _ in g.state_keys]
        out, zi = [], 0
        for ln, lk in zip(leaves_np, like_leaves):
            shape = tuple(np.shape(lk))
            dtype = getattr(lk, "dtype", ln.dtype)
            if ln.shape == shape:
                arr = ln
            else:
                if zi >= len(expected):
                    raise ValueError(
                        f"unexpected shape mismatch: checkpoint "
                        f"{ln.shape} vs like {shape}")
                arr = engine.reshard_state_leaf(expected[zi], ln, old_n)
                zi += 1
                if arr.shape != shape:
                    raise ValueError(
                        f"re-sharded state {arr.shape} still does not "
                        f"match like {shape}")
            arr = arr.astype(dtype, copy=False)
            sharding = getattr(lk, "sharding", None)
            out.append(jax.device_put(arr, sharding)
                       if sharding is not None else jnp.asarray(arr))
        return jax.tree.unflatten(treedef, out)

    return _reshard
