"""Elastic fault-tolerant training: the supervised step loop.

Reference: the Spark + VoidParameterServer layer (SharedTrainingMaster,
ParameterAveragingTrainingMaster — SURVEY.md §5.3-5.4) is the one major
reference surface the TPU build hadn't reproduced: training that survives
a real cluster, where workers get preempted, interconnects degrade, and
checkpoints get truncated mid-write. :class:`ElasticTrainer` wraps
``ParallelWrapper.fit`` in a supervised step loop that:

  - **checkpoints asynchronously** (``util/async_checkpoint``): a
    background-thread writer over the sharded-checkpoint format, with a
    latest-wins queue — the step loop never blocks on the device OR the
    filesystem (same sync-free discipline as the deferred-score listener
    protocol; pinned by the HostSyncDetector tripwire test).
  - **recovers from worker loss**: on a detected loss the coordinator
    re-forms a (possibly smaller) mesh — retry/backoff via
    ``util/retry`` on coordination flakes — and resumes from the newest
    checkpoint that actually restores, walking past truncated/corrupt
    saves. A re-formed SAME-shape mesh resumes bit-identically to an
    uninterrupted run; a smaller mesh resumes within float tolerance
    (the psum over per-shard partials is the same full-batch reduction
    in a different association order).
  - **degrades instead of stalling** (SparkNet, arXiv 1511.06051): when
    the per-step sync latency estimate exceeds ``sync_latency_budget_ms``
    the loop switches to K-step parameter-averaging windows
    (``training_mode="averaging"``) so one collective amortizes over K
    steps, and switches back once the interconnect recovers.
  - **exits preemption cleanly**: SIGTERM (or an injected
    :class:`~.faults.PreemptAt`) sets a flag the loop polls at step
    boundaries; a final checkpoint is flushed synchronously and ``fit``
    returns with ``trainer.preempted = True``.

Faults are injectable deterministically (``parallel/faults.py``) so all
of the above is *proved* by tier-1 tests rather than hoped for — the
``elastic.*`` counters/gauges/histograms and ``elastic.recover`` spans
give the same evidence in production.
"""
from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from ..telemetry import get_registry, span
from ..telemetry.flightrec import get_flight_recorder
from ..telemetry.tracecontext import (current_trace_context, event,
                                      new_trace_context, use_trace_context)
from ..util.async_checkpoint import AsyncCheckpointWriter, PreemptionGuard
from ..util.distributed_checkpoint import (latest_sharded_step,
                                           restore_latest_sharded_checkpoint)
from ..util.retry import RetryError, RetryPolicy
from .data_parallel import ParallelWrapper
from .faults import CoordinationError, FaultInjector, WorkerLostError
from .mesh import make_mesh, replicated
from .overlap import DEFAULT_BUCKET_BYTES
from .resharding import make_any_resharder
from .tensor_parallel import (build_opt_shardings, build_param_shardings,
                              build_param_specs, model_axis_size)
from .zero import ZeroUpdateEngine

log = logging.getLogger("deeplearning4j_tpu")

__all__ = ["ElasticTrainer", "RecoveryFailedError"]


class RecoveryFailedError(RuntimeError):
    """Recovery exhausted its retry budget / max_recoveries / workers."""


# control-flow signals raised from the step callback (the only point
# where params, iteration_count, and listeners are mutually consistent)
class _StopRun(Exception):
    pass


class _Preempted(Exception):
    pass


class _ModeSwitch(Exception):
    def __init__(self, to: str):
        super().__init__(f"switch to {to}")
        self.to = to


def _default_retry_policy() -> RetryPolicy:
    return RetryPolicy(max_attempts=4, base_delay_s=0.05, max_delay_s=0.5,
                       retryable=lambda e: isinstance(
                           e, (CoordinationError, OSError)))


class ElasticTrainer:
    """Supervised elastic step loop over :class:`ParallelWrapper`.

        trainer = ElasticTrainer(net, checkpoint_dir="/ckpt",
                                 checkpoint_every_n_steps=50)
        with trainer.preemption_guard():
            trainer.fit(iterator, num_steps=10_000)

    The iterator is treated as an epoch stream that is ``reset()`` and
    re-run until ``num_steps`` supervised steps have completed; after a
    recovery the loop resumes at the restored step, skipping the
    already-trained prefix of the epoch (``skip_first_batches`` — the
    position is persisted in the checkpoint manifest, so resume never
    replays an epoch).

    ``prefetch_buffer`` defaults to 0 (no device prefetch): a recovery
    aborts the epoch mid-stream, and a background prefetcher racing the
    iterator ``reset()`` would make the resumed data stream
    nondeterministic. Pass >0 only with an iterator that tolerates
    concurrent pulls.

    Results after ``fit``: ``steps_done``, ``recoveries``,
    ``degraded_transitions``, ``mode_history``, ``preempted``,
    ``last_recovery_ms``.
    """

    def __init__(self, net, *, checkpoint_dir: Optional[str] = None,
                 devices: Optional[List] = None,
                 mesh_shape: Optional[tuple] = None,
                 checkpoint_every_n_steps: int = 50, keep_last: int = 3,
                 steps_per_dispatch: int = 1, prefetch_buffer: int = 0,
                 max_recoveries: int = 8,
                 retry_policy: Optional[RetryPolicy] = None,
                 sync_latency_budget_ms: Optional[float] = None,
                 latency_window: int = 4,
                 degraded_averaging_window: int = 8,
                 degraded_exit_patience: int = 2,
                 final_checkpoint: bool = True,
                 fault_injector: Optional[FaultInjector] = None,
                 zero_stage: int = 0,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 registry=None):
        self.net = net
        # ZeRO sharded update (parallel/zero.py): the supervised sync
        # loop runs ParallelWrapper(zero_stage=...); the sharded updater
        # state flows through the async checkpoint writer with its
        # shard-layout block in the manifest, and a mesh that shrinks
        # after worker loss RE-SHARDS the state on restore (all-gather ->
        # re-slice) instead of aborting. The SparkNet degraded mode
        # averages full per-worker state trajectories, which sharded
        # state cannot represent — refuse the combination loudly.
        if zero_stage and sync_latency_budget_ms is not None:
            raise ValueError(
                "zero_stage does not compose with the degraded "
                "averaging-window mode (sync_latency_budget_ms): "
                "averaging needs full per-worker updater state")
        self.zero_stage = zero_stage
        self.bucket_bytes = bucket_bytes
        self._engines = {}               # mesh-size -> ZeroUpdateEngine
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_n_steps = checkpoint_every_n_steps
        self.keep_last = keep_last
        self.steps_per_dispatch = steps_per_dispatch
        self.prefetch_buffer = prefetch_buffer
        self.max_recoveries = max_recoveries
        self.sync_latency_budget_ms = sync_latency_budget_ms
        self.latency_window = max(1, latency_window)
        self.degraded_averaging_window = max(2, degraded_averaging_window)
        self.degraded_exit_patience = max(1, degraded_exit_patience)
        self.final_checkpoint = final_checkpoint
        self._injector = fault_injector
        self._retry = retry_policy or _default_retry_policy()
        self._reg = registry if registry is not None else get_registry()
        self._all_devices = list(devices if devices is not None
                                 else jax.devices())
        # (data, model) tensor-parallel mesh (tensor_parallel.py). The
        # degraded averaging mode holds full per-worker param copies,
        # which a model-sharded layout cannot represent — refuse the
        # combination like the zero one above.
        if mesh_shape is not None and len(mesh_shape) not in (1, 2):
            raise ValueError(f"mesh_shape must be (d,) or (d, m), "
                             f"got {mesh_shape}")
        self._mesh_shape = tuple(mesh_shape) if mesh_shape else None
        if self._mesh_shape is not None and len(self._mesh_shape) == 2 \
                and self._mesh_shape[1] > 1 \
                and sync_latency_budget_ms is not None:
            raise ValueError(
                "a model-sharded mesh does not compose with the degraded "
                "averaging-window mode (sync_latency_budget_ms): "
                "averaging needs full per-worker param copies")
        if self._mesh_shape is not None:
            need = 1
            for s in self._mesh_shape:
                need *= int(s)
            if need > len(self._all_devices):
                raise ValueError(f"mesh_shape {self._mesh_shape} needs "
                                 f"{need} devices, have "
                                 f"{len(self._all_devices)}")
            self._devices = list(self._all_devices[:need])
        else:
            self._devices = list(self._all_devices)
        self._mesh = self._mesh_for(self._devices)
        self._wrappers = {}
        self._writer: Optional[AsyncCheckpointWriter] = None
        self._preempt_flag = False
        self._epoch_len: Optional[int] = None
        self._skip_next: Optional[int] = None
        self._pass_start = 0
        self._pass_skip = 0
        self._num_steps = 0
        self._next_ckpt_step = 0
        self._lat = deque(maxlen=self.latency_window)
        self._ok_items = 0
        self._t_item = 0.0
        # results
        self.mode = "sync"
        self.recoveries = 0
        self.degraded_transitions = 0
        self.mode_history: List[tuple] = []
        self.preempted = False
        self.steps_done = 0
        self.last_recovery_ms: Optional[float] = None

    # ------------------------------------------------------------ preemption
    def _on_preempt(self) -> None:
        """Signal-handler-safe: set the flag only; the loop does the rest
        at the next step boundary."""
        self._preempt_flag = True

    def preemption_guard(self, signals=None) -> PreemptionGuard:
        """A context manager installing SIGTERM handlers that trigger the
        clean preemption path (final checkpoint flush + clean return)."""
        kw = {} if signals is None else {"signals": signals}
        return PreemptionGuard(on_preempt=self._on_preempt, **kw)

    # ----------------------------------------------------------------- mesh
    def _mesh_for(self, devices) -> Any:
        """Mesh-shape policy over a (possibly shrunk) device set. 1-D
        trainers keep the historical all-data mesh. A (d, m) trainer
        keeps its shape while the devices last; after a shrink it keeps
        the DATA axis and shrinks the model axis when the survivors
        still tile it — (2, 2) on 3 dead chips re-forms as (2, 1), and
        the generalized resharder redistributes the model-sharded
        checkpoint onto the new layout instead of aborting — falling
        back to (n, 1) otherwise. The model axis stays in the mesh
        either way so the recovery programs keep one axis vocabulary."""
        n = len(devices)
        shape = self._mesh_shape
        if shape is None or len(shape) == 1:
            return make_mesh((n,), ("data",), devices)
        d, m = int(shape[0]), int(shape[1])
        if n == d * m:
            return make_mesh((d, m), ("data", "model"), devices)
        if n % d == 0 and n // d <= m:
            return make_mesh((d, n // d), ("data", "model"), devices)
        return make_mesh((n, 1), ("data", "model"), devices)

    # -------------------------------------------------------------- wrappers
    def _wrapper(self) -> ParallelWrapper:
        key = (self.mode, tuple(self._devices))
        pw = self._wrappers.get(key)
        if pw is None:
            if self.mode == "sync":
                pw = ParallelWrapper(
                    self.net, mesh=self._mesh,
                    steps_per_dispatch=self.steps_per_dispatch,
                    prefetch_buffer=self.prefetch_buffer,
                    zero_stage=self.zero_stage,
                    bucket_bytes=self.bucket_bytes,
                    step_callback=self._on_item)
                if self.zero_stage:
                    # ONE engine per mesh: the wrapper reuses the
                    # trainer's (same net/stage/bucket_bytes by
                    # construction), so the layout is built once and
                    # sharding_meta/resharder/step programs cannot drift
                    pw._zero_engine = self._engine_for(self._mesh)
            else:       # degraded: SparkNet-style infrequent-sync windows
                pw = ParallelWrapper(
                    self.net, mesh=self._mesh, training_mode="averaging",
                    averaging_frequency=self.degraded_averaging_window,
                    average_updaters=True,
                    prefetch_buffer=self.prefetch_buffer,
                    step_callback=self._on_item)
            self._wrappers[key] = pw
        return pw

    def _tree(self) -> dict:
        net = self.net
        return {"params": net.params, "state": net.state,
                "opt": net.opt_state}

    def _engine_for(self, mesh) -> ZeroUpdateEngine:
        """The ZeRO layout for ``mesh`` (cached per device set — the
        layout is host metadata, but the init/like state it builds must
        carry the right mesh's shardings)."""
        key = (tuple(mesh.devices.shape),
               tuple(d.id for d in mesh.devices.flat))
        eng = self._engines.get(key)
        if eng is None:
            eng = self._engines[key] = ZeroUpdateEngine.from_net(
                self.net, mesh, stage=self.zero_stage,
                bucket_bytes=self.bucket_bytes)
        return eng

    def _sharding_meta(self) -> Optional[dict]:
        return (self._engine_for(self._mesh).sharding_meta()
                if self.zero_stage else None)

    def _resharder(self, mesh):
        """Restore hook (parallel/resharding.py): ANY saved layout —
        other mesh topologies, model-sharded params, zero-flat state on
        a different data-axis size — redistributes onto ``mesh`` instead
        of failing the restore."""
        return make_any_resharder(
            self._engine_for(mesh) if self.zero_stage else None)

    def _like_tree(self, mesh) -> dict:
        """Restore target: the current train state re-homed on ``mesh``
        (params on their tp layout when the mesh has a model axis, else
        replicated; state replicated; zero updater state in the engine's
        [N, L] data-axis-sharded layout for that mesh) — supplies both
        the tree structure and the target shardings for
        restore_sharded_checkpoint."""
        rep = replicated(mesh)
        put = lambda t: jax.tree.map(
            lambda a: jax.device_put(jnp.asarray(a), rep), t)
        m = model_axis_size(mesh)
        specs = build_param_specs(self.net, m) if m > 1 else None
        if specs is not None:
            psh = build_param_shardings(mesh, specs)
            params_like = jax.tree.map(
                lambda a, s: jax.device_put(jnp.asarray(a), s),
                self.net.params, psh)
        else:
            params_like = put(self.net.params)
        if self.zero_stage:
            opt_like = self._engine_for(mesh).init_opt_state()
        elif specs is not None and self.net.opt_state is not None:
            osh = build_opt_shardings(mesh, specs, self.net.params,
                                      self.net.opt_state)
            opt_like = jax.tree.map(
                lambda a, s: jax.device_put(jnp.asarray(a), s),
                self.net.opt_state, osh)
        else:
            opt_like = put(self.net.opt_state)
        return {"params": params_like,
                "state": put(self.net.state),
                "opt": opt_like}

    # ------------------------------------------------------------- step hook
    def _step_in_epoch(self) -> int:
        return self._pass_skip + (self.net.iteration_count - self._pass_start)

    def _on_item(self, net, k: int) -> None:
        """The supervision seam — runs after every dispatched item (k
        fused steps) with params/iteration_count/listeners consistent.
        Pure host bookkeeping: nothing here reads back from the device
        (the elastic path inherits the sync-freedom contract)."""
        it = net.iteration_count
        if self._injector is not None:
            self._injector.on_step(it, self)     # may raise WorkerLostError
        if self._writer is not None and self.checkpoint_every_n_steps \
                and it >= self._next_ckpt_step:
            self._submit_checkpoint(it)
            every = self.checkpoint_every_n_steps
            self._next_ckpt_step = (it // every + 1) * every
        if self.sync_latency_budget_ms is not None:
            self._update_latency(it, k)          # may raise _ModeSwitch
        if self._preempt_flag:
            raise _Preempted()
        if it >= self._num_steps:
            raise _StopRun()

    def _submit_checkpoint(self, it: int) -> None:
        extra = {"step_in_epoch": self._step_in_epoch()}
        if self._epoch_len:
            extra["epoch_len"] = self._epoch_len
        self._writer.submit(it, self._tree(), extra=extra,
                            sharding=self._sharding_meta())

    # ------------------------------------------------------- degraded mode
    def _update_latency(self, it: int, k: int) -> None:
        """Track a per-step sync-latency estimate and flip modes across
        the budget. With a fault injector the estimate is the synthetic
        per-collective delay divided by the current sync period (1 in
        sync mode, K in averaging mode — the SparkNet amortization made
        explicit, and deterministic for tests); without one it is the
        measured per-step wall time, which conflates compute and sync —
        good enough to dodge a pathologically slow interconnect, too
        coarse to flap on."""
        now = time.perf_counter()
        dt_ms = (now - self._t_item) * 1e3
        self._t_item = now
        period = 1 if self.mode == "sync" else self.degraded_averaging_window
        if self._injector is not None:
            delay = self._injector.collective_delay_ms(it)
            est = delay / period
            exit_signal = delay          # the true per-collective cost
        else:
            est = dt_ms / max(1, k)
            # measured mode can't separate collective cost from compute,
            # so the exit signal is the WHOLE item's wall time: if K
            # amortized steps plus one collective all fit inside one
            # per-step budget, sync mode is certainly healthy. Comparing
            # the amortized per-step time instead would exit while the
            # interconnect is still pathological and ping-pong between
            # modes forever (each flap paying latency_window full-cost
            # sync steps).
            exit_signal = dt_ms
        if self.mode == "sync":
            self._lat.append(est)
            if len(self._lat) == self.latency_window and \
                    sum(self._lat) / len(self._lat) > self.sync_latency_budget_ms:
                raise _ModeSwitch("averaging")
        else:
            # exit when the full per-collective cost fits the budget again
            # (i.e. sync mode would be healthy), with patience against
            # one-sample blips
            self._ok_items = self._ok_items + 1 \
                if exit_signal <= self.sync_latency_budget_ms else 0
            if self._ok_items >= self.degraded_exit_patience:
                raise _ModeSwitch("sync")

    def _switch_mode(self, to: str) -> None:
        self.degraded_transitions += 1
        # SparkNet degraded-mode decisions leave an evidence trail: an
        # instant event in the trace ring (any later flight dump shows
        # when and why the mode flipped), not just a counter
        event("elastic.mode_switch", to=to, step=self.net.iteration_count,
              budget_ms=self.sync_latency_budget_ms)
        self.mode_history.append((self.net.iteration_count, to))
        self.mode = to
        self._lat.clear()
        self._ok_items = 0
        if self._reg.enabled:
            self._reg.counter("elastic.degraded_transitions").inc()
            self._reg.gauge("elastic.degraded").set(
                1.0 if to != "sync" else 0.0)
        log.warning("elastic: %s mode at step %d (sync latency budget "
                    "%s ms)", "entering degraded averaging-window" if
                    to != "sync" else "returning to per-step sync",
                    self.net.iteration_count, self.sync_latency_budget_ms)

    # --------------------------------------------------------------- recover
    def _recover(self, exc: BaseException) -> None:
        self.recoveries += 1
        # black box BEFORE touching anything: what the trainer was doing
        # in the moments before the worker loss is exactly what the ring
        # still holds
        get_flight_recorder().dump(
            "elastic_recovery", reason=str(exc),
            reason_type=type(exc).__name__, attempt=self.recoveries,
            step=self.net.iteration_count, mesh_devices=len(self._devices))
        if self._reg.enabled:
            self._reg.counter("elastic.recoveries").inc()
        if self.recoveries > self.max_recoveries:
            raise RecoveryFailedError(
                f"recovery #{self.recoveries} exceeds max_recoveries="
                f"{self.max_recoveries}") from exc
        t0 = time.perf_counter()
        with span("elastic.recover", reason=str(exc),
                  attempt=self.recoveries):
            if self._writer is not None:
                self._writer.flush()

            def attempt():
                if self._injector is not None:
                    self._injector.on_coordinate()   # may raise (retried)
                devices = (self._injector.surviving(self._all_devices)
                           if self._injector is not None
                           else list(self._all_devices))
                if not devices:
                    raise RecoveryFailedError("no surviving workers")
                mesh = self._mesh_for(devices)
                like = self._like_tree(mesh)
                if self.checkpoint_dir is not None:
                    step, tree, extra = restore_latest_sharded_checkpoint(
                        self.checkpoint_dir, like,
                        resharder=self._resharder(mesh))
                else:
                    step, tree, extra = None, like, {}
                return devices, mesh, step, tree, extra

            try:
                devices, mesh, step, tree, extra = self._retry.call(
                    attempt,
                    on_retry=lambda i, e: log.warning(
                        "elastic: coordination attempt %d failed (%s); "
                        "backing off", i + 1, e))
            except RecoveryFailedError:
                raise
            except RetryError as e:
                raise RecoveryFailedError(
                    f"mesh re-form/restore gave up: {e}") from e

        if len(devices) != len(self._devices):
            log.warning("elastic: mesh re-formed with %d workers (was %d)",
                        len(devices), len(self._devices))
        self._devices = devices
        self._mesh = mesh
        self._wrappers = {}          # programs are per-mesh
        # drop engines for dead meshes (the one just built for the new
        # mesh — via _like_tree — stays cached)
        keep = (mesh.devices.size, tuple(d.id for d in mesh.devices.flat))
        self._engines = {k: v for k, v in self._engines.items() if k == keep}
        net = self.net
        if step is None:
            # nothing restorable: deterministic restart from scratch
            log.warning("elastic: no restorable checkpoint in %r; "
                        "restarting from step 0", self.checkpoint_dir)
            net.init()
            net.iteration_count = 0
            self._skip_next = 0
        else:
            net.params = tree["params"]
            net.state = tree["state"]
            net.opt_state = tree["opt"]
            net.iteration_count = step
            self._skip_next = int(extra.get("step_in_epoch", 0))
            if self._epoch_len is None and extra.get("epoch_len"):
                self._epoch_len = int(extra["epoch_len"])
        every = self.checkpoint_every_n_steps or 1
        self._next_ckpt_step = (net.iteration_count // every + 1) * every
        self._lat.clear()
        self._ok_items = 0
        self.last_recovery_ms = (time.perf_counter() - t0) * 1e3
        if self._reg.enabled:
            self._reg.histogram("elastic.recover_ms").observe(
                self.last_recovery_ms)
            self._reg.gauge("elastic.mesh_devices").set(len(devices))
        log.warning("elastic: recovered to step %s on a %d-device mesh in "
                    "%.0f ms", net.iteration_count, len(devices),
                    self.last_recovery_ms)

    def _initial_restore(self) -> None:
        """Cross-process resume: a fresh ElasticTrainer pointed at an
        existing checkpoint dir continues where the previous process
        died (manifest-only metadata — no device readbacks). A LIVE
        trainer (in-memory state already ahead of the newest on-disk
        save — e.g. a second ``fit`` call continuing the run) is never
        rolled backwards: the disk is a floor, not the truth — probed
        via the cheap manifest scan first, so a continuation fit never
        pays the shard reads + device_put of a restore it would
        discard."""
        newest = latest_sharded_step(self.checkpoint_dir)
        if newest is None or newest <= self.net.iteration_count:
            return
        step, tree, extra = restore_latest_sharded_checkpoint(
            self.checkpoint_dir, self._like_tree(self._mesh),
            resharder=self._resharder(self._mesh))
        # the actual restore may fall back to an OLDER save than the
        # probe saw (corrupt member only detectable on read)
        if step is None or step <= self.net.iteration_count:
            return
        net = self.net
        net.params = tree["params"]
        net.state = tree["state"]
        net.opt_state = tree["opt"]
        net.iteration_count = step
        self._skip_next = int(extra.get("step_in_epoch", 0))
        if extra.get("epoch_len"):
            self._epoch_len = int(extra["epoch_len"])
        log.info("elastic: resuming from checkpoint step %d", step)

    # ------------------------------------------------------------------- fit
    def fit(self, iterator, *, num_steps: int):
        net = self.net
        if net.params is None:
            net.init()
        self._num_steps = num_steps
        self._preempt_flag = False
        self.preempted = False
        self.steps_done = 0
        reg = self._reg
        if self.checkpoint_dir is not None:
            self._writer = AsyncCheckpointWriter(
                self.checkpoint_dir, keep_last=self.keep_last, registry=reg)
        # ONE trace id for the whole supervised run (checkpoints, mode
        # switches, recoveries included) — the step_callback loop and the
        # inner ParallelWrapper.fit spans all stamp it
        _ctx = current_trace_context()
        _trace_scope = use_trace_context(
            _ctx if _ctx is not None else new_trace_context())
        try:
            _trace_scope.__enter__()
            with span("elastic.fit", num_steps=num_steps,
                      devices=len(self._devices)):
                if self.checkpoint_dir is not None:
                    self._initial_restore()
                every = self.checkpoint_every_n_steps or 1
                self._next_ckpt_step = \
                    (net.iteration_count // every + 1) * every
                self._pass_start = net.iteration_count
                self._pass_skip = self._skip_next or 0
                if reg.enabled:
                    reg.gauge("elastic.mesh_devices").set(len(self._devices))
                    reg.gauge("elastic.degraded").set(
                        0.0 if self.mode == "sync" else 1.0)
                while net.iteration_count < num_steps \
                        and not self._preempt_flag:
                    skip = self._skip_next
                    if skip is None:
                        L = self._epoch_len
                        skip = (net.iteration_count % L) if L else 0
                    self._skip_next = None
                    self._pass_start = net.iteration_count
                    self._pass_skip = skip
                    self._t_item = time.perf_counter()
                    if hasattr(iterator, "reset"):
                        iterator.reset()
                    pw = self._wrapper()
                    try:
                        pw.fit(iterator, epochs=1, skip_first_batches=skip)
                    except (_StopRun, _Preempted):
                        # record the mid-epoch position so a continuation
                        # fit() on this SAME trainer resumes here instead
                        # of replaying the epoch prefix (it % epoch_len
                        # can't be computed before the first clean pass)
                        self._skip_next = self._step_in_epoch()
                        break
                    except _ModeSwitch as ms:
                        consumed = self._step_in_epoch()
                        self._switch_mode(ms.to)
                        self._skip_next = consumed
                        continue
                    except WorkerLostError as e:
                        self._recover(e)
                        continue
                    # clean pass: measure the epoch length once
                    n_pass = self._step_in_epoch()
                    if n_pass == 0:
                        # an exhausted, non-resettable iterator would
                        # otherwise spin this loop forever at zero
                        # progress — fail loudly instead
                        raise ValueError(
                            f"iterator yielded no batches at step "
                            f"{net.iteration_count} of {num_steps}: "
                            f"ElasticTrainer re-runs the iterator per "
                            f"pass and needs it resettable (reset()) or "
                            f"re-iterable")
                    self._epoch_len = n_pass
                    self._skip_next = 0
                if self._preempt_flag:
                    self.preempted = True
                    if reg.enabled:
                        reg.counter("elastic.preemptions").inc()
                    # SIGTERM black box: flushed from the loop thread at
                    # the step boundary (the handler only sets a flag —
                    # dumping from a signal handler could deadlock)
                    get_flight_recorder().dump(
                        "preemption", step=net.iteration_count,
                        steps_target=num_steps)
        finally:
            _trace_scope.__exit__(None, None, None)
            writer, self._writer = self._writer, None
            if writer is not None:
                try:
                    it = net.iteration_count
                    if (self.final_checkpoint or self.preempted) and it > 0:
                        writer.save_sync(
                            it, self._tree(),
                            extra={"step_in_epoch": self._step_in_epoch(),
                                   **({"epoch_len": self._epoch_len}
                                      if self._epoch_len else {})},
                            sharding=self._sharding_meta())
                finally:
                    writer.close()
        self.steps_done = net.iteration_count
        return net
