"""Overlapped gradient synchronization: bucketed backward-overlap collectives.

Reference: the async ``VoidParameterServer``/``EncodingHandler`` exchange hid
collective cost behind compute by design (SilentTrainingDriver.java:109-142
streams updates while workers keep training). The TPU-native sync path lost
that: one monolithic post-backward sweep of per-leaf ``pmean`` binds —
O(leaves) collective launches, all serialized after the last gradient is
produced (BENCH_r05 ``collective_overhead_by_mesh``: 6.9ms -> 41.2ms from
mesh 1 to 8, ~44% of an 8-device step).

Two techniques close the gap (PAPERS.md):
- arXiv:2004.13336 (cross-replica weight-update sharding): collectives
  scheduled so ICI traffic overlaps the remaining backward FLOPs. Here the
  lever is DATA DEPENDENCE, not program order: each bucket's all-reduce
  depends only on its own leaves, so XLA's latency-hiding scheduler can
  launch it as soon as those gradients exist, while the rest of the
  backward is still computing. Buckets are packed in REVERSE leaf order
  because the backward produces the last layers' gradients first — the
  first bucket closes (and its collective becomes launchable) earliest.
- arXiv:1905.04035 (densifying assumed-sparse tensors): many small
  messages cost latency, not bandwidth. Small leaves are flattened into
  one contiguous bucket buffer and all-reduced as a SINGLE dense array —
  one launch per ~4MB bucket instead of one per leaf (161 for ResNet-50).
  Leaves at or above the bucket size skip the pack/unpack copy entirely
  (their own launch is already bandwidth-bound).

The schedule is host-side metadata (leaf indices + byte sizes); the psum
math is unchanged — ``bucketed_pmean`` is elementwise bit-identical to the
per-leaf sweep on the test backend (grouping does not change any element's
reduction), pinned by tests/test_overlap_sync.py across bucket sizes.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import get_registry
from ..telemetry.spans import record_external_span

DEFAULT_BUCKET_BYTES = 4 * 2 ** 20      # ~4MB: the DDP-proven sweet spot


@dataclass(frozen=True)
class GradBucket:
    """One collective launch: ``indices`` are leaf positions (flatten
    order). A multi-leaf bucket is packed into one flat buffer; a
    singleton bucket ships its leaf directly (no pack/unpack copy)."""
    indices: Tuple[int, ...]
    nbytes: int

    def __len__(self) -> int:
        return len(self.indices)


class BucketSchedule:
    """Size-targeted partition of a gradient pytree into collective
    buckets. Built ONCE per (tree structure, bucket_bytes) on the host;
    applying it (``bucketed_pmean``) is pure traced math."""

    def __init__(self, buckets: List[GradBucket], treedef,
                 leaf_shapes: List[tuple], leaf_dtypes: List[Any],
                 bucket_bytes: int):
        self.buckets = buckets
        self.treedef = treedef
        self.leaf_shapes = leaf_shapes
        self.leaf_dtypes = leaf_dtypes
        self.bucket_bytes = bucket_bytes
        self.total_bytes = sum(b.nbytes for b in buckets)

    def __len__(self) -> int:
        return len(self.buckets)

    @property
    def num_leaves(self) -> int:
        return len(self.leaf_shapes)

    def describe(self) -> List[dict]:
        """Host-side summary rows (telemetry / bench / dryrun)."""
        return [{"bucket": i, "leaves": len(b), "bytes": b.nbytes}
                for i, b in enumerate(self.buckets)]


def build_bucket_schedule(tree, bucket_bytes: int = DEFAULT_BUCKET_BYTES
                          ) -> BucketSchedule:
    """Partition ``tree``'s leaves into collective buckets of ~``bucket_bytes``.

    Packing runs over the leaves in REVERSE flatten order (the backward
    pass produces the last parameters' gradients first, so the tail-end
    bucket is complete — and its all-reduce launchable — while the head of
    the model is still differentiating). A leaf whose own size reaches
    ``bucket_bytes`` closes the current bucket and ships as a singleton;
    leaves of different dtypes never share a bucket (the packed buffer is
    one dense array).
    """
    if bucket_bytes < 1:
        raise ValueError(f"bucket_bytes must be >= 1, got {bucket_bytes}")
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        raise ValueError("cannot bucket an empty pytree")
    shapes = [tuple(np.shape(l)) for l in leaves]
    dtypes = [jnp.asarray(l).dtype if not hasattr(l, "dtype") else l.dtype
              for l in leaves]
    nbytes = [int(np.prod(s, dtype=np.int64)) * dt.itemsize
              for s, dt in zip(shapes, dtypes)]

    buckets: List[GradBucket] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None

    def close():
        nonlocal cur, cur_bytes, cur_dtype
        if cur:
            buckets.append(GradBucket(tuple(cur), cur_bytes))
        cur, cur_bytes, cur_dtype = [], 0, None

    for i in reversed(range(len(leaves))):
        if nbytes[i] >= bucket_bytes:
            close()
            buckets.append(GradBucket((i,), nbytes[i]))
            continue
        if cur_dtype is not None and dtypes[i] != cur_dtype:
            close()
        cur.append(i)
        cur_bytes += nbytes[i]
        cur_dtype = dtypes[i]
        if cur_bytes >= bucket_bytes:
            close()
    close()
    return BucketSchedule(buckets, treedef, shapes, dtypes, bucket_bytes)


def _check_tree(schedule: BucketSchedule, leaves, treedef) -> None:
    if treedef != schedule.treedef or len(leaves) != schedule.num_leaves:
        raise ValueError(
            f"tree does not match the bucket schedule it was built for "
            f"({len(leaves)} leaves vs {schedule.num_leaves}) — rebuild the "
            f"schedule when the parameter structure changes")


def bucketed_pmean(tree, schedule: BucketSchedule, axis: str = "data"):
    """Per-bucket all-reduce mean of ``tree`` (must be called with ``axis``
    in scope, i.e. inside shard_map). Multi-leaf buckets are packed into
    one flat buffer (ONE psum launch), singletons ship directly. Each
    bucket's launch depends only on its own leaves, so XLA's scheduler can
    start it while gradients for other buckets are still being computed.

    Elementwise identical to ``jax.tree.map(pmean)`` — grouping never
    changes any element's reduction — at O(buckets) launches instead of
    O(leaves)."""
    leaves, treedef = jax.tree.flatten(tree)
    _check_tree(schedule, leaves, treedef)
    out = list(leaves)
    for b in schedule.buckets:
        if len(b) == 1:
            i = b.indices[0]
            out[i] = jax.lax.pmean(leaves[i], axis)
            continue
        flat = jnp.concatenate([jnp.ravel(leaves[i]) for i in b.indices])
        red = jax.lax.pmean(flat, axis)
        off = 0
        for i in b.indices:
            n = int(np.prod(schedule.leaf_shapes[i], dtype=np.int64))
            out[i] = jax.lax.dynamic_slice_in_dim(red, off, n).reshape(
                schedule.leaf_shapes[i])
            off += n
    return jax.tree.unflatten(treedef, out)


def fused_pmean(tree, axis: str = "data"):
    """ONE variadic psum bind for a whole pytree (vs ``tree.map``'s
    per-leaf binds): ``lax.pmean`` flattens the tree and binds every leaf
    in a single primitive call. Used to collapse the averaging path's
    separate params/state/opt_state sweeps into one launch; for O(buckets)
    launch-count control use ``bucketed_pmean``."""
    return jax.lax.pmean(tree, axis)


# --------------------------------------------------------------- profiling
def profile_schedule(mesh, schedule: BucketSchedule, axis: str = "data",
                     repeats: int = 3) -> dict:
    """Time each bucket's all-reduce on ``mesh`` (one tiny jitted program
    per bucket, best-of-``repeats``), emit a per-bucket Chrome-trace event
    (cat="collective") under the current span path, and set the
    ``parallel.collective_ms`` gauge to the total. Host-side tooling for
    bench/dryrun/traces — the training step itself never calls this."""
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map

    reg = get_registry()
    rows = []
    total_ms = 0.0
    # ONE jitted callable for every bucket: jax's jit cache then compiles
    # once per distinct (elems, dtype) instead of once per bucket (real
    # schedules repeat bucket shapes — ~4MB buckets of one dtype)
    fn = jax.jit(shard_map(lambda g: jax.lax.pmean(g, axis), mesh=mesh,
                           in_specs=P(), out_specs=P(), check_vma=False))
    for i, b in enumerate(schedule.buckets):
        elems = b.nbytes // schedule.leaf_dtypes[b.indices[0]].itemsize
        buf = jnp.zeros((max(1, elems),), schedule.leaf_dtypes[b.indices[0]])
        jax.block_until_ready(fn(buf))
        best = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(buf))
            best = min(best, time.perf_counter() - t0)
        ms = best * 1e3
        total_ms += ms
        rows.append({"bucket": i, "leaves": len(b), "bytes": b.nbytes,
                     "ms": round(ms, 4)})
        record_external_span("bucket_psum", ms, cat="collective",
                             bucket=i, bytes=b.nbytes, leaves=len(b))
    if reg.enabled:
        reg.gauge("parallel.collective_ms").set(total_ms)
        reg.gauge("parallel.bucket_count").set(len(schedule))
    return {"buckets": rows, "collective_ms": round(total_ms, 4)}
