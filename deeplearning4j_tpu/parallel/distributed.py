"""Multi-host distributed entry point.

Reference: the Spark/Aeron orchestration layer — SharedTrainingMaster.java
:46-53,464 (VoidParameterServer + RoutedTransport bootstrap across executors)
and ParameterAveragingTrainingMaster's driver-centric broadcast/aggregate.
The TPU build replaces ALL of it with the JAX coordination service +
XLA collectives (SURVEY.md §5.8): every process calls
``initialize_distributed`` once at startup, after which ``jax.devices()`` is
the GLOBAL device list and any Mesh built over it spans hosts — pjit/GSPMD
then emit ICI/DCN collectives; no parameter server, no hand-rolled transport.

Usage (one process per host, e.g. under a TPU pod scheduler):

    from deeplearning4j_tpu.parallel import distributed
    distributed.initialize_distributed()          # env-driven on TPU pods
    mesh = distributed.global_mesh(("data",))
    pw = ParallelWrapper(net, mesh=mesh)          # same API as single-host

Tested without real multi-host hardware via 2 CPU processes + gloo
collectives (tests/test_distributed.py — the analogue of the reference's
Spark local[n] testing, SURVEY.md §4).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           local_device_ids=None,
                           cpu_collectives: Optional[str] = None) -> None:
    """Join (or start) the JAX coordination service.

    On real TPU pods all arguments are inferred from the environment
    (jax.distributed reads the TPU metadata); pass them explicitly for
    CPU/GPU clusters. ``cpu_collectives``: set "gloo" when running
    multi-process on CPU (the test configuration).
    """
    import jax
    if cpu_collectives:
        jax.config.update("jax_cpu_collectives_implementation", cpu_collectives)
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)


def shutdown_distributed() -> None:
    import jax
    jax.distributed.shutdown()


def global_mesh(axis_names: Sequence[str] = ("data",),
                shape: Optional[Tuple[int, ...]] = None):
    """Mesh over the GLOBAL device list (all processes). With the default
    1-D shape this is the multi-host data axis the ParallelWrapper shards
    batches over."""
    import jax
    from jax.sharding import Mesh
    devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n != len(devices):
        raise ValueError(f"Mesh shape {shape} must cover all {len(devices)} "
                         f"global devices")
    return Mesh(np.array(devices).reshape(shape), tuple(axis_names))


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()
