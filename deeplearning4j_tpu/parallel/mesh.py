"""Device mesh helpers.

The TPU replacement for the reference's device-thread plumbing
(ParallelWrapper worker threads, Spark executors): a `jax.sharding.Mesh`
over which pjit/GSPMD emits the collectives (SURVEY.md §5.8).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """Version-portable ``shard_map``: new JAX exports it as
    ``jax.shard_map`` (with ``check_vma``); older versions ship
    ``jax.experimental.shard_map.shard_map`` (same semantics, the kwarg is
    named ``check_rep``). One seam so every sharded module runs on both."""
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    if check_vma is not None:
        kw["check_vma"] = check_vma
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = ("data",),
              devices=None) -> Mesh:
    """Build a mesh. Default: all local devices on one 'data' axis."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"Mesh shape {shape} needs {n} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def data_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Batch-dim sharding: leading dim split across the data axis."""
    return NamedSharding(mesh, P(axis))


def window_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """[K, batch, ...] feed sharding for fused K-step windows: the scan
    axis replicated, the batch dim split across ``axis``."""
    return NamedSharding(mesh, P(None, axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
