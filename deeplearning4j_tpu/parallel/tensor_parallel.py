"""Megatron-style tensor-parallel layout rules for the ``model`` mesh axis.

The reference stack has no model-parallel story (ParallelWrapper.java and
the Spark masters shard BATCHES, never weights); this module is the
net-new layer that makes ``(data, model)`` meshes first-class. The split
is the standard head/width recipe (arXiv 1909.08053): attention Q/K/V
projections column-parallel, the output projection row-parallel, MLP
ff1 column- / ff2 row-parallel, and LSTM gate blocks (the 4H gate dim)
column-parallel — everything else (embeddings, layernorms, heads,
biases feeding row-parallel matmuls, peepholes) replicated.

Crucially these are GSPMD *layout hints*, not manual collectives: the
specs go into ``jax.jit`` ``in_shardings``/``out_shardings`` (or ride a
``shard_map(..., auto={'model'})`` region) and XLA inserts the
all-reduces after every row-parallel matmul. Correctness is therefore
independent of the rules below — a leaf the rules leave replicated is
merely not memory-sharded. That is what lets the same rule table serve
the transformer LM, the LSTM stacks, and any future zoo entry without a
per-model parallelism implementation, and what keeps the ``m=1`` path
bit-identical to the 1-D programs (an empty spec table == today's
replicated layout).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"

_LSTM_LAYER_TYPES = ("GravesLSTM", "LSTM", "GravesBidirectionalLSTM")


def model_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the ``model`` axis (1 when the mesh is 1-D / None)."""
    if mesh is None or MODEL_AXIS not in mesh.axis_names:
        return 1
    return int(dict(zip(mesh.axis_names,
                        mesh.devices.shape))[MODEL_AXIS])


def _is_spec_leaf(x) -> bool:
    return isinstance(x, P)


def _attn_spec(key: str, shape, m: int) -> P:
    # Wq/Wk/Wv [d_model, d_model] column-parallel: the head dim lives in
    # the output columns, so slicing columns slices whole heads when
    # n_heads % m == 0 (callers gate on that for the decode pool; for
    # training GSPMD is correct either way).
    if key in ("Wq", "Wk", "Wv") and len(shape) == 2 and shape[1] % m == 0:
        return P(None, MODEL_AXIS)
    # Wo [d_model, d_model] row-parallel: consumes the head-sharded
    # activation; XLA inserts the psum after the partial matmul.
    if key == "Wo" and len(shape) == 2 and shape[0] % m == 0:
        return P(MODEL_AXIS, None)
    return P()       # attention bias rides the post-psum add: replicated


def _ff_spec(vertex: str, key: str, shape, m: int) -> P:
    if vertex.endswith("_ff1"):
        if key == "W" and len(shape) == 2 and shape[1] % m == 0:
            return P(None, MODEL_AXIS)
        if key == "b" and len(shape) == 1 and shape[0] % m == 0:
            return P(MODEL_AXIS)      # adds onto the column-sharded hidden
    if vertex.endswith("_ff2"):
        if key == "W" and len(shape) == 2 and shape[0] % m == 0:
            return P(MODEL_AXIS, None)
        # ff2 bias adds after the row-parallel psum: replicated
    return P()


def _lstm_spec(key: str, shape, m: int) -> P:
    # W [n_in, 4H] / R [H, 4H]: the gate blocks live in the 4H output
    # columns — column-parallel, with the bias sharded to match. The
    # H-sized peepholes stay replicated (they multiply the cell state,
    # which GSPMD keeps consistent across the psum boundary either way).
    if key in ("W", "R", "U") and len(shape) == 2 and shape[1] % m == 0:
        return P(None, MODEL_AXIS)
    if key == "b" and len(shape) == 1 and shape[0] % m == 0:
        return P(MODEL_AXIS)
    return P()


def build_param_specs(net, m: int) -> Any:
    """PartitionSpec tree matching ``net.params``. ``m`` is the model-axis
    size; at ``m == 1`` (or a net with nothing shardable) every leaf is
    ``P()`` — exactly the replicated layout of the 1-D path. Leaves whose
    shard dim does not divide by ``m`` fall back to ``P()`` individually,
    so an odd head count degrades that one layer, not the mesh."""
    params = net.params
    if params is None:
        raise ValueError("net has no params — call net.init() first")

    def leaf_spec(rule):
        def per_vertex(name, p):
            if not hasattr(p, "items"):
                return jax.tree.map(lambda _: P(), p)
            return {k: (rule(name, k, np.shape(v)) if m > 1 else P())
                    for k, v in p.items()}
        return per_vertex

    names = None
    if hasattr(net, "vertex_names"):          # ComputationGraph
        names = list(net.vertex_names)

        def rule(name, key, shape):
            if name.endswith("_attn"):
                return _attn_spec(key, shape, m)
            if name.endswith("_ff1") or name.endswith("_ff2"):
                return _ff_spec(name, key, shape, m)
            return P()
    elif hasattr(net.conf, "layers"):         # MultiLayerNetwork
        layers = list(net.conf.layers)
        names = [type(l).__name__ for l in layers]

        def rule(name, key, shape):
            if name in _LSTM_LAYER_TYPES:
                return _lstm_spec(key, shape, m)
            return P()
    else:
        return jax.tree.map(lambda _: P(), params)
    per = leaf_spec(rule)
    return tuple(per(nm, p) for nm, p in zip(names, params))


def build_param_shardings(mesh: Mesh, specs) -> Any:
    """NamedSharding tree from a spec tree (``is_leaf`` on PartitionSpec —
    a P is itself a tuple, so the default flatten would explode it)."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=_is_spec_leaf)


def build_opt_shardings(mesh: Mesh, specs, params, opt_state) -> Any:
    """NamedSharding tree matching ``opt_state``: each updater-state leaf
    whose shape equals its param's shape (momentum/velocity slots)
    inherits the param's spec; anything else (scalar step counts, etc.)
    stays replicated."""
    def per(spec, p, st):
        pshape = np.shape(p)
        return jax.tree.map(
            lambda leaf: NamedSharding(
                mesh, spec if np.shape(leaf) == pshape else P()),
            st)
    return jax.tree.map(per, specs, params, opt_state,
                        is_leaf=_is_spec_leaf)


def sharded_leaf_count(specs) -> int:
    """How many param leaves the rules actually shard (0 == pure dp)."""
    return sum(1 for s in jax.tree.leaves(specs, is_leaf=_is_spec_leaf)
               if s != P())


def shard_params(mesh: Mesh, params, specs) -> Any:
    """device_put the param tree onto its tp layout (pure redistribution;
    values unchanged)."""
    sh = build_param_shardings(mesh, specs)
    return jax.tree.map(lambda v, s: jax.device_put(v, s), params, sh)


def host_gather(tree) -> Any:
    """Gather a (possibly model-sharded) tree to host numpy — the seam
    ``write_model`` and the resharder use. Raises loudly when a leaf is
    not fully addressable (multi-host: gather on each host would be a
    silent partial read)."""
    def per(leaf):
        if hasattr(leaf, "is_fully_addressable") and \
                not leaf.is_fully_addressable:
            raise ValueError(
                "cannot host-gather a non-fully-addressable array (leaf "
                f"sharding {getattr(leaf, 'sharding', None)}); gather on "
                "a process that addresses every shard, or save with "
                "save_sharded_checkpoint instead")
        return np.asarray(jax.device_get(leaf))
    return jax.tree.map(per, tree)


def per_replica_bytes(tree, device=None) -> int:
    """Bytes of ``tree`` resident on ONE device (the first addressable
    one by default) — the number the m×-reduction gauges report. For a
    replicated leaf this is the full leaf; for a model-sharded leaf it is
    1/m of it."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            shards = leaf.addressable_shards
            if device is None and shards:
                device = shards[0].device
            total += sum(np.asarray(s.data).nbytes for s in shards
                         if s.device == device)
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total
