"""GradientsAccumulator: the pluggable cross-worker gradient-exchange seam.

Reference: optimize/solvers/accumulation/GradientsAccumulator.java (SPI) with
BasicGradientsAccumulator + EncodingHandler (threshold compression,
:64-66) / LocalHandler — the training loop asks "combine my grads" without
knowing the transport (SURVEY.md §5.8 names this the right abstraction seam).

TPU mapping: the accumulator is a pure function invoked INSIDE the sharded
train step (under shard_map, with a named mesh axis in scope). The default
``PsumAccumulator`` is a plain pmean — GSPMD lowers it to an ICI all-reduce,
which is the right call intra-pod. ``EncodedAccumulator`` quantizes each
worker's gradient with threshold encoding (+residual error feedback, see
ops/compression.py) before the all-reduce — the DCN/multi-pod capability the
reference ships over Aeron; the payload that would cross DCN is the
static-capacity index/sign pair, exchanged here via psum of the decoded
updates (on real multi-slice meshes the axis would be the DCN axis).

Design note vs the reference: the reference encodes POST-updater updates
(SymmetricTrainer pushes what each worker already applied); here the
accumulator combines RAW gradients BEFORE the updater so the (replicated)
updater state stays bitwise identical on every worker inside one XLA program.
The quantization + error-feedback dynamics are the same; convergence is
covered by tests/test_compression.py.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.compression import (threshold_decode, threshold_encode,
                               threshold_encode_signs)


class GradientsAccumulator:
    """SPI. ``init(size, dtype)`` builds per-worker carry state;
    ``combine(flat_grad, state, axis)`` returns (combined_flat, new_state)
    and must be called with a mesh axis name in scope (inside shard_map)."""

    def init(self, size: int, dtype) -> Any:
        return ()

    def combine(self, flat_grad: jnp.ndarray, state: Any,
                axis: str = "data") -> Tuple[jnp.ndarray, Any]:
        raise NotImplementedError


@dataclass
class PsumAccumulator(GradientsAccumulator):
    """Exact all-reduce mean (reference LocalHandler / plain sync DP)."""

    def combine(self, flat_grad, state, axis="data"):
        return jax.lax.pmean(flat_grad, axis), state


@dataclass
class EncodedAccumulator(GradientsAccumulator):
    """Threshold-compressed exchange (reference EncodingHandler.java:64-66):
    each worker adds its gradient to a residual, quantizes what clears the
    threshold to +-threshold, subtracts the sent mass from the residual
    (Strom-style error feedback), and all workers apply the mean of the
    decoded updates.

    Two encoders:
    - ``"dense"`` — the reference's exact semantics: EVERY entry above
      threshold ships (as an int8 sign map on the wire, 4x smaller than
      f32). Pure elementwise, fused by XLA into the step.
    - ``"topk"`` — fixed-size index/sign payload (static capacity =
      ``capacity_fraction * n`` via top_k): bounded message size for a DCN
      hop, at a real top_k cost (~90ms at ResNet scale).
    Default (``encoder=None``) selects "topk" when ``capacity_fraction``
    is set (a capacity request implies the bounded payload format) and
    "dense" otherwise.
    """
    threshold: float = 1e-3
    capacity_fraction: Optional[float] = None
    encoder: Optional[str] = None

    def __post_init__(self):
        if self.encoder is None:
            self.encoder = "dense" if self.capacity_fraction is None else "topk"
        if self.encoder not in ("dense", "topk"):
            raise ValueError(f"Unknown encoder {self.encoder!r} "
                             f"(expected 'dense' or 'topk')")
        if self.encoder == "dense" and self.capacity_fraction is not None:
            raise ValueError(
                "capacity_fraction only applies to the bounded 'topk' "
                "payload format; the dense encoder ships every entry above "
                "threshold")
        if self.encoder == "topk" and self.capacity_fraction is None:
            self.capacity_fraction = 0.1

    def init(self, size: int, dtype) -> Any:
        return jnp.zeros((size,), dtype)

    def combine(self, flat_grad, state, axis="data"):
        residual = state + flat_grad
        if self.encoder == "dense":
            # sign-map front door: ONE fused pass (Pallas kernel when
            # applicable, XLA elementwise fallback — bit-identical); the
            # f32 update peers apply is reconstructed from the int8 map
            # only as the psum operand
            signs, new_residual = threshold_encode_signs(residual,
                                                         self.threshold)
            sent = signs.astype(residual.dtype) * \
                jnp.asarray(self.threshold, residual.dtype)
            return jax.lax.pmean(sent, axis), new_residual
        capacity = max(1, int(self.capacity_fraction * flat_grad.shape[0]))
        payload, new_residual = threshold_encode(residual, self.threshold,
                                                 capacity)
        update = threshold_decode(payload, self.threshold,
                                  flat_grad.shape[0], flat_grad.dtype)
        return jax.lax.pmean(update, axis), new_residual
