"""Deterministic fault injection for elastic training.

Reference: the Spark layer's fault tolerance was only ever *exercised* by
real cluster weather — a preempted executor here, a slow shuffle there —
which is why its recovery paths rotted (SURVEY.md §5.3). This module makes
the weather reproducible: a :class:`FaultPlan` is an explicit list of
faults keyed to the supervised step counter, so a test (or the chaos
soak) can say "worker 2 dies at step 12, the newest checkpoint is
truncated, coordination flakes twice during recovery" and get the same
run every time.

Fault kinds:
  - :class:`KillWorker` — raises :class:`WorkerLostError` out of the step
    loop at step N. ``rejoin=True`` models a preempted VM that comes back
    before recovery completes (mesh re-forms at full size — recovery must
    be bit-identical to an uninterrupted run); ``rejoin=False`` models a
    permanently lost worker (mesh re-forms smaller).
  - :class:`SlowCollective` — reports a synthetic per-collective latency
    to the supervisor over a step range (the degraded-mode trigger);
    optionally sleeps for wall-clock realism.
  - :class:`CorruptCheckpoint` — truncates (or bit-flips) the newest
    on-disk checkpoint's shard files at step N, after draining the async
    writer so the damage is deterministic.
  - :class:`PreemptAt` — fires the trainer's preemption flag at step N
    (the in-process stand-in for SIGTERM).
  - :class:`CoordinationFlake` — the next ``n`` coordination attempts
    during recovery raise :class:`CoordinationError` (retry/backoff
    coverage; ``n`` > the retry budget exercises the give-up path).

The file-damage helpers (:func:`truncate_newest_sharded`,
:func:`corrupt_newest_sharded`, :func:`truncate_newest_zip`) are usable
directly from tests without a plan.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Set

from ..telemetry.flightrec import get_flight_recorder
from ..util.distributed_checkpoint import (_shard_files,
                                           list_sharded_checkpoints)

__all__ = [
    "WorkerLostError", "CoordinationError", "Fault", "KillWorker",
    "SlowCollective", "CorruptCheckpoint", "PreemptAt", "CoordinationFlake",
    "FaultPlan", "FaultInjector", "truncate_newest_sharded",
    "corrupt_newest_sharded", "truncate_newest_zip",
]


class WorkerLostError(RuntimeError):
    """A mesh worker stopped responding (injected or real)."""

    def __init__(self, worker: int, step: int):
        super().__init__(f"worker {worker} lost at step {step}")
        self.worker = worker
        self.step = step


class CoordinationError(RuntimeError):
    """Transient coordination failure during mesh re-form (retryable)."""


# ------------------------------------------------------------------ faults
@dataclass
class Fault:
    step: int
    fired: bool = field(default=False, init=False)


@dataclass
class KillWorker(Fault):
    worker: int = 0
    rejoin: bool = False


@dataclass
class SlowCollective(Fault):
    """Per-collective extra latency over ``[step, until_step)``."""
    until_step: int = 0
    delay_ms: float = 0.0
    sleep: bool = False        # also burn real wall time (soak realism)


@dataclass
class CorruptCheckpoint(Fault):
    mode: str = "truncate"     # "truncate" | "flip"


@dataclass
class PreemptAt(Fault):
    pass


@dataclass
class CoordinationFlake(Fault):
    """Arms ``failures`` transient coordination errors (consumed by the
    recovery path's retry loop, regardless of which step recovery starts
    at — ``step`` only orders the plan)."""
    failures: int = 1


class FaultPlan:
    """An ordered list of faults. ``FaultPlan(KillWorker(step=10), ...)``."""

    def __init__(self, *faults: Fault):
        self.faults: List[Fault] = sorted(faults, key=lambda f: f.step)

    def __iter__(self):
        return iter(self.faults)

    def __len__(self):
        return len(self.faults)


# ------------------------------------------------------- file-damage helpers
def truncate_newest_sharded(directory: str, keep_bytes: int = 64) -> Optional[int]:
    """Truncate every shard file of the newest sharded checkpoint (manifest
    left intact — the dangerous shape: a save that LOOKS complete). Returns
    the damaged step, or None if the directory has no checkpoints."""
    ckpts = list_sharded_checkpoints(directory)
    if not ckpts:
        return None
    step = ckpts[-1][0]
    for path in _shard_files(directory, step):
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(min(keep_bytes, size))
    return step


def corrupt_newest_sharded(directory: str) -> Optional[int]:
    """Flip bytes mid-file in every shard of the newest checkpoint: the
    zip central directory survives (``is_zipfile`` passes) but the member
    CRC fails on read — the corruption only the actual restore catches."""
    ckpts = list_sharded_checkpoints(directory)
    if not ckpts:
        return None
    step = ckpts[-1][0]
    for path in _shard_files(directory, step):
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(16)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
    return step


def truncate_newest_zip(directory: str, keep_bytes: int = 64) -> Optional[str]:
    """Truncate the newest ``checkpoint_epoch*.zip`` (util/checkpointing
    format). Returns the damaged path."""
    from ..util.checkpointing import _scan_checkpoints
    entries = _scan_checkpoints(directory)
    if not entries:
        return None
    path = entries[-1][0]
    with open(path, "r+b") as f:
        f.truncate(min(keep_bytes, os.path.getsize(path)))
    return path


# ---------------------------------------------------------------- injector
class FaultInjector:
    """Executes a :class:`FaultPlan` against a supervised step loop.

    The elastic trainer calls :meth:`on_step` once per completed dispatch
    (with the post-increment step counter), :meth:`collective_delay_ms`
    when estimating sync latency, :meth:`on_coordinate` inside each
    recovery attempt, and :meth:`on_recovery` once a recovery succeeds.
    All methods are also callable directly from tests."""

    def __init__(self, plan: Optional[FaultPlan] = None):
        self.plan = plan or FaultPlan()
        self.failed_workers: Set[int] = set()
        self._flakes_armed = 0
        self.coordination_attempts = 0

    # ------------------------------------------------------------ step hook
    def on_step(self, step: int, trainer=None) -> None:
        """Apply every not-yet-fired fault with ``fault.step <= step``.
        Order within a step: disk damage first, then preemption, then the
        kill (so a kill+corrupt plan at the same step damages the disk the
        recovery will read)."""
        due = [f for f in self.plan if not f.fired and f.step <= step
               and not isinstance(f, (SlowCollective, CoordinationFlake))]
        kill: Optional[KillWorker] = None
        for f in due:
            if isinstance(f, CorruptCheckpoint):
                f.fired = True
                self._apply_corrupt(f, trainer)
                self._blackbox(f, step)
            elif isinstance(f, PreemptAt):
                f.fired = True
                if trainer is not None:
                    trainer._on_preempt()
            elif isinstance(f, KillWorker):
                kill = f
        for f in self.plan:
            if isinstance(f, CoordinationFlake) and not f.fired \
                    and f.step <= step:
                f.fired = True
                self._flakes_armed += f.failures
        if kill is not None:
            kill.fired = True
            self.failed_workers.add(kill.worker)
            # dump BEFORE raising: every chaos run leaves a readable
            # black box of the spans/events preceding the injected loss
            self._blackbox(kill, step, worker=kill.worker)
            raise WorkerLostError(kill.worker, step)
        for f in self.plan:
            if isinstance(f, SlowCollective) and f.sleep \
                    and f.step <= step < f.until_step:
                time.sleep(f.delay_ms / 1e3)

    @staticmethod
    def _blackbox(fault: Fault, step: int, **info) -> None:
        get_flight_recorder().dump(
            f"fault_{type(fault).__name__.lower()}", step=step,
            planned_step=fault.step, **info)

    def _apply_corrupt(self, f: CorruptCheckpoint, trainer) -> None:
        directory = getattr(trainer, "checkpoint_dir", None)
        if directory is None:
            return
        writer = getattr(trainer, "_writer", None)
        if writer is not None:
            writer.flush()      # damage the *landed* newest, deterministically
        if f.mode == "flip":
            corrupt_newest_sharded(directory)
        else:
            truncate_newest_sharded(directory)

    # ------------------------------------------------------- latency signal
    def collective_delay_ms(self, step: int) -> float:
        """Synthetic per-collective latency active at ``step`` (sum of
        overlapping SlowCollective windows)."""
        return sum(f.delay_ms for f in self.plan
                   if isinstance(f, SlowCollective)
                   and f.step <= step < f.until_step)

    # ----------------------------------------------------- recovery hooks
    def on_coordinate(self) -> None:
        """Called inside each mesh re-form attempt. Rejoin-flagged killed
        workers answer the coordination call (a preempted VM that came
        back — the mesh re-forms at full size), then armed coordination
        flakes raise (exercising the retry/backoff path)."""
        for f in self.plan:
            if isinstance(f, KillWorker) and f.fired and f.rejoin:
                self.failed_workers.discard(f.worker)
        self.coordination_attempts += 1
        if self._flakes_armed > 0:
            self._flakes_armed -= 1
            raise CoordinationError(
                f"injected coordination flake "
                f"({self._flakes_armed} more armed)")

    def surviving(self, devices: Sequence) -> List:
        return [d for i, d in enumerate(devices)
                if i not in self.failed_workers]
