"""Pipeline parallelism: GPipe-style microbatched stage pipeline over a mesh
axis.

NET-NEW capability beyond reference parity (SURVEY.md §2.2 records the
reference has data parallelism only; TP/PP/SP are the TPU-idiomatic
extensions the survey directs to build on GSPMD/shard_map meshes).

The practical pipeline case is a deep stack of IDENTICAL blocks (transformer
/ recurrent stacks): block parameters are STACKED on a leading stage axis and
sharded over the ``pipe`` mesh axis, so each device holds 1/n of the
parameters — the actual memory win of pipeline parallelism. This identical-
block restriction is by design: activations hop via ppermute (one static
shape) and params stack on one leading axis; heterogeneous ends (embedding,
LM head) stay outside the pipeline, replicated — see
examples/pipeline_transformer.py for the end-to-end pattern. Microbatches
stream through the classic GPipe schedule: at tick t, stage s processes
microbatch (t - s); activations hop stage-to-stage via ``ppermute`` (ICI
neighbor traffic) inside one ``lax.scan``. Forward is differentiable (scan +
ppermute both have transpose rules), so ``jax.grad`` of a pipelined loss
yields the standard GPipe backward schedule for free.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from .mesh import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply(block_fn: Callable, mesh: Mesh, axis: str = "pipe"):
    """Build a pipelined apply: ``fn(stacked_params, x_micro)``.

    - ``block_fn(params_i, x) -> y``: one stage's computation; all stages
      share this structure (x and y must have identical shapes).
    - ``stacked_params``: pytree whose leaves have a leading ``n_stages``
      axis, sharded on ``axis`` (use :func:`stage_sharding`).
    - ``x_micro``: [n_micro, micro_batch, ...] microbatches (replicated).

    Returns [n_micro, micro_batch, ...] outputs after all stages. Semantics
    identical to applying the n blocks sequentially to each microbatch.
    """
    n = int(mesh.shape[axis])

    def _validate(stacked_params):
        for leaf in jax.tree.leaves(stacked_params):
            if leaf.shape[0] != n:
                raise ValueError(
                    f"stacked stage params must have leading dim == mesh "
                    f"axis size ({n}); got {leaf.shape[0]} — one stage per "
                    f"device (each worker strips its own stage)")

    def worker(params, x_micro):
        # params: this stage's block params (leading stage axis stripped to 1)
        params = jax.tree.map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        n_micro = x_micro.shape[0]
        ticks = n_micro + n - 1
        perm = [(i, (i + 1) % n) for i in range(n)]
        buf = jnp.zeros_like(x_micro[0])      # activation entering this stage
        outs = jnp.zeros_like(x_micro)

        def tick(carry, t):
            buf, outs = carry
            # stage 0 injects microbatch t from the input stream
            inject = jnp.where(t < n_micro, t, 0)
            x_in = jnp.where(stage == 0, x_micro[inject], buf)
            y = block_fn(params, x_in)
            # last stage emits microbatch (t - (n-1)) into the output stream
            emit_idx = t - (n - 1)
            valid = jnp.logical_and(stage == n - 1, emit_idx >= 0)
            outs = jax.lax.cond(
                valid,
                lambda o: o.at[jnp.maximum(emit_idx, 0)].set(y),
                lambda o: o, outs)
            # activations hop to the next stage
            buf = jax.lax.ppermute(y, axis, perm)
            return (buf, outs), None

        (_, outs), _ = jax.lax.scan(tick, (buf, outs), jnp.arange(ticks))
        # outputs live on the LAST stage; share them with every stage so the
        # result is replicated (psum of one-hot contribution)
        outs = jax.lax.psum(jnp.where(stage == n - 1, outs, 0.0), axis)
        return outs

    inner = jax.jit(shard_map(worker, mesh=mesh,
                              in_specs=(P(axis), P()), out_specs=P(),
                              check_vma=False))

    def fn(stacked_params, x_micro):
        _validate(stacked_params)
        return inner(stacked_params, x_micro)

    return fn


def stage_sharding(mesh: Mesh, axis: str = "pipe") -> NamedSharding:
    """Sharding for stacked per-stage parameters: leading axis on ``axis``."""
    return NamedSharding(mesh, P(axis))


def stack_stage_params(param_list) -> dict:
    """Stack a list of per-stage param pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)
