"""Ring attention: sequence/context parallelism over a mesh axis.

NET-NEW capability beyond reference parity (SURVEY.md §5.7 records that the
reference has NO attention and no context parallelism; the survey directs
that the sequence dimension be a shardable mesh axis). This module provides
the TPU-idiomatic long-context primitive: the sequence is sharded across a
``seq`` mesh axis, each device holds one Q/K/V block, and K/V blocks rotate
around the ring via ``jax.lax.ppermute`` while a numerically-stable online
softmax (running max + rescaled partial sums, the FlashAttention recurrence)
accumulates the output — peak memory per device is O(T/n) instead of O(T),
and the permute traffic rides ICI neighbor links.

Public surface:
- ``attention(q, k, v, causal=...)`` — plain single-device reference.
- ``ring_attention_sharded(mesh, axis, ...)`` — builds the shard_map'd
  long-context attention over the mesh; output is bitwise-comparable (up to
  float tolerance) with the single-device version on the gathered sequence.
- ``SelfAttentionLayer`` (nn/layers/attention.py) uses ``attention`` on one
  chip; swap in the sharded variant for long sequences.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from .mesh import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_update(acc, m, l, q, k, v, scale, mask=None):
    """One block of the online-softmax recurrence (FlashAttention-style):
    q [B,H,Tq,D], k/v [B,H,Tk,D]; carry (acc [B,H,Tq,D], m [B,H,Tq],
    l [B,H,Tq])."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * jnp.asarray(scale, q.dtype)
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # exp(-inf - -inf) guards: fully-masked blocks contribute nothing
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(s), s - safe_m[..., None], -jnp.inf))
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return acc_new, m_new, l_new


def attention(q, k, v, *, causal: bool = False,
              scale: Optional[float] = None, key_mask=None):
    """Plain softmax attention, [B,H,T,D] in/out (single-device reference
    semantics for the ring version). ``key_mask`` [B,Tk] excludes padded
    timesteps as keys (large-negative rather than -inf so a fully-masked
    query row yields a uniform distribution instead of NaN)."""
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * jnp.asarray(scale, q.dtype)
    if key_mask is not None:
        s = jnp.where(jnp.asarray(key_mask, q.dtype)[:, None, None, :] > 0,
                      s, -1e30)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, -1e30 if key_mask is not None else -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _ring_body(q, k0, v0, axis, n, causal, scale, t_local):
    """Executes on each device inside shard_map: local q stays, k/v rotate
    n-1 hops; online softmax accumulates across blocks."""
    idx = jax.lax.axis_index(axis)
    B, H, Tq, D = q.shape

    def step(carry, j):
        # lax.scan (not fori_loop): scan has a reverse-mode rule, so the ring
        # is TRAINABLE — jax.grad re-runs the ring backwards with the same
        # ppermute traffic pattern
        acc, m, l, k, v = carry
        src = (idx - j) % n          # which device's k/v block we hold now
        mask = None
        if causal:
            q_pos = idx * t_local + jnp.arange(Tq)[:, None]       # [Tq,1]
            k_pos = src * t_local + jnp.arange(k.shape[2])[None]  # [1,Tk]
            mask = (k_pos <= q_pos)[None, None]                   # [1,1,Tq,Tk]
        acc, m, l = _block_update(acc, m, l, q, k, v, scale, mask)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k = jax.lax.ppermute(k, axis, perm)
        v = jax.lax.ppermute(v, axis, perm)
        return (acc, m, l, k, v), None

    acc = jnp.zeros(q.shape, q.dtype)
    m = jnp.full((B, H, Tq), -jnp.inf, q.dtype)
    l = jnp.zeros((B, H, Tq), q.dtype)
    (acc, m, l, _, _), _ = jax.lax.scan(step, (acc, m, l, k0, v0),
                                        jnp.arange(n))
    return acc / jnp.maximum(l, 1e-20)[..., None]


# ------------------------------------------------------------- fused ring
# The XLA ring body above materializes the local [Tq,Tk] score block in HBM
# every hop; the fused ring folds each hop through the carry-emitting Pallas
# kernel (ops/pallas_attention.flash_block_update) so per-hop HBM traffic is
# O(t_local * D). With EQUAL per-device blocks the causal relation between
# the resident q block and the visiting k/v block is one of exactly three
# cases — fully visible (src < idx), diagonal (src == idx), fully hidden
# (src > idx) — so a lax.switch over non-causal / causal / skip kernels
# covers causality with no global-offset plumbing inside the kernel.
# Backward is the standard ring-attention decomposition: FlashAttention-2
# per-hop contributions with the GLOBAL logsumexp, dk/dv accumulators
# rotating WITH their k/v blocks (after n hops they land back home).


def _ring_fused_fwd(q3, k3, v3, axis, n, causal, scale):
    from ..ops.pallas_attention import flash_block_update
    # axis_index only when causality needs it: a dead PartitionId survives
    # to SPMD partitioning on older XLA CPU backends and aborts the compile
    idx = jax.lax.axis_index(axis) if causal else None
    BH, t, D = q3.shape
    f32 = jnp.float32
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, j):
        acc, m, l, k, v = carry
        src = (idx - j) % n if causal else None
        ops = (acc, m, l)

        def diag(o):
            return flash_block_update(*o, q3, k, v, causal=True, scale=scale)

        def full(o):
            return flash_block_update(*o, q3, k, v, causal=False, scale=scale)

        def skip(o):
            return o

        if causal:
            branch = jnp.where(src == idx, 0, jnp.where(src < idx, 1, 2))
            acc, m, l = jax.lax.switch(branch, [diag, full, skip], ops)
        else:
            acc, m, l = full(ops)
        k = jax.lax.ppermute(k, axis, perm)
        v = jax.lax.ppermute(v, axis, perm)
        return (acc, m, l, k, v), None

    acc = jnp.zeros((BH, t, D), f32)
    m = jnp.full((BH, t, 128), -1e30, f32)
    l = jnp.zeros((BH, t, 128), f32)
    (acc, m, l, _, _), _ = jax.lax.scan(step, (acc, m, l, k3, v3),
                                        jnp.arange(n))
    # epsilon guard matching the XLA ring body: a row that accumulated no
    # probability mass (a future key_mask / all-hops-skipped case) degrades
    # to zeros instead of NaN
    o3 = (acc / jnp.maximum(l[:, :, :1], 1e-20)).astype(q3.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return o3, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_fused(q3, k3, v3, axis, n, causal, scale):
    o3, _ = _ring_fused_fwd(q3, k3, v3, axis, n, causal, scale)
    return o3


def _ring_fused_fwd_rule(q3, k3, v3, axis, n, causal, scale):
    o3, lse = _ring_fused_fwd(q3, k3, v3, axis, n, causal, scale)
    return o3, (q3, k3, v3, o3, lse)


def _ring_fused_bwd_rule(axis, n, causal, scale, res, do3):
    from ..ops.pallas_attention import flash_block_bwd
    q3, k3, v3, o3, lse = res
    idx = jax.lax.axis_index(axis) if causal else None
    f32 = jnp.float32
    perm = [(i, (i + 1) % n) for i in range(n)]
    zero = (jnp.zeros(q3.shape, f32),) + 2 * (jnp.zeros(k3.shape, f32),)

    def step(carry, j):
        dq, dk, dv, k, v = carry
        src = (idx - j) % n if causal else None

        def diag(ops):
            out = flash_block_bwd(q3, *ops, o3, lse, do3, causal=True,
                                  scale=scale)
            return tuple(x.astype(f32) for x in out)

        def full(ops):
            out = flash_block_bwd(q3, *ops, o3, lse, do3, causal=False,
                                  scale=scale)
            return tuple(x.astype(f32) for x in out)

        def skip(ops):
            return zero

        if causal:
            branch = jnp.where(src == idx, 0, jnp.where(src < idx, 1, 2))
            dq_c, dk_c, dv_c = jax.lax.switch(branch, [diag, full, skip],
                                              (k, v))
        else:
            dq_c, dk_c, dv_c = full((k, v))
        dq = dq + dq_c
        dk = dk + dk_c
        dv = dv + dv_c
        # dk/dv accumulators travel WITH their k/v blocks: after n hops
        # each lands on the device that owns its block
        k, v, dk, dv = (jax.lax.ppermute(x, axis, perm)
                        for x in (k, v, dk, dv))
        return (dq, dk, dv, k, v), None

    dq = jnp.zeros(q3.shape, f32)
    dk = jnp.zeros(k3.shape, f32)
    dv = jnp.zeros(v3.shape, f32)
    (dq, dk, dv, _, _), _ = jax.lax.scan(step, (dq, dk, dv, k3, v3),
                                         jnp.arange(n))
    return (dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype))


_ring_fused.defvjp(_ring_fused_fwd_rule, _ring_fused_bwd_rule)


def _ring_body_fused(q, k0, v0, axis, n, causal, scale):
    B, H, t, D = q.shape
    o3 = _ring_fused(q.reshape(B * H, t, D), k0.reshape(B * H, t, D),
                     v0.reshape(B * H, t, D), axis, n, causal, scale)
    return o3.reshape(B, H, t, D)


def ring_attention_sharded(mesh: Mesh, axis: str = "seq", *,
                           causal: bool = False,
                           scale: Optional[float] = None,
                           use_fused: Optional[bool] = None):
    """Build a jitted ring-attention fn over ``mesh``: inputs [B,H,T,D] with
    T sharded on ``axis`` (T must divide evenly); output sharded the same.

        fn = ring_attention_sharded(mesh, "seq", causal=True)
        out = fn(q, k, v)     # q,k,v sharded NamedSharding(mesh, P(None,None,"seq"))

    ``use_fused``: None (default) probes fused_ring_applicable and takes
    the Pallas carry-emitting hop kernels when the local block qualifies
    (O(t_local*D) HBM traffic per hop instead of the XLA body's [Tq,Tk]
    score materialization); True forces, False opts out.
    """
    from ..ops.pallas_attention import fused_ring_applicable
    n = int(mesh.shape[axis])

    def fn(q, k, v):
        sc = float(scale) if scale is not None else 1.0 / float(np.sqrt(q.shape[-1]))
        t_local = q.shape[2] // n
        fused = use_fused
        if fused is None:
            fused = fused_ring_applicable(t_local, q.shape[-1], q.dtype)
        elif fused and not (t_local > 0 and t_local % 128 == 0
                            and (q.shape[-1] % 128 == 0
                                 or q.shape[-1] in (64, 96))):
            # validate the explicit opt-in HERE, at the misuse site — the
            # alternative is a confusing 'T not a multiple of 128'
            # ValueError from deep inside the Pallas kernel's block sizing
            # (ops/pallas_attention._blocks) at trace time. Only the HARD
            # shape constraints are enforced: an explicit True is allowed
            # to force the interpret path on a non-TPU backend (the
            # multichip dryrun and the CPU parity tests do exactly that),
            # which the fused_ring_applicable auto-probe would refuse.
            raise ValueError(
                f"use_fused=True, but the fused ring-hop kernels cannot "
                f"serve this call: t_local = T/ring_size = "
                f"{q.shape[2]}/{n} = {t_local} must be a positive "
                f"multiple of 128 (the TPU lane dim), with head dim "
                f"{q.shape[-1]} in (64, 96, any multiple of 128). Pass "
                f"use_fused=None to auto-fallback to the XLA ring body "
                f"instead")
        if fused:
            body = functools.partial(_ring_body_fused, axis=axis, n=n,
                                     causal=causal, scale=sc)
        else:
            body = functools.partial(_ring_body, axis=axis, n=n,
                                     causal=causal, scale=sc,
                                     t_local=t_local)
        spec = P(None, None, axis, None)
        sharded = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False)
        return sharded(q, k, v)

    return jax.jit(fn)


def sequence_sharding(mesh: Mesh, axis: str = "seq") -> NamedSharding:
    """Sharding for [B,H,T,D] tensors with the time axis on ``axis``."""
    return NamedSharding(mesh, P(None, None, axis, None))
