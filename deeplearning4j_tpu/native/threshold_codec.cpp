// Host-side threshold compression codec.
//
// Reference: the ND4J NATIVE ops behind EncodingHandler.java:64-66
// (Nd4j.getExecutioner().thresholdEncode/thresholdDecode) — the reference's
// sparse sign+threshold quantizer is C++ in libnd4j; this is the TPU build's
// native equivalent for the host/DCN boundary (the on-device variant is
// ops/compression.py). Semantics are kept bit-identical to the XLA path:
// top-`capacity` entries by |residual| (ties broken by LOWER index, matching
// jax.lax.top_k), entries clearing `threshold` are quantized to +-threshold
// and subtracted from the residual (Strom error feedback).
//
// Built with: g++ -O3 -shared -fPIC threshold_codec.cpp -o libthreshold_codec.so
// Loaded via ctypes (deeplearning4j_tpu/native/__init__.py) — no pybind11.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <numeric>
#include <vector>

extern "C" {

// Encode the largest-magnitude entries of residual[n] that clear `threshold`.
// Writes up to `capacity` (index, sign) pairs; unused slots get sign 0 (their
// index is still the top-k index, mirroring the XLA payload layout). Residual
// is updated IN PLACE (sent mass subtracted). Returns the live-entry count.
int threshold_encode(float* residual, int64_t n, float threshold,
                     int64_t capacity, int32_t* idx_out, int8_t* sign_out) {
  if (capacity > n) capacity = n;
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  // top-`capacity` by magnitude, ties -> lower index first (jax.lax.top_k)
  std::partial_sort(order.begin(), order.begin() + capacity, order.end(),
                    [&](int64_t a, int64_t b) {
                      float ma = std::fabs(residual[a]);
                      float mb = std::fabs(residual[b]);
                      if (ma != mb) return ma > mb;
                      return a < b;
                    });
  int count = 0;
  for (int64_t k = 0; k < capacity; ++k) {
    int64_t i = order[k];
    idx_out[k] = static_cast<int32_t>(i);
    float v = residual[i];
    if (std::fabs(v) >= threshold) {
      int8_t s = (v > 0.0f) ? 1 : ((v < 0.0f) ? -1 : 0);
      sign_out[k] = s;
      residual[i] -= s * threshold;
      if (s != 0) ++count;
    } else {
      sign_out[k] = 0;
    }
  }
  return count;
}

// Reconstruct the dense update a payload represents (SilentTrainingDriver
// thresholdDecode): out[idx[k]] += sign[k] * threshold. `out` must be
// zero-initialized by the caller (or hold a partial sum to accumulate into —
// the receiving-accumulator semantics of the reference).
void threshold_decode(const int32_t* idx, const int8_t* signs,
                      int64_t capacity, float threshold, float* out,
                      int64_t n) {
  for (int64_t k = 0; k < capacity; ++k) {
    int32_t i = idx[k];
    if (i >= 0 && i < n && signs[k] != 0) {
      out[i] += signs[k] * threshold;
    }
  }
}

}  // extern "C"
