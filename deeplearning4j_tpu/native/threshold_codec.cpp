// Host-side threshold compression codec.
//
// Reference: the ND4J NATIVE ops behind EncodingHandler.java:64-66
// (Nd4j.getExecutioner().thresholdEncode/thresholdDecode) — the reference's
// sparse sign+threshold quantizer is C++ in libnd4j; this is the TPU build's
// native equivalent for the host/DCN boundary (the on-device variant is
// ops/compression.py). Semantics are kept bit-identical to the XLA path:
// a SINGLE PASS takes every entry clearing `threshold` in index order until
// the payload is full (the reference encodes all >=threshold entries with
// no magnitude ordering — EncodingHandler.java:64-66; the capacity bound is
// the static-shape adaptation, and what doesn't fit stays in the residual
// for the next round, the Strom error feedback). Taken entries are
// quantized to +-threshold and subtracted from the residual.
//
// Built with: g++ -O3 -shared -fPIC threshold_codec.cpp -o libthreshold_codec.so
// Loaded via ctypes (deeplearning4j_tpu/native/__init__.py) — no pybind11.

#include <cmath>
#include <cstdint>

extern "C" {

// Encode entries of residual[n] clearing `threshold`, in index order, up
// to `capacity`. Unused payload slots get index 0 / sign 0 (decode adds
// nothing for sign 0, mirroring the XLA payload layout). Residual is
// updated IN PLACE (sent mass subtracted). Returns the encoded count.
int threshold_encode(float* residual, int64_t n, float threshold,
                     int64_t capacity, int32_t* idx_out, int8_t* sign_out) {
  if (capacity > n) capacity = n;
  int64_t k = 0;
  for (int64_t i = 0; i < n && k < capacity; ++i) {
    float v = residual[i];
    if (std::fabs(v) >= threshold) {
      int8_t s = (v > 0.0f) ? 1 : ((v < 0.0f) ? -1 : 0);
      if (s == 0) continue;   // threshold == 0 with v == 0
      idx_out[k] = static_cast<int32_t>(i);
      sign_out[k] = s;
      residual[i] -= s * threshold;
      ++k;
    }
  }
  for (int64_t r = k; r < capacity; ++r) {
    idx_out[r] = 0;
    sign_out[r] = 0;
  }
  return static_cast<int>(k);
}

// Reconstruct the dense update a payload represents (SilentTrainingDriver
// thresholdDecode): out[idx[k]] += sign[k] * threshold. `out` must be
// zero-initialized by the caller (or hold a partial sum to accumulate into —
// the receiving-accumulator semantics of the reference).
void threshold_decode(const int32_t* idx, const int8_t* signs,
                      int64_t capacity, float threshold, float* out,
                      int64_t n) {
  for (int64_t k = 0; k < capacity; ++k) {
    int32_t i = idx[k];
    if (i >= 0 && i < n && signs[k] != 0) {
      out[i] += signs[k] * threshold;
    }
  }
}

}  // extern "C"
