"""Native (C++) components, built on demand and loaded via ctypes.

The reference leans on libnd4j (C++/CUDA) for its native ops (SURVEY.md §2.6);
the TPU build keeps the device path in XLA and provides C++ equivalents only
where the work is host-side by nature — e.g. the threshold codec a DCN hop
would run on the host network boundary (reference's thresholdEncode/Decode
are native ND4J ops, EncodingHandler.java:64-66).

Build strategy: `g++ -O3 -shared -fPIC` into the package's `_build/`
directory on first use (no pybind11 in the image; ctypes binds the extern-C
surface). Everything degrades gracefully: `available()` is False when no
compiler is present and callers fall back to the XLA/numpy path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_SR_LIB: Optional[ctypes.CDLL] = None
_SR_TRIED = False


def _compile(src_name: str, lib_name: str) -> Optional[ctypes.CDLL]:
    """g++ -O3 -shared -fPIC on demand; None when no toolchain."""
    src = os.path.join(_HERE, src_name)
    out = os.path.join(_BUILD_DIR, lib_name)
    if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = ["g++", "-O3", "-shared", "-fPIC", src, "-o", out]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        return ctypes.CDLL(out)
    except OSError:
        return None


def _build_and_load() -> Optional[ctypes.CDLL]:
    lib = _compile("threshold_codec.cpp", "libthreshold_codec.so")
    if lib is None:
        return None
    lib.threshold_encode.restype = ctypes.c_int
    lib.threshold_encode.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int8)]
    lib.threshold_decode.restype = None
    lib.threshold_decode.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int8),
        ctypes.c_int64, ctypes.c_float, ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64]
    return lib


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if not _TRIED:
            _LIB = _build_and_load()
            _TRIED = True
    return _LIB


def available() -> bool:
    """True when the native codec compiled and loaded on this host."""
    return _lib() is not None


def native_threshold_encode(residual: np.ndarray, threshold: float,
                            capacity: int):
    """C++ threshold encode. Mutates nothing: returns
    (indices[int32 capacity], signs[int8 capacity], count, new_residual).
    Semantics identical to ops.compression.threshold_encode."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native threshold codec unavailable (no g++?); "
                           "use ops.compression.threshold_encode instead")
    res = np.ascontiguousarray(residual, np.float32).copy()
    n = res.shape[0]
    capacity = min(int(capacity), n)
    idx = np.zeros(capacity, np.int32)
    signs = np.zeros(capacity, np.int8)
    count = lib.threshold_encode(
        res.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n,
        ctypes.c_float(threshold), capacity,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        signs.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)))
    return idx, signs, int(count), res


def native_threshold_decode(idx: np.ndarray, signs: np.ndarray,
                            threshold: float, size: int) -> np.ndarray:
    lib = _lib()
    if lib is None:
        raise RuntimeError("native threshold codec unavailable (no g++?); "
                           "use ops.compression.threshold_decode instead")
    idx = np.ascontiguousarray(idx, np.int32)
    signs = np.ascontiguousarray(signs, np.int8)
    out = np.zeros(size, np.float32)
    lib.threshold_decode(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        signs.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        idx.shape[0], ctypes.c_float(threshold),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), size)
    return out


# ------------------------------------------------------------ shard reader
def _sr_build_and_load() -> Optional[ctypes.CDLL]:
    lib = _compile("shard_reader.cpp", "libshard_reader.so")
    if lib is None:
        return None
    c = ctypes
    lib.sr_open.restype = c.c_void_p
    lib.sr_open.argtypes = [c.c_char_p]
    lib.sr_num_members.restype = c.c_int
    lib.sr_num_members.argtypes = [c.c_void_p]
    lib.sr_member_name.restype = c.c_char_p
    lib.sr_member_name.argtypes = [c.c_void_p, c.c_int]
    lib.sr_member_descr.restype = c.c_char_p
    lib.sr_member_descr.argtypes = [c.c_void_p, c.c_int]
    lib.sr_member_ndim.restype = c.c_int
    lib.sr_member_ndim.argtypes = [c.c_void_p, c.c_int]
    lib.sr_member_shape.restype = None
    lib.sr_member_shape.argtypes = [c.c_void_p, c.c_int,
                                    c.POINTER(c.c_int64)]
    lib.sr_member_fortran.restype = c.c_int
    lib.sr_member_fortran.argtypes = [c.c_void_p, c.c_int]
    lib.sr_member_nbytes.restype = c.c_int64
    lib.sr_member_nbytes.argtypes = [c.c_void_p, c.c_int]
    lib.sr_read.restype = c.c_int
    lib.sr_read.argtypes = [c.c_void_p, c.c_int, c.c_void_p]
    lib.sr_close.restype = None
    lib.sr_close.argtypes = [c.c_void_p]
    return lib


def _sr_lib() -> Optional[ctypes.CDLL]:
    global _SR_LIB, _SR_TRIED
    with _LOCK:
        if not _SR_TRIED:
            _SR_LIB = _sr_build_and_load()
            _SR_TRIED = True
    return _SR_LIB


def shard_reader_available() -> bool:
    """True when the native shard reader compiled and loaded on this host."""
    return _sr_lib() is not None


class NativeNpzFile:
    """np.load-compatible view of an uncompressed .npz, served by the C++
    mmap reader (datasets/export.py's shard format): exposes ``.files`` and
    ``__getitem__`` like numpy's NpzFile, but the zip/npy headers are
    parsed natively and member payloads arrive via a single GIL-free
    memcpy. Context-manage or .close() to drop the mmap."""

    def __init__(self, path: str):
        lib = _sr_lib()
        if lib is None:
            raise RuntimeError("native shard reader unavailable (no g++?); "
                               "use numpy.load instead")
        self._lib = lib
        self._h = lib.sr_open(os.fsencode(path))
        if not self._h:
            raise OSError(f"native shard reader could not parse {path!r} "
                          "(not an uncompressed npz?)")
        n = lib.sr_num_members(self._h)
        self.files = [lib.sr_member_name(self._h, i).decode()
                      for i in range(n)]
        self._index = {name: i for i, name in enumerate(self.files)}

    def __getitem__(self, name: str) -> np.ndarray:
        i = self._index[name]
        lib = self._lib
        ndim = lib.sr_member_ndim(self._h, i)
        shape = (ctypes.c_int64 * max(ndim, 1))()
        if ndim:
            lib.sr_member_shape(self._h, i, shape)
        descr = lib.sr_member_descr(self._h, i).decode()
        order = "F" if lib.sr_member_fortran(self._h, i) else "C"
        out = np.empty(tuple(shape[:ndim]), dtype=np.dtype(descr),
                       order=order)
        nbytes = lib.sr_member_nbytes(self._h, i)
        if out.nbytes != nbytes:
            # parse_npy's element-size heuristic disagreed with numpy's
            # itemsize for this descr — an unchecked memcpy here would
            # silently corrupt, so refuse instead
            raise ValueError(
                f"member {name!r}: descr {descr!r} implies {out.nbytes} "
                f"bytes but native header says {nbytes}")
        lib.sr_read(self._h, i, out.ctypes.data_as(ctypes.c_void_p))
        if out.dtype.kind == "V":
            # np.savez stores ml_dtypes bfloat16 as a raw 2-byte void
            # ('|V2', or '<V2'/'=V2' depending on the numpy version's
            # byte-order tag; np.load returns the same). ONLY those exact
            # descrs are reinterpreted — same recovery as
            # util/distributed_checkpoint.py; any other void dtype (a
            # structured record, '|V4', a big-endian '>V2', ...) is not
            # ours to guess at, so refuse rather than silently mis-type it
            # (mirrors the nbytes strictness above).
            if descr in ("|V2", "<V2", "=V2"):
                import ml_dtypes
                out = out.view(ml_dtypes.bfloat16)
            else:
                raise ValueError(
                    f"member {name!r}: void dtype descr {descr!r} is not "
                    "the raw-bfloat16 '|V2' this shard format produces — "
                    "refusing to reinterpret an unknown void layout")
        return out

    def close(self):
        if self._h:
            self._lib.sr_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass
