"""Native (C++) components, built on demand and loaded via ctypes.

The reference leans on libnd4j (C++/CUDA) for its native ops (SURVEY.md §2.6);
the TPU build keeps the device path in XLA and provides C++ equivalents only
where the work is host-side by nature — e.g. the threshold codec a DCN hop
would run on the host network boundary (reference's thresholdEncode/Decode
are native ND4J ops, EncodingHandler.java:64-66).

Build strategy: `g++ -O3 -shared -fPIC` into the package's `_build/`
directory on first use (no pybind11 in the image; ctypes binds the extern-C
surface). Everything degrades gracefully: `available()` is False when no
compiler is present and callers fall back to the XLA/numpy path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = os.path.join(_HERE, "threshold_codec.cpp")
    out = os.path.join(_BUILD_DIR, "libthreshold_codec.so")
    if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = ["g++", "-O3", "-shared", "-fPIC", src, "-o", out]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        lib = ctypes.CDLL(out)
    except OSError:
        return None
    lib.threshold_encode.restype = ctypes.c_int
    lib.threshold_encode.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int8)]
    lib.threshold_decode.restype = None
    lib.threshold_decode.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int8),
        ctypes.c_int64, ctypes.c_float, ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64]
    return lib


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if not _TRIED:
            _LIB = _build_and_load()
            _TRIED = True
    return _LIB


def available() -> bool:
    """True when the native codec compiled and loaded on this host."""
    return _lib() is not None


def native_threshold_encode(residual: np.ndarray, threshold: float,
                            capacity: int):
    """C++ threshold encode. Mutates nothing: returns
    (indices[int32 capacity], signs[int8 capacity], count, new_residual).
    Semantics identical to ops.compression.threshold_encode."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native threshold codec unavailable (no g++?); "
                           "use ops.compression.threshold_encode instead")
    res = np.ascontiguousarray(residual, np.float32).copy()
    n = res.shape[0]
    capacity = min(int(capacity), n)
    idx = np.zeros(capacity, np.int32)
    signs = np.zeros(capacity, np.int8)
    count = lib.threshold_encode(
        res.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n,
        ctypes.c_float(threshold), capacity,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        signs.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)))
    return idx, signs, int(count), res


def native_threshold_decode(idx: np.ndarray, signs: np.ndarray,
                            threshold: float, size: int) -> np.ndarray:
    lib = _lib()
    if lib is None:
        raise RuntimeError("native threshold codec unavailable (no g++?); "
                           "use ops.compression.threshold_decode instead")
    idx = np.ascontiguousarray(idx, np.int32)
    signs = np.ascontiguousarray(signs, np.int8)
    out = np.zeros(size, np.float32)
    lib.threshold_decode(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        signs.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        idx.shape[0], ctypes.c_float(threshold),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), size)
    return out
