// Native shard reader: mmap'd zero-copy access to exported .npz shards.
//
// Reference capability: the reference's data plane is native — DataVec's
// loaders and ND4J's IO run in C++ under the JVM (SURVEY.md §2.6 / §3 L3);
// its Spark workers stream exported batch files through that native path.
// Here the export-shard format (datasets/export.py: uncompressed .npz =
// zip of .npy members, np.savez) gets the same treatment: the zip central
// directory and the npy headers are parsed in C++, the file is mmap'd, and
// member bytes are served either zero-copy (pointer into the map) or by a
// GIL-free memcpy — the Python path (np.load) re-parses headers and copies
// through BufferedIO on every shard.
//
// Scope: STORED (method 0) zip members only — np.savez never compresses —
// classic (non-zip64) format, which covers shards to 4GB.
//
// Built with: g++ -O3 -shared -fPIC shard_reader.cpp -o libshard_reader.so
// Loaded via ctypes (deeplearning4j_tpu/native/__init__.py) — no pybind11.

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

struct Member {
  std::string name;
  std::string descr;        // npy dtype string, e.g. "<f4"
  int64_t shape[32];
  int ndim = 0;
  int fortran = 0;
  uint64_t data_off = 0;    // absolute offset of the array bytes
  uint64_t nbytes = 0;      // array payload size
};

struct Reader {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t size = 0;
  std::vector<Member> members;
};

uint16_t rd16(const uint8_t* p) { uint16_t v; std::memcpy(&v, p, 2); return v; }
uint32_t rd32(const uint8_t* p) { uint32_t v; std::memcpy(&v, p, 4); return v; }

// Parse one npy header at `off`; fills descr/shape/data offset. Returns
// false on malformed input.
bool parse_npy(const uint8_t* base, size_t limit, uint64_t off, Member* m) {
  static const uint8_t magic[6] = {0x93, 'N', 'U', 'M', 'P', 'Y'};
  if (off + 10 > limit || std::memcmp(base + off, magic, 6) != 0) return false;
  uint8_t major = base[off + 6];
  uint64_t hlen, hstart;
  if (major == 1) {
    hlen = rd16(base + off + 8);
    hstart = off + 10;
  } else {                                   // v2/v3: 4-byte header length
    if (off + 12 > limit) return false;
    hlen = rd32(base + off + 8);
    hstart = off + 12;
  }
  if (hstart + hlen > limit) return false;
  std::string h(reinterpret_cast<const char*>(base + hstart), hlen);

  auto find_value = [&](const char* key) -> size_t {
    size_t k = h.find(key);
    if (k == std::string::npos) return std::string::npos;
    k = h.find(':', k);
    return k == std::string::npos ? k : k + 1;
  };

  size_t p = find_value("'descr'");
  if (p == std::string::npos) return false;
  size_t q1 = h.find('\'', p);
  size_t q2 = h.find('\'', q1 + 1);
  if (q1 == std::string::npos || q2 == std::string::npos) return false;
  m->descr = h.substr(q1 + 1, q2 - q1 - 1);

  p = find_value("'fortran_order'");
  if (p == std::string::npos) return false;
  size_t v = h.find_first_not_of(' ', p);
  m->fortran = (v != std::string::npos && h.compare(v, 4, "True") == 0) ? 1 : 0;

  p = find_value("'shape'");
  if (p == std::string::npos) return false;
  size_t lp = h.find('(', p), rp = h.find(')', p);
  if (lp == std::string::npos || rp == std::string::npos) return false;
  m->ndim = 0;
  int64_t cur = -1;
  for (size_t i = lp + 1; i <= rp; ++i) {
    char c = h[i];
    if (c >= '0' && c <= '9') {
      cur = (cur < 0 ? 0 : cur) * 10 + (c - '0');
    } else if (cur >= 0) {
      if (m->ndim >= 32) return false;
      m->shape[m->ndim++] = cur;
      cur = -1;
    }
  }
  // element size from descr tail (e.g. "<f4" -> 4; "|V2" -> 2)
  int64_t esize = 0;
  for (char c : m->descr)
    if (c >= '0' && c <= '9') esize = esize * 10 + (c - '0');
  if (esize <= 0) return false;
  int64_t count = 1;
  for (int i = 0; i < m->ndim; ++i) count *= m->shape[i];
  m->data_off = hstart + hlen;
  m->nbytes = static_cast<uint64_t>(count * esize);
  return m->data_off + m->nbytes <= limit;
}

}  // namespace

extern "C" {

void* sr_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 22) { ::close(fd); return nullptr; }
  size_t size = static_cast<size_t>(st.st_size);
  void* map = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) { ::close(fd); return nullptr; }
  const uint8_t* base = static_cast<const uint8_t*>(map);

  // end-of-central-directory: scan back over the (usually empty) comment
  int64_t eocd = -1;
  int64_t lo = static_cast<int64_t>(size) - 22;
  int64_t stop = lo > 65557 ? lo - 65557 : 0;
  for (int64_t i = lo; i >= stop; --i) {
    if (rd32(base + i) == 0x06054b50u) { eocd = i; break; }
  }
  auto fail = [&]() -> void* {
    munmap(map, size); ::close(fd); return nullptr;
  };
  if (eocd < 0) return fail();
  uint16_t count = rd16(base + eocd + 10);
  uint32_t cd_off = rd32(base + eocd + 16);
  if (cd_off >= size) return fail();

  Reader* r = new Reader{fd, base, size, {}};
  uint64_t p = cd_off;
  for (uint16_t i = 0; i < count; ++i) {
    if (p + 46 > size || rd32(base + p) != 0x02014b50u) { delete r; return fail(); }
    uint16_t method = rd16(base + p + 10);
    uint16_t nlen = rd16(base + p + 28);
    uint16_t xlen = rd16(base + p + 30);
    uint16_t clen = rd16(base + p + 32);
    uint32_t local_off = rd32(base + p + 42);
    std::string name(reinterpret_cast<const char*>(base + p + 46), nlen);
    p += 46 + nlen + xlen + clen;
    if (method != 0) { delete r; return fail(); }   // stored only (np.savez)
    if (local_off + 30 > size ||
        rd32(base + local_off) != 0x04034b50u) { delete r; return fail(); }
    uint16_t lnlen = rd16(base + local_off + 26);
    uint16_t lxlen = rd16(base + local_off + 28);
    uint64_t npy_off = static_cast<uint64_t>(local_off) + 30 + lnlen + lxlen;
    Member m;
    // strip the ".npy" suffix np.savez appends to member names
    m.name = (name.size() > 4 && name.compare(name.size() - 4, 4, ".npy") == 0)
                 ? name.substr(0, name.size() - 4) : name;
    if (!parse_npy(base, size, npy_off, &m)) { delete r; return fail(); }
    r->members.push_back(std::move(m));
  }
  return r;
}

int sr_num_members(void* h) {
  return static_cast<int>(static_cast<Reader*>(h)->members.size());
}

const char* sr_member_name(void* h, int i) {
  return static_cast<Reader*>(h)->members[i].name.c_str();
}

const char* sr_member_descr(void* h, int i) {
  return static_cast<Reader*>(h)->members[i].descr.c_str();
}

int sr_member_ndim(void* h, int i) {
  return static_cast<Reader*>(h)->members[i].ndim;
}

void sr_member_shape(void* h, int i, int64_t* out) {
  const Member& m = static_cast<Reader*>(h)->members[i];
  std::memcpy(out, m.shape, sizeof(int64_t) * m.ndim);
}

int sr_member_fortran(void* h, int i) {
  return static_cast<Reader*>(h)->members[i].fortran;
}

int64_t sr_member_nbytes(void* h, int i) {
  return static_cast<int64_t>(static_cast<Reader*>(h)->members[i].nbytes);
}

// GIL-free bulk copy of a member's payload into dst (caller sizes it).
int sr_read(void* h, int i, void* dst) {
  Reader* r = static_cast<Reader*>(h);
  const Member& m = r->members[i];
  std::memcpy(dst, r->map + m.data_off, m.nbytes);
  return 0;
}

// Zero-copy pointer into the mmap (valid until sr_close).
const void* sr_member_ptr(void* h, int i) {
  Reader* r = static_cast<Reader*>(h);
  return r->map + r->members[i].data_off;
}

void sr_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  munmap(const_cast<uint8_t*>(r->map), r->size);
  ::close(r->fd);
  delete r;
}

}  // extern "C"
