"""Shape-bucketed dynamic batcher: bounded queue, deadlines, drain.

Fixes the legacy ParallelInference contract holes by construction:
  - a candidate that would overshoot the largest bucket is DEFERRED to the
    next batch, never merged (the legacy loop appended whatever it popped);
  - every admitted request is resolved exactly once — served, failed with
    the model error, failed at shutdown, or skipped as expired — so callers
    with ``event.wait(timeout)`` can never hang;
  - admission is fast-fail: a full queue or a draining batcher raises
    immediately (HTTP 429/503) instead of blocking the caller.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional, Tuple

import numpy as np

from ..telemetry.flightrec import get_flight_recorder
from ..telemetry.registry import get_registry
from ..telemetry.tracecontext import current_trace_id, event
from .buckets import BucketLadder
from .errors import (DeadlineExceededError, DrainingError, QueueFullError,
                     ShapeMismatchError)
from .metrics import ServingMetrics


class _Request:
    __slots__ = ("x", "n", "event", "result", "error", "enqueue_t",
                 "deadline", "abandoned", "trace_id")

    def __init__(self, x: np.ndarray, deadline: float):
        self.x = x
        self.n = x.shape[0]
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.enqueue_t = time.monotonic()
        self.deadline = deadline
        self.abandoned = False        # caller gave up (deadline expired)
        # request tracing: the submitter's trace id rides the queued
        # request across the handoff to the dispatch thread, which stamps
        # it on the per-request batch events (None = untraced caller:
        # zero per-request trace cost)
        self.trace_id = current_trace_id()


class ShapeBucketedBatcher:
    """Coalesces concurrent ``submit()`` callers into padded ladder-bucket
    batches and runs them through ``runner`` (an np.ndarray -> np.ndarray
    callable over pre-compiled programs; the engine resolves the active
    model version per batch, which is what makes hot-swap seamless)."""

    def __init__(self, runner: Callable[[np.ndarray], np.ndarray],
                 ladder: BucketLadder, feature_shape: Tuple[int, ...],
                 dtype=np.float32, *, queue_limit: int = 256,
                 batch_window_ms: float = 2.0,
                 default_timeout_s: float = 30.0,
                 metrics: Optional[ServingMetrics] = None,
                 name: str = "default"):
        self._runner = runner
        self.ladder = ladder
        self.feature_shape = tuple(int(d) for d in feature_shape)
        self.dtype = np.dtype(dtype)
        self.queue_limit = queue_limit
        self.window_s = batch_window_ms / 1000.0
        self.default_timeout_s = default_timeout_s
        self.metrics = metrics or ServingMetrics()
        self.name = name
        self._dq: "deque[_Request]" = deque()
        self._cond = threading.Condition()
        self._draining = False
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"serving-batcher-{name}")
        self._thread.start()

    # ------------------------------------------------------------- admission
    @property
    def queue_depth(self) -> int:
        return len(self._dq)

    @property
    def draining(self) -> bool:
        return self._draining

    def submit(self, x, timeout: Optional[float] = None) -> np.ndarray:
        """Blocking predict with a hard deadline. Oversized requests are
        chunked across max-bucket sub-requests and reassembled, so callers
        see the legacy accept-any-size contract with bounded programs."""
        t_start = time.monotonic()
        timeout = self.default_timeout_s if timeout is None else timeout
        deadline = t_start + timeout
        x = np.asarray(x)
        if x.ndim == len(self.feature_shape):      # single row convenience
            x = x[None]
        if x.shape[0] == 0:
            raise ShapeMismatchError("empty request (0 rows)")
        if tuple(x.shape[1:]) != self.feature_shape:
            raise ShapeMismatchError(
                f"model '{self.name}' serves feature shape "
                f"{self.feature_shape}, got {tuple(x.shape[1:])}")
        x = np.ascontiguousarray(x, self.dtype)
        mx = self.ladder.max
        if x.shape[0] <= mx:
            out = self._submit_one(x, deadline)
        else:
            reqs = []
            try:
                for off in range(0, x.shape[0], mx):
                    reqs.append(self._enqueue(x[off:off + mx], deadline))
                parts = [self._await(r, deadline) for r in reqs]
            except BaseException:
                # partial failure (queue full / deadline / model error):
                # abandon the sibling chunks so the dispatcher skips them
                # instead of running padded batches nobody is waiting on
                for r in reqs:
                    r.abandoned = True
                raise
            out = np.concatenate(parts, axis=0)
        self.metrics.record_request(
            (time.monotonic() - t_start) * 1000.0, x.shape[0])
        return out

    def _submit_one(self, x: np.ndarray, deadline: float) -> np.ndarray:
        req = self._enqueue(x, deadline)
        return self._await(req, deadline)

    def _enqueue(self, x: np.ndarray, deadline: float) -> _Request:
        req = _Request(x, deadline)
        with self._cond:
            if self._draining or self._stopped:
                self.metrics.record_rejection("draining")
                raise DrainingError(
                    f"model '{self.name}' is draining/stopped")
            if len(self._dq) >= self.queue_limit:
                self.metrics.record_rejection("full")
                raise QueueFullError(
                    f"model '{self.name}' queue full "
                    f"({self.queue_limit} requests)")
            self._dq.append(req)
            self._cond.notify_all()
        if req.trace_id is not None:
            event("serving.admit", model=self.name, rows=req.n,
                  queue_depth=len(self._dq))
        return req

    def _await(self, req: _Request, deadline: float) -> np.ndarray:
        remaining = deadline - time.monotonic()
        if not req.event.wait(max(0.0, remaining)):
            req.abandoned = True
        if req.event.is_set():     # dispatcher resolved it (maybe in the race)
            if req.error is not None:
                if isinstance(req.error, DeadlineExceededError):
                    self.metrics.record_rejection("deadline")
                raise req.error
            return req.result
        self.metrics.record_rejection("deadline")
        raise DeadlineExceededError(
            f"deadline expired after "
            f"{round(deadline - req.enqueue_t, 3)}s "
            f"(queue depth {self.queue_depth})")

    # -------------------------------------------------------------- dispatch
    def _loop(self):
        while True:
            first = self._take_first()
            if first is None:
                return                         # stopped and queue empty
            batch, total = [first], first.n
            window_end = time.monotonic() + self.window_s
            mx = self.ladder.max
            while total < mx:
                now = time.monotonic()
                if now >= window_end and not self._dq:
                    break
                with self._cond:
                    r = self._dq[0] if self._dq else None
                    if r is not None:
                        if r.abandoned or (now > r.deadline):
                            self._dq.popleft()
                            self._expire(r)
                            continue
                        if total + r.n > mx:
                            break              # DEFER: next batch, no overshoot
                        self._dq.popleft()
                    elif now < window_end and not self._stopped:
                        if all(b.abandoned or now > b.deadline
                               for b in batch):
                            break     # nobody left waiting: free the window
                        self._cond.wait(min(window_end - now, 0.0005))
                        continue
                    else:
                        break
                batch.append(r)
                total += r.n
            self._dispatch(batch, total)

    def _take_first(self) -> Optional[_Request]:
        while True:
            with self._cond:
                while not self._dq and not self._stopped:
                    self._cond.wait(0.05)
                if not self._dq:
                    return None                # stopped + drained
                req = self._dq.popleft()
            if req.abandoned or time.monotonic() > req.deadline:
                self._expire(req)
                continue
            return req

    def _expire(self, req: _Request) -> None:
        req.error = DeadlineExceededError("deadline expired while queued")
        req.event.set()

    def _dispatch(self, batch, total: int) -> None:
        t_disp = time.monotonic()
        # drop requests whose caller already gave up (their 504 is raised);
        # running them would spend a padded device batch on nobody
        live = []
        for r in batch:
            if r.abandoned or t_disp > r.deadline:
                self._expire(r)
            else:
                live.append(r)
        if not live:
            return
        batch = live
        total = sum(r.n for r in batch)
        bucket = self.ladder.bucket_for(total)
        padded = np.zeros((bucket,) + self.feature_shape, self.dtype)
        off = 0
        for r in batch:
            padded[off:off + r.n] = r.x
            off += r.n
        try:
            t_run = time.perf_counter()
            out = self._runner(padded)
            # per-bucket dispatch wall (the runner blocks on np.asarray,
            # so this IS device-complete time) — the timing half of the
            # cost index's serving bucket entries (telemetry/perf.py)
            reg = get_registry()
            if reg.enabled:
                reg.histogram(
                    f"serving.{self.name}.bucket{bucket}.dispatch_ms"
                ).observe((time.perf_counter() - t_run) * 1e3)
        except Exception as e:                 # model/device-side failure
            self.metrics.record_rejection("error")
            for r in batch:
                r.error = e
                r.event.set()
            # black box AFTER resolving the callers (a slow dump write
            # must never eat into their deadlines); force=False because
            # the loop keeps dispatching after a failure — a persistently
            # failing runner must not write a dump per batch window
            get_flight_recorder().dump(
                "serving_dispatch_error", force=False, model=self.name,
                bucket=bucket, rows=total, error=str(e),
                error_type=type(e).__name__)
            return
        self.metrics.record_batch(bucket, total)
        for r in batch:
            self.metrics.record_queue_wait((t_disp - r.enqueue_t) * 1000.0)
            if r.trace_id is not None:
                # cross-thread handoff: the dispatch thread has no trace
                # context of its own — each request's id is stamped
                # explicitly on its batch event
                event("serving.batch", trace_id=r.trace_id,
                      model=self.name, bucket=bucket, rows=r.n,
                      queue_ms=round((t_disp - r.enqueue_t) * 1e3, 3))
        off = 0
        for r in batch:
            r.result = out[off:off + r.n]
            off += r.n
            r.event.set()

    # -------------------------------------------------------------- lifecycle
    def stop(self, drain: bool = True, timeout: float = 10.0) -> None:
        """drain=True: refuse new work (503) but flush everything queued;
        drain=False: refuse new work AND fail everything queued now."""
        with self._cond:
            self._draining = True
            if not drain:
                while self._dq:
                    r = self._dq.popleft()
                    r.error = DrainingError(
                        f"model '{self.name}' shut down before dispatch")
                    r.event.set()
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout)
        # belt-and-braces: if the worker died or timed out, nothing may hang
        with self._cond:
            while self._dq:
                r = self._dq.popleft()
                r.error = DrainingError(f"model '{self.name}' stopped")
                r.event.set()
