"""serving/ — production inference engine.

The upgrade path from ``parallel.ParallelInference`` + ``ModelServingServer``
(the reproduction of reference ParallelInference.BATCHED +
DL4jServeRouteBuilder): requests coalesce into padded batches drawn from a
fixed bucket ladder, every bucket's forward program is AOT-compiled ONCE at
warm-up (``jax.jit(...).lower(...).compile()``), so steady-state serving
never traces or recompiles — the cuDNN insight (shape-specialized programs,
arXiv:1410.0759) applied to whole-model XLA programs, plus SparkNet-style
batch coalescing across callers (arXiv:1511.06051).

Pillars:
  - buckets.py   bucket ladder + padding-waste accounting
  - batcher.py   bounded-queue dynamic batcher: deadlines, fast-fail
                 admission, drain-then-stop shutdown
  - programs.py  AOT-warmed per-bucket executables (single-host or
                 mesh-sharded on the 'data' axis)
  - registry.py  named models loaded from model zips / checkpoint dirs
  - engine.py    the facade: multi-model routing + zero-downtime hot-swap
  - metrics.py   p50/p99 latency, queue-wait, occupancy, padding waste,
                 rejection counters; XLA compile counter
  - http.py      /predict /health /metrics /models /reload with real
                 status codes (400/404/429/500/503/504)
  - fleet/       elastic multi-process replica pool: supervised replica
                 processes behind a prefix-cache-affinity router with
                 health-gated admission, SLO-driven autoscaling, and
                 persistent-compilation-cache cold start (import
                 ``deeplearning4j_tpu.serving.fleet`` — kept out of this
                 namespace so single-process serving stays light)
"""
from .buckets import BucketLadder
from .batcher import ShapeBucketedBatcher
from .engine import InferenceEngine
from .errors import (BlockPoolExhaustedError, DeadlineExceededError,
                     DrainingError, GenerationClosedError, QueueFullError,
                     ServingError, ShapeMismatchError, UnknownModelError)
from .metrics import ServingMetrics, xla_compile_count
from .http import ServingHTTPServer
from .programs import ProgramSet
from .registry import ModelRegistry, load_net
from .generation import (GenerationConfig, GenerationEngine,
                         GenerationMetrics, TokenStream)

__all__ = [
    "BucketLadder", "ShapeBucketedBatcher", "InferenceEngine",
    "ServingError", "QueueFullError", "DrainingError",
    "DeadlineExceededError", "UnknownModelError", "ShapeMismatchError",
    "BlockPoolExhaustedError", "GenerationClosedError",
    "ServingMetrics", "xla_compile_count", "ServingHTTPServer",
    "ProgramSet", "ModelRegistry", "load_net",
    "GenerationEngine", "GenerationConfig", "GenerationMetrics",
    "TokenStream",
]
