"""Token sampling, jit-carried PRNG: greedy / temperature / top-k.

Runs INSIDE the compiled prefill/decode programs — per-request temperature
and top-k are runtime arrays, so changing them never recompiles, and the
PRNG key threads through the programs as a carried device array (split
in-program; the host never touches randomness on the decode path).

Greedy (temperature <= 0) is ``argmax`` over the model-dtype logits — the
exact comparison the naive full-recompute reference makes, which is what
lets the bit-exactness pin hold in bf16 as well as f32.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(logits, key, temperature, top_k):
    """logits [N,V] (pre-activation, model dtype); temperature [N] f32
    (<=0 -> greedy); top_k [N] int32 (<=0 -> full vocab). Returns
    (tokens [N] int32, new key)."""
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    key, sub = jax.random.split(key)
    lf = logits.astype(jnp.float32)
    scaled = lf / jnp.maximum(temperature, 1e-6)[:, None]
    kk = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    thr = jnp.take_along_axis(sorted_desc, (kk - 1)[:, None], axis=-1)
    masked = jnp.where(scaled >= thr, scaled, -jnp.inf)
    sampled = jax.random.categorical(sub, masked, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled), key
