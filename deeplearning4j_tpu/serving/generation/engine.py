"""GenerationEngine: the autoregressive-serving facade.

The decode-side sibling of ``serving.InferenceEngine``: multi-model
registry, AOT warm-up, continuous-batching scheduling (ModelRuntime per
model), per-token streaming, zero-downtime hot-swap with the
finish-on-old-params cutover rule, drain-then-stop lifecycle.

    eng = GenerationEngine(net, model_name="lm",
                           block_len=16, max_seq_len=128, decode_slots=8)
    tokens, reason = eng.generate([5, 7, 11], max_tokens=32)
    for tok in eng.generate([5, 7, 11], max_tokens=32, stream=True):
        ...                     # per-token, TTFT = one prefill away

Serve it over HTTP by passing ``generation=eng`` to
``serving.ServingHTTPServer`` (POST /generate streams NDJSON chunks).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple, Union

from ..errors import DrainingError, UnknownModelError
from ..registry import load_net
from .metrics import GenerationMetrics
from .programs import GenerationConfig, GenerationProgramSet
from .scheduler import ModelRuntime, TokenStream


class GenerationEngine:
    def __init__(self, net=None, *, model_name: str = "default",
                 config: Optional[GenerationConfig] = None,
                 adapter: str = "auto", warm: bool = True,
                 watch_recompiles: bool = True, draft=None, mesh=None,
                 **config_kwargs):
        self._models: Dict[str, ModelRuntime] = {}
        self._default: Optional[str] = None
        self._lock = threading.Lock()
        self._draining = False
        self._trace_count = 0
        self._watch = watch_recompiles
        self._mesh = mesh          # default (data, model) mesh for add_model
        if net is not None:
            self.add_model(model_name, net, config=config, adapter=adapter,
                           warm=warm, default=True, draft=draft, mesh=mesh,
                           **config_kwargs)

    # ------------------------------------------------------------------ models
    def add_model(self, name: str, net, *,
                  config: Optional[GenerationConfig] = None,
                  adapter: str = "auto", warm: bool = True,
                  default: bool = False, draft=None, mesh=None,
                  **config_kwargs) -> ModelRuntime:
        """Register a generation model. Per-model opt-ins (ISSUE 14):
        ``draft=`` attaches a speculative-decoding draft model (the
        config's ``spec_k`` proposals per verify window, default 4);
        ``prefix_cache=`` (config/kwarg) disables or forces prompt-prefix
        KV sharing (default: on for paged-transformer models);
        ``mesh=`` (ISSUE 20) a ``(data, model)`` mesh whose model axis
        shards the projections and KV pools by head across chips
        (defaults to the engine-level mesh)."""
        with self._lock:
            if name in self._models:
                raise ValueError(f"generation model '{name}' already "
                                 "registered (use hot_swap to replace)")
        cfg = config or GenerationConfig(**config_kwargs)
        self._pause_detectors()
        try:
            ps = GenerationProgramSet(net, config=cfg, adapter=adapter,
                                      draft_net=draft,
                                      trace_hook=self._on_trace,
                                      cost_path=f"generation.{name}",
                                      mesh=mesh or self._mesh)
            if warm:
                ps.warm()
        finally:
            self._resume_detectors()
        rt = ModelRuntime(name, ps, GenerationMetrics(name=name),
                          watch_recompiles=self._watch)
        with self._lock:
            if name in self._models:      # lost a registration race
                rt.stop(drain=False, timeout=1.0)
                raise ValueError(f"generation model '{name}' already "
                                 "registered")
            self._models[name] = rt
            if default or self._default is None:
                self._default = name
        return rt

    def remove_model(self, name: str) -> None:
        rt = self._get(name)
        with self._lock:
            self._models.pop(name, None)
            if self._default == name:
                self._default = next(iter(self._models), None)
        rt.stop(drain=True)

    def _get(self, name: Optional[str]) -> ModelRuntime:
        with self._lock:
            key = name or self._default
            if key is None or key not in self._models:
                raise UnknownModelError(
                    f"no generation model {key!r} (registered: "
                    f"{sorted(self._models)})")
            return self._models[key]

    def names(self):
        with self._lock:
            return sorted(self._models)

    @property
    def default_name(self) -> Optional[str]:
        return self._default

    # ------------------------------------------------------------- generation
    def generate(self, prompt, *, model: Optional[str] = None,
                 max_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 stop: Sequence[int] = (),
                 timeout: Optional[float] = None, stream: bool = False,
                 speculative: bool = True
                 ) -> Union[TokenStream, Tuple[list, str]]:
        """Generate up to ``max_tokens`` tokens after ``prompt`` (a 1-D int
        token-id sequence). ``stream=True`` returns a TokenStream to
        iterate; otherwise blocks and returns (tokens, finish_reason).
        ``temperature<=0`` is greedy; ``top_k<=0`` disables the top-k cut;
        ``stop`` token ids terminate generation (not emitted);
        ``speculative=False`` opts this request out of draft-verify decode
        on a speculating model (sampling requests opt out automatically —
        the exact-output guarantee is greedy-only)."""
        if self._draining:
            raise DrainingError("generation engine is draining")
        rt = self._get(model)
        ts = rt.submit(prompt,
                       max_new=(max_tokens if max_tokens is not None
                                else rt.config.default_max_tokens),
                       temperature=temperature, top_k=top_k, stop=stop,
                       timeout=timeout, speculative=speculative)
        if stream:
            return ts
        return ts.result()

    # --------------------------------------------------------------- hot-swap
    def hot_swap(self, name: str, net_or_path, draft=None) -> int:
        """Replace model ``name`` with zero downtime. Cutover rule:
        generations in flight at swap time FINISH on the old params AND the
        old draft (their cohort keeps its program set, cache pool, prefix
        cache and draft cache until it drains); every admission after the
        swap runs the new params. Same-architecture swaps reuse the
        compiled executables (the draft carries over unless a new one is
        given); changed architectures warm a full new program set BEFORE
        the cutover. Returns the new version."""
        rt = self._get(name)
        net = load_net(net_or_path) if isinstance(net_or_path, str) \
            else net_or_path
        with rt.swap_lock:
            old = rt.active_ps
            try:
                new_ps = old.with_params_from(net, draft_net=draft)
            except ValueError:
                self._pause_detectors()
                try:
                    new_ps = GenerationProgramSet(
                        net, config=old.config, adapter="auto",
                        draft_net=draft or old.draft_net,
                        trace_hook=self._on_trace,
                        cost_path=old.cost_path, mesh=old.mesh).warm()
                finally:
                    self._resume_detectors()
            rt.active_ps = new_ps         # atomic: next admission cohort
            rt.version += 1
            rt.metrics.record_swap()
            return rt.version

    def reload_from_checkpoint(self, name: str, path: str) -> int:
        return self.hot_swap(name, load_net(path))

    # ---------------------------------------------------------- observability
    def metrics(self) -> Dict[str, dict]:
        with self._lock:
            rts = list(self._models.values())
        return {rt.name: rt.metrics.snapshot() for rt in rts}

    def models(self) -> Dict[str, dict]:
        with self._lock:
            rts = list(self._models.values())
        return {rt.name: {
            "version": rt.version,
            "adapter": rt.active_ps.adapter,
            "warmed": rt.active_ps.warmed,
            "decode_slots": rt.config.decode_slots,
            "block_len": rt.config.block_len,
            "capacity": rt.config.capacity,
            "num_blocks": rt.config.num_blocks,
            "prompt_rungs": list(rt.config.prompt_rungs),
            "prefill_batches": list(rt.config.prefill_batches),
            "in_flight": rt.in_flight,
            "queue_depth": rt.queue_depth,
            "prefix_cache": rt.active_ps.prefix_enabled,
            "kv_cache_dtype": rt.config.kv_cache_dtype,
            "kv_bytes_per_token": rt.active_ps.kv_bytes_per_token(),
            "model_shards": rt.active_ps.model_shards,
            "kv_pool_bytes_per_chip": rt.active_ps.kv_pool_chip_bytes,
            "speculative": {
                "enabled": rt.active_ps.spec_k > 0,
                "k": rt.active_ps.spec_k,
                "draft_adapter": rt.active_ps.draft_adapter,
            },
        } for rt in rts}

    def queue_depths(self) -> Dict[str, int]:
        with self._lock:
            rts = list(self._models.values())
        return {rt.name: rt.queue_depth for rt in rts}

    def steering(self) -> dict:
        """Per-model routing signals + the worst-case aggregate a fleet
        router steers on (``/health``'s ``steering`` key): total queue
        depth, max slot occupancy, min block-pool free fraction, and the
        request-weighted prefix hit rate across models."""
        with self._lock:
            rts = list(self._models.values())
        per = {rt.name: rt.steering() for rt in rts}
        rows = list(per.values())
        hits = sum(r["prefix_hit_rate"] * r["prefix_lookups"] for r in rows)
        lookups = sum(r["prefix_lookups"] for r in rows)
        return {
            "queue_depth": sum(r["queue_depth"] for r in rows),
            "in_flight": sum(r["in_flight"] for r in rows),
            "slot_occupancy": max(
                (r["slot_occupancy"] for r in rows), default=0.0),
            "block_pool_free_frac": min(
                (r["block_pool_free_frac"] for r in rows), default=1.0),
            "prefix_hit_rate": round(hits / lookups, 4) if lookups else 0.0,
            "prefix_lookups": lookups,
            "block_len": per.get(self._default, {}).get(
                "block_len", rows[0]["block_len"] if rows else None),
            "models": per,
        }

    def publish_metrics(self, storage, session_id: str = "generation"):
        with self._lock:
            rts = list(self._models.values())
        for rt in rts:
            rt.metrics.publish(storage, session_id=session_id,
                               worker_id=rt.name)

    @property
    def trace_count(self) -> int:
        return self._trace_count

    def _on_trace(self):
        self._trace_count += 1

    @staticmethod
    def compile_count() -> int:
        from ..metrics import xla_compile_count
        return xla_compile_count()

    @property
    def draining(self) -> bool:
        return self._draining

    def _pause_detectors(self):
        """Warm-up compiles are legitimate — keep them out of the armed
        decode-loop recompile watchdogs."""
        with self._lock:
            rts = list(self._models.values())
        for rt in rts:
            if rt._det is not None:
                rt._det.__exit__(None, None, None)

    def _resume_detectors(self):
        with self._lock:
            rts = list(self._models.values())
        for rt in rts:
            if rt._det is not None:
                rt._det.__enter__()

    # ------------------------------------------------------------- lifecycle
    def stop(self, drain: bool = True, timeout: float = 10.0) -> None:
        with self._lock:
            self._draining = True
            rts = list(self._models.values())
        for rt in rts:
            rt.stop(drain=drain, timeout=timeout)
