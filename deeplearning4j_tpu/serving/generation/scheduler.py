"""Continuous batching: slot-based decode scheduling with step-boundary
admission, per-token streams, and cohort-pinned hot-swap.

One dispatch thread per model owns the decode loop:

    loop:  admit (bucketed prefill for queued requests, into free slots)
           -> one decode step per live cohort (ALL in-flight sequences
              advance one token)
           -> emit tokens to per-request TokenStreams, retire finished
              slots (stop token / max_tokens / deadline / cancel), which
              frees their cache blocks for the next admission

Admission happens at step boundaries only — a new request never stalls
in-flight decode, it just lands in the next step's batch (freed slots are
backfilled from the queue; idle slots ride along masked). All device work
goes through the cohort's AOT-warmed ``GenerationProgramSet``; the host
side is numpy-only, so steady state never traces (a ``RecompileDetector``
stays armed on the loop to prove it).

Hot-swap cutover rule: a request is pinned to the program set (params) it
was admitted under. After ``hot_swap``, new admissions form a NEW cohort on
the new params (its own cache pool); old cohorts keep decoding on the old
params until they drain, then their pool is dropped. During the transition
each step runs one decode program per live cohort.
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ...telemetry import RecompileDetector, span
from ...telemetry.flightrec import get_flight_recorder
from ...telemetry.tracecontext import current_trace_id, event
from ..errors import (BlockPoolExhaustedError, DeadlineExceededError,
                      DrainingError, GenerationClosedError, QueueFullError,
                      ShapeMismatchError)
from .kvcache import BlockAllocator
from .metrics import GenerationMetrics
from .prefix import PrefixCache
from .programs import GenerationProgramSet


class TokenStream:
    """Per-request token stream: the scheduler produces, ONE consumer
    iterates (or calls ``result()`` — not both). Always terminates: every
    admitted request is finished with a reason (or failed) exactly once,
    so iterating callers can never hang."""

    def __init__(self):
        self._q: "_queue.Queue" = _queue.Queue()
        self._done = threading.Event()
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.emitted = 0
        self._cancel_cb = None

    # ---------------------------------------------------- producer (loop)
    def _put(self, tok: int) -> None:
        self.emitted += 1
        self._q.put(("tok", tok))

    def _finish(self, reason: str, error: Optional[BaseException] = None):
        if self._done.is_set():
            return
        self.finish_reason = reason
        self.error = error
        self._done.set()
        self._q.put(("end", reason))

    # ------------------------------------------------------------ consumer
    def __iter__(self):
        while True:
            kind, val = self._q.get()
            if kind == "tok":
                yield val
            else:
                return

    def result(self, raise_on_error: bool = True):
        """Drain the stream; returns (tokens, finish_reason). With
        ``raise_on_error`` a stream that failed (engine error/shutdown)
        raises instead of returning partial output."""
        tokens = list(self)
        if raise_on_error and self.error is not None \
                and self.finish_reason not in ("deadline",):
            raise self.error
        return tokens, self.finish_reason

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> None:
        """Consumer gave up (e.g. HTTP client disconnected): the scheduler
        retires the slot at the next step boundary."""
        if self._cancel_cb is not None:
            self._cancel_cb()


class _GenRequest:
    __slots__ = ("prompt", "max_new", "temperature", "top_k", "stop",
                 "deadline", "stream", "slot", "blocks", "shared_blocks",
                 "replay", "replaying", "matched_tokens", "spec", "emitted",
                 "cancelled", "cancel_reason", "enqueue_t", "cohort",
                 "trace_id")

    def __init__(self, prompt: np.ndarray, max_new: int, temperature: float,
                 top_k: int, stop: frozenset, deadline: float,
                 speculative: bool = True):
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.top_k = top_k
        self.stop = stop
        self.deadline = deadline
        self.stream = TokenStream()
        self.stream._cancel_cb = self._cancel
        self.slot: Optional[int] = None
        self.blocks: List[int] = []          # owned (freed at finish)
        self.shared_blocks: List[int] = []   # cache custody (released)
        self.replay: "deque[int]" = deque()  # prompt suffix still to feed
        self.replaying = False
        self.matched_tokens = 0
        # speculative decoding is exact only for greedy requests; sampling
        # ones ride the plain decode path
        self.spec = bool(speculative) and temperature <= 0.0
        self.cohort = None                  # set at admission
        self.emitted = 0
        self.cancelled = False
        self.cancel_reason = "cancelled"
        self.enqueue_t = time.monotonic()
        # the submitter's trace id rides the request across the queue
        # handoff into the decode loop thread (None = untraced: the
        # per-token trace events are skipped entirely)
        self.trace_id = current_trace_id()

    def _cancel(self):
        self.cancelled = True


class _Cohort:
    """In-flight sequences pinned to one program set (one model version):
    their cache pool, block allocator, block tables, prefix cache and
    draft cache live and die with the cohort — shared prefix K/V and draft
    proposals can never cross a hot-swap boundary."""
    __slots__ = ("ps", "cache", "allocator", "tables", "slots", "version",
                 "prefix", "draft_cache")

    def __init__(self, ps: GenerationProgramSet, version: int):
        self.ps = ps
        self.version = version
        self.cache = ps.make_cache()
        self.allocator = BlockAllocator(ps.config.num_blocks)
        S, mb = ps.config.decode_slots, ps.config.blocks_per_seq
        self.tables = np.zeros((S, mb), np.int32)
        self.slots: Set[int] = set()
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.allocator, ps.config.block_len)
            if ps.prefix_enabled else None)
        self.draft_cache = ps.make_draft_cache()


class ModelRuntime:
    """Scheduler + device state for one generation model."""

    def __init__(self, name: str, ps: GenerationProgramSet,
                 metrics: Optional[GenerationMetrics] = None, *,
                 watch_recompiles: bool = True):
        self.name = name
        self.active_ps = ps
        self.version = 1
        self.swap_lock = threading.Lock()
        self.config = ps.config
        self.metrics = metrics or GenerationMetrics(name=name)
        self.metrics.set_kv_bytes_per_token(ps.kv_bytes_per_token())
        S = self.config.decode_slots
        self._queue: "deque[_GenRequest]" = deque()
        self._cond = threading.Condition()
        self._slots_free: Set[int] = set(range(S))
        self._slot_req: Dict[int, _GenRequest] = {}
        self._tokens = np.zeros(S, np.int32)
        self._pos = np.zeros(S, np.int32)
        self._temp = np.zeros(S, np.float32)
        self._topk = np.zeros(S, np.int32)
        self._active = np.zeros(S, np.bool_)
        self._cohorts: List[_Cohort] = []
        self._key = ps.fresh_key()
        self._draining = False
        self._stopped = False
        self._det = RecompileDetector(allowed=0, warn=False) \
            if watch_recompiles else None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"generation-{name}")
        self._thread.start()

    # -------------------------------------------------------------- admission
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def in_flight(self) -> int:
        return len(self._slot_req)

    @property
    def draining(self) -> bool:
        return self._draining

    def steering(self) -> dict:
        """Cheap routing signals for the fleet router (the ``/health``
        steering payload — the router must not scrape full ``/metrics``
        per admission): prefix hit rate, instantaneous decode-slot
        occupancy, block-pool free fraction and queue depth, plus the
        ``block_len`` the affinity hash needs. Lock-free reads of ints
        under the GIL — a slightly torn snapshot only mis-routes one
        request, it cannot corrupt anything."""
        cfg = self.config
        coh = self._cohorts[-1] if self._cohorts else None
        free = coh.allocator.free_blocks if coh is not None \
            else cfg.num_blocks
        m = self.metrics
        lookups = m.prefix_hits + m.prefix_misses
        in_flight = len(self._slot_req)
        return {
            "queue_depth": len(self._queue),
            "in_flight": in_flight,
            "decode_slots": cfg.decode_slots,
            "slot_occupancy": round(in_flight / cfg.decode_slots, 4),
            "block_len": cfg.block_len,
            "blocks_total": cfg.num_blocks,
            "block_pool_free_frac": (round(free / cfg.num_blocks, 4)
                                     if cfg.num_blocks else 1.0),
            "prefix_hit_rate": (round(m.prefix_hits / lookups, 4)
                                if lookups else 0.0),
            "prefix_lookups": lookups,
        }

    def submit(self, prompt, *, max_new: int, temperature: float = 0.0,
               top_k: int = 0, stop: Sequence[int] = (),
               timeout: Optional[float] = None,
               speculative: bool = True) -> TokenStream:
        cfg = self.config
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = int(prompt.shape[0])
        if plen < 1:
            raise ShapeMismatchError("empty prompt")
        if max_new < 1:
            raise ShapeMismatchError(f"max_tokens must be >= 1, "
                                     f"got {max_new}")
        if plen > cfg.max_prompt_len:
            raise ShapeMismatchError(
                f"prompt length {plen} exceeds the largest warmed prompt "
                f"rung {cfg.max_prompt_len}")
        if plen + max_new > cfg.capacity:
            raise ShapeMismatchError(
                f"prompt ({plen}) + max_tokens ({max_new}) exceeds cache "
                f"capacity {cfg.capacity} tokens")
        if self.active_ps.adapter == "paged":
            need = cfg.blocks_needed(plen, max_new)
            if need > cfg.num_blocks - 1:
                raise BlockPoolExhaustedError(
                    f"request needs {need} cache blocks but the pool only "
                    f"has {cfg.num_blocks - 1} — lower max_tokens or grow "
                    f"num_blocks; retry will not help at this size",
                    retryable=False)
        timeout = cfg.default_timeout_s if timeout is None else timeout
        req = _GenRequest(prompt, int(max_new), float(temperature),
                          int(top_k), frozenset(int(s) for s in stop),
                          time.monotonic() + timeout,
                          speculative=speculative)
        with self._cond:
            if self._draining or self._stopped:
                self.metrics.record_rejection("draining")
                raise DrainingError(
                    f"generation model '{self.name}' is draining/stopped")
            if len(self._queue) >= self.config.queue_limit:
                cohorts = self._cohorts       # loop thread rebinds the list
                coh = cohorts[-1] if cohorts else None
                if self.active_ps.adapter == "paged" and coh is not None \
                        and coh.allocator.free_blocks == 0 \
                        and (coh.prefix is None
                             or coh.prefix.lru_blocks == 0):
                    self.metrics.record_rejection("exhausted")
                    raise BlockPoolExhaustedError(
                        f"model '{self.name}': KV block pool exhausted and "
                        f"admission queue full ({self.config.queue_limit}) "
                        f"— retry after in-flight generations complete")
                self.metrics.record_rejection("full")
                raise QueueFullError(
                    f"model '{self.name}' generation queue full "
                    f"({self.config.queue_limit} requests)")
            self.metrics.record_request()
            self._queue.append(req)
            self._cond.notify_all()
        if req.trace_id is not None:
            event("generation.submit", model=self.name, prompt_len=plen,
                  max_tokens=int(max_new))
        return req.stream

    # ------------------------------------------------------------ loop body
    def _loop(self):
        if self._det is not None:
            self._det.__enter__()
        try:
            while True:
                with self._cond:
                    if self._stopped:
                        break
                    if not self._queue and not self._slot_req:
                        self._cond.wait(0.02)
                        continue
                try:
                    self._admit()
                    self._step()
                except Exception as e:       # defensive: nobody may hang
                    self._fail_all(e)
        finally:
            if self._det is not None:
                self._det.__exit__(None, None, None)
            self._shutdown_flush()

    def _cohort_for_admission(self) -> _Cohort:
        ps = self.active_ps
        if self._cohorts and self._cohorts[-1].ps is ps:
            return self._cohorts[-1]
        coh = _Cohort(ps, self.version)
        self._cohorts.append(coh)
        return coh

    def _worth_replaying(self, matched_blocks: int, plen: int) -> bool:
        """A cache hit replays its unmatched suffix ONE token per decode
        dispatch — dramatically slower than a batched prefill for a long
        suffix. Only take the hit when the suffix fits the configured
        replay budget (``prefix_max_replay``, default 2 blocks); a shorter
        match admits as a plain miss (and still registers its blocks)."""
        if not matched_blocks:
            return False
        suffix = plen - matched_blocks * self.config.block_len
        if suffix == 0:
            suffix = 1                    # block-aligned: COW + one feed
        return suffix <= self.config.prefix_max_replay

    def _setup_blocks(self, coh: _Cohort, r: _GenRequest) -> None:
        """Blocks for one admission (paged adapter, under the cond lock):
        take references on the longest cached prefix, evict refcount-0
        LRU blocks if the fresh remainder needs room, allocate the rest.
        ``r.matched_tokens == len(prompt)`` flags the block-aligned full
        match whose COW copy the caller performs after the lock."""
        cfg = self.config
        total = cfg.blocks_needed(len(r.prompt), r.max_new)
        plen = len(r.prompt)
        if coh.prefix is None or not self._worth_replaying(
                coh.prefix.probe(r.prompt), plen):
            # miss (or a match too short to beat prefill): plain path —
            # still evict refcount-0 LRU blocks under pool pressure;
            # registration after prefill extends the cached chain
            if coh.prefix is not None:
                evicted = coh.prefix.ensure_free(total)
                if evicted:
                    self.metrics.record_prefix_evictions(evicted)
            r.blocks = coh.allocator.alloc(total) if total else []
            return
        shared, matched = coh.prefix.match(r.prompt)
        # the final prompt token must still be FED through decode for its
        # next-token logits; when the match covers the whole prompt that
        # feed writes inside the last shared block -> COW copy needed
        fresh = total - len(shared) + (1 if matched == plen and shared
                                       else 0)
        evicted = coh.prefix.ensure_free(fresh)
        if evicted:
            self.metrics.record_prefix_evictions(evicted)
        r.blocks = coh.allocator.alloc(fresh) if fresh else []
        r.shared_blocks = shared
        r.matched_tokens = matched

    def _admit(self):
        cfg = self.config
        cands: List[_GenRequest] = []
        now = time.monotonic()
        with self._cond:
            # expire/cancel while queued
            q = self._queue
            keep: "deque[_GenRequest]" = deque()
            while q:
                r = q.popleft()
                if r.cancelled:
                    r.stream._finish(r.cancel_reason)
                    self.metrics.record_finish(r.cancel_reason)
                elif now > r.deadline:
                    self.metrics.record_rejection("deadline")
                    r.stream._finish("deadline", DeadlineExceededError(
                        "deadline expired while queued for admission"))
                else:
                    keep.append(r)
            self._queue = keep
            if not self._queue or not self._slots_free:
                return
            coh = self._cohort_for_admission()
            max_p = cfg.prefill_batches[-1]
            blk = cfg.block_len
            while self._queue and self._slots_free and len(cands) < max_p:
                r = self._queue[0]
                if coh.ps.adapter != "state":
                    total = cfg.blocks_needed(len(r.prompt), r.max_new)
                    budget = coh.allocator.free_blocks
                    fresh = total
                    if coh.prefix is not None:
                        m = coh.prefix.probe(r.prompt)
                        if not self._worth_replaying(m, len(r.prompt)):
                            m = 0                # short match -> plain miss
                        fresh = total - m + \
                            (1 if m and m * blk == len(r.prompt) else 0)
                        budget += coh.prefix.evictable_for(r.prompt)
                    if fresh > budget:
                        break        # head-of-line: wait for blocks to free
                self._queue.popleft()
                # register the request for failure delivery BEFORE block
                # setup: if _setup_blocks raises (an accounting bug —
                # the head-of-line budget above should prevent it), the
                # loop's _fail_all resolves this caller instead of
                # leaving a popped-but-unregistered stream hanging
                r.slot = self._slots_free.pop()
                r.cohort = coh
                self._slot_req[r.slot] = r
                if coh.ps.adapter != "state":
                    self._setup_blocks(coh, r)
                cands.append(r)
        if not cands:
            return
        for r in cands:
            if r.trace_id is not None:
                # admission: queue -> slot handoff, stamped per request
                # (the loop thread has no context of its own)
                event("generation.admit", trace_id=r.trace_id,
                      model=self.name, slot=r.slot,
                      queue_ms=round((time.monotonic() - r.enqueue_t) * 1e3,
                                     3))
        hits = [r for r in cands if r.matched_tokens]
        misses = [r for r in cands if not r.matched_tokens]
        if misses:
            self._prefill_misses(coh, misses)
        if hits:
            self._admit_hits(coh, hits)
        if coh.ps.spec_k:
            # speculating requests only: sampling/opted-out rows would
            # waste draft compute and could force a larger (P, L) rung
            spec_cands = [r for r in cands if r.spec]
            if spec_cands:
                self._draft_prefill(coh, spec_cands)
        if coh.prefix is not None:
            self.metrics.set_prefix_gauges(coh.prefix.stats())

    def _prefill_misses(self, coh: _Cohort, cands: List["_GenRequest"]):
        cfg = self.config
        S, mb = cfg.decode_slots, cfg.blocks_per_seq
        P = cfg.prefill_rung(len(cands))
        L = cfg.prompt_rung(max(len(r.prompt) for r in cands))
        tokens = np.zeros((P, L), np.int32)
        lengths = np.ones(P, np.int32)
        tables_p = np.zeros((P, mb), np.int32)
        slots = np.full(P, S, np.int32)          # padding rows -> trash slot
        temp = np.zeros(P, np.float32)
        topk = np.zeros(P, np.int32)
        for i, r in enumerate(cands):
            plen = len(r.prompt)
            tokens[i, :plen] = r.prompt
            lengths[i] = plen
            tables_p[i, :len(r.blocks)] = r.blocks
            slots[i] = r.slot
            temp[i] = r.temperature
            topk[i] = r.top_k
        with span("generation.prefill", model=self.name, batch=len(cands),
                  rung=L):
            first, coh.cache, self._key = coh.ps.run_prefill(
                coh.cache, tokens, lengths, tables_p, slots, self._key,
                temp, topk)
        now = time.monotonic()
        emitted = 0
        for i, r in enumerate(cands):
            s = r.slot
            coh.slots.add(s)
            coh.tables[s] = tables_p[i]
            self._pos[s] = len(r.prompt)
            self._temp[s] = r.temperature
            self._topk[s] = r.top_k
            if coh.prefix is not None:
                self.metrics.record_prefix_miss()
                # the prompt's full blocks are immutable from here on:
                # index them so the next identical prefix skips this
                # prefill; custody of the registered blocks moves to the
                # cache (released at finish, not freed)
                managed = coh.prefix.register(r.prompt, tables_p[i],
                                              r.blocks)
                if managed:
                    drop = set(managed)
                    r.blocks = [b for b in r.blocks if b not in drop]
                    r.shared_blocks.extend(managed)
            if r.trace_id is not None:
                event("generation.prefill", trace_id=r.trace_id,
                      model=self.name, slot=s, rung=int(L),
                      batch=len(cands),
                      ttft_ms=round((now - r.enqueue_t) * 1e3, 3))
            did_emit, _ = self._slot_emit(coh, r, int(first[i]), now)
            emitted += did_emit
        self.metrics.record_prefill(
            len(cands), [(now - r.enqueue_t) * 1e3 for r in cands],
            emitted)

    def _admit_hits(self, coh: _Cohort, hits: List["_GenRequest"]):
        """Cache-hit admission: NO target prefill. The sequence's table
        points at the shared read-only blocks; the unmatched prompt suffix
        replays through the warmed decode program (one token per step,
        teacher-forced), and the first emitted token falls out of the step
        that feeds the final prompt token. Block-aligned full matches COW
        the last shared block first — its final position gets rewritten by
        that feed, and shared blocks are never written."""
        blk = self.config.block_len
        for r in hits:
            s = r.slot
            plen = len(r.prompt)
            cow = 0
            if r.matched_tokens == plen:
                # copy-on-write: table entry m-1 becomes a private copy
                src = r.shared_blocks[-1]
                dst = r.blocks[0]
                coh.cache = coh.ps.run_cow(coh.cache, src, dst)
                coh.prefix.release([src])
                r.shared_blocks = r.shared_blocks[:-1]
                coh.prefix.cow_copies += 1
                self.metrics.record_cow()
                table = r.shared_blocks + [dst] + r.blocks[1:]
                start = plen - 1
                cow = 1
            else:
                table = r.shared_blocks + r.blocks
                start = r.matched_tokens
            row = np.zeros(self.config.blocks_per_seq, np.int32)
            row[:len(table)] = table
            coh.slots.add(s)
            coh.tables[s] = row
            self._pos[s] = start
            self._temp[s] = r.temperature
            self._topk[s] = r.top_k
            self._tokens[s] = int(r.prompt[start])
            self._active[s] = True
            r.replay = deque(int(t) for t in r.prompt[start + 1:])
            r.replaying = True
            self.metrics.record_prefix_hit(start)
            if r.trace_id is not None:
                event("generation.prefix_hit", trace_id=r.trace_id,
                      model=self.name, slot=s,
                      matched_tokens=int(r.matched_tokens),
                      shared_blocks=len(r.shared_blocks) + cow,
                      cow=cow, replay_tokens=plen - start)

    def _draft_prefill(self, coh: _Cohort, cands: List["_GenRequest"]):
        """The draft consumes every admitted FULL prompt (hits included —
        the target skipped its matched span, the draft is cheap and has no
        paged cache to share)."""
        cfg = self.config
        S = cfg.decode_slots
        P = cfg.prefill_rung(len(cands))
        L = cfg.prompt_rung(max(len(r.prompt) for r in cands))
        tokens = np.zeros((P, L), np.int32)
        lengths = np.ones(P, np.int32)
        slots = np.full(P, S, np.int32)
        for i, r in enumerate(cands):
            plen = len(r.prompt)
            tokens[i, :plen] = r.prompt
            lengths[i] = plen
            slots[i] = r.slot
        coh.draft_cache = coh.ps.run_draft_prefill(coh.draft_cache, tokens,
                                                   lengths, slots)

    def _step(self):
        cfg = self.config
        S = cfg.decode_slots
        for coh in list(self._cohorts):
            live = [s for s in sorted(coh.slots) if self._active[s]]
            if not live:
                continue
            # speculative slots (greedy, past replay) advance through
            # draft-propose + one batched verify; everything else —
            # spec disabled, sampling requests, prompt-suffix replay —
            # rides the plain one-token decode program
            spec_on = coh.ps.spec_k > 0
            plain = [s for s in live
                     if not spec_on or not self._slot_req[s].spec
                     or self._slot_req[s].replaying]
            specs = [s for s in live if s not in set(plain)]
            if plain:
                self._plain_step(coh, plain)
            if specs:
                self._spec_step(coh, specs)
        if self._det is not None:
            self.metrics.record_recompile(self._det.count)
        # drop drained cohorts (old params/pools released)
        self._cohorts = [c for c in self._cohorts
                         if c.slots or c.ps is self.active_ps]
        if not self._slot_req:
            self._check_quiesce()

    def _plain_step(self, coh: _Cohort, live: List[int]):
        cfg = self.config
        S = cfg.decode_slots
        mask = np.zeros(S, np.bool_)
        mask[live] = True
        t0 = time.perf_counter()
        with span("generation.decode_step", model=self.name,
                  slots=len(live)):
            nxt, coh.cache, self._key = coh.ps.run_decode(
                coh.cache, self._tokens, self._pos, coh.tables, mask,
                self._key, self._temp, self._topk)
        dt_ms = (time.perf_counter() - t0) * 1e3
        now = time.monotonic()
        emitted = 0
        for s in live:
            r = self._slot_req[s]
            if r.trace_id is not None:
                # one event per decode step the request participated
                # in — the per-request timeline's heartbeat
                event("generation.decode_step", trace_id=r.trace_id,
                      model=self.name, slot=s, token_index=r.emitted,
                      step_ms=round(dt_ms, 3))
            if r.replaying:
                emitted += self._replay_advance(coh, r, int(nxt[s]), now)
                continue
            did_emit, cont = self._slot_emit(coh, r, int(nxt[s]), now)
            emitted += did_emit
            if cont:
                self._pos[s] += 1
        self.metrics.record_decode_step(
            dt_ms, len(live), emitted, slots=S,
            blocks_used=coh.allocator.used_blocks,
            blocks_total=coh.allocator.total_usable,
            queue_depth=len(self._queue))

    def _replay_advance(self, coh: _Cohort, r: "_GenRequest", sampled: int,
                        now: float) -> int:
        """One replay step for a cache-hit admission: the decode program
        just fed prompt[pos]. While suffix tokens remain the sample is a
        mid-prompt prediction — discarded, teacher-force the next prompt
        token. The step that fed the FINAL prompt token produced the first
        generated token: record the cached TTFT and emit. Returns tokens
        emitted (0 or 1)."""
        s = r.slot
        if r.cancelled or now > r.deadline:
            if r.cancelled:
                err = GenerationClosedError("engine stopped mid-generation") \
                    if r.cancel_reason == "shutdown" else None
                self._finish_slot(coh, r, r.cancel_reason, err)
            else:
                self._finish_slot(coh, r, "deadline", DeadlineExceededError(
                    "deadline expired while replaying the prompt suffix"))
            return 0
        if r.replay:
            self._tokens[s] = r.replay.popleft()
            self._pos[s] += 1
            return 0
        r.replaying = False
        self.metrics.record_cached_first_token(
            (now - r.enqueue_t) * 1e3)
        if coh.prefix is not None:
            # full prompt blocks beyond the matched span are now valid:
            # index them so the NEXT request extends the cached chain
            managed = coh.prefix.register(r.prompt, coh.tables[s], r.blocks)
            if managed:
                drop = set(managed)
                r.blocks = [b for b in r.blocks if b not in drop]
                r.shared_blocks.extend(managed)
            self.metrics.set_prefix_gauges(coh.prefix.stats())
        did_emit, cont = self._slot_emit(coh, r, sampled, now)
        if cont:
            self._pos[s] += 1
        return did_emit

    def _spec_step(self, coh: _Cohort, specs: List[int]):
        """Draft proposes k tokens per slot; ONE batched target pass
        verifies; the longest agreeing prefix + the target's correction
        token are emitted — plain-greedy-identical output, up to k+1
        tokens per target dispatch."""
        from .speculative import accept_greedy
        cfg = self.config
        S, k = cfg.decode_slots, coh.ps.spec_k
        mask = np.zeros(S, np.bool_)
        mask[specs] = True
        t0 = time.perf_counter()
        with span("generation.verify", model=self.name, slots=len(specs),
                  k=k):
            props, aux = coh.ps.run_propose(
                coh.draft_cache, self._tokens, self._pos, mask)
            if coh.ps.draft_adapter == "dense":
                coh.draft_cache = aux
            feeds = np.concatenate(
                [self._tokens[:, None], props], axis=1).astype(np.int32)
            targets, coh.cache = coh.ps.run_verify(
                coh.cache, feeds, self._pos, coh.tables, mask)
        dt_ms = (time.perf_counter() - t0) * 1e3
        counts, emitted_toks = accept_greedy(props, targets)
        now = time.monotonic()
        emitted = 0
        accepted = 0
        cont_mask = np.zeros(S, np.bool_)
        rewind_idx = np.ones(S, np.int32)
        for s in specs:
            r = self._slot_req[s]
            if r.trace_id is not None:
                event("generation.verify", trace_id=r.trace_id,
                      model=self.name, slot=s, token_index=r.emitted,
                      proposed=k, accepted=int(counts[s]),
                      step_ms=round(dt_ms, 3))
            accepted += int(counts[s])
            n_emit, cont = 0, False
            for tok in emitted_toks[s]:
                did, cont = self._slot_emit(coh, r, int(tok), now)
                n_emit += did
                if not cont:
                    break
            emitted += n_emit
            if cont:
                self._pos[s] += n_emit
                cont_mask[s] = True
                rewind_idx[s] = n_emit
        if coh.ps.draft_adapter == "state":
            # commit, per continuing slot, the draft state matching what
            # verify accepted (s_{j+1} = after the j-th accepted proposal)
            coh.draft_cache = coh.ps.run_rewind(
                coh.draft_cache, aux, rewind_idx, cont_mask)
        self.metrics.record_verify(
            dt_ms, len(specs), proposed=k * len(specs), accepted=accepted,
            emitted=emitted, slots=S,
            blocks_used=coh.allocator.used_blocks,
            blocks_total=coh.allocator.total_usable,
            queue_depth=len(self._queue))

    def _check_quiesce(self):
        """Block-accounting invariant at quiesce (no in-flight requests):
        every allocated block is exactly a cached block (refcounted owner
        refs are gone, so cached == prefix index incl. its LRU). A
        violation is a leak or a double-custody bug — fail loudly (the
        loop's defensive except turns this into _fail_all + a flight
        dump) rather than serving corrupt shared state."""
        for coh in self._cohorts:
            if coh.ps.adapter != "paged" or coh.slots:
                continue
            alloc = set(coh.allocator.allocated)
            cached = (coh.prefix.cached_block_ids()
                      if coh.prefix is not None else set())
            if alloc != cached:
                raise RuntimeError(
                    f"block accounting violated at quiesce for model "
                    f"'{self.name}': leaked={sorted(alloc - cached)} "
                    f"phantom={sorted(cached - alloc)}")

    def _slot_emit(self, coh: _Cohort, r: _GenRequest, tok: int,
                   now: float):
        """Handle one sampled token for a slot: emit/terminate. Returns
        (emitted, continuing)."""
        if r.cancelled:
            # a shutdown-cancel must surface as an ERROR to blocking
            # callers (engine stopped under them); a consumer cancel is a
            # normal close
            err = GenerationClosedError("engine stopped mid-generation") \
                if r.cancel_reason == "shutdown" else None
            return self._finish_slot(coh, r, r.cancel_reason, err)
        if now > r.deadline:
            return self._finish_slot(
                coh, r, "deadline",
                DeadlineExceededError("deadline expired mid-generation "
                                      f"after {r.emitted} tokens"))
        if tok in r.stop:
            return self._finish_slot(coh, r, "stop")
        r.stream._put(tok)
        r.emitted += 1
        if r.emitted >= r.max_new:
            out = self._finish_slot(coh, r, "length")
            return (1, out[1])
        self._tokens[r.slot] = tok
        self._active[r.slot] = True
        return (1, True)

    def _finish_slot(self, coh: _Cohort, r: _GenRequest, reason: str,
                     error: Optional[BaseException] = None):
        s = r.slot
        r.stream._finish(reason, error)
        if r.trace_id is not None:
            event("generation.finish", trace_id=r.trace_id,
                  model=self.name, slot=s, reason=reason,
                  tokens=r.emitted)
        self.metrics.record_finish(reason)
        if r.blocks:
            coh.allocator.free(r.blocks)
            r.blocks = []
        if r.shared_blocks:
            # cache-custody blocks: drop this sequence's reference;
            # refcount-0 blocks park in the LRU for the next identical
            # prefix (eviction under pool pressure frees them)
            coh.prefix.release(r.shared_blocks)
            r.shared_blocks = []
        if coh.prefix is not None:
            self.metrics.set_prefix_gauges(coh.prefix.stats())
        coh.slots.discard(s)
        self._active[s] = False
        with self._cond:
            del self._slot_req[s]
            self._slots_free.add(s)
            self._cond.notify_all()
        return (0, False)

    def _fail_all(self, exc: BaseException):
        """A dispatch-side failure must resolve every caller (the batcher
        contract): fail queued + in-flight, release blocks/slots.
        Iterates ``_slot_req`` (not cohort slot sets) so requests whose
        PREFILL raised — admitted but never added to a cohort's slots —
        are failed too instead of hanging their callers. Every cohort is
        dropped: after a program failure its cache may reference donated
        (invalidated) buffers, so the next admission must build a fresh
        pool."""
        self.metrics.record_rejection("error")
        with self._cond:
            queued = list(self._queue)
            self._queue.clear()
            reqs = list(self._slot_req.values())
        in_flight = len(reqs)
        for r in queued:
            r.stream._finish("error", exc)
        for r in reqs:
            self._finish_slot(r.cohort, r, "error", exc)
        self._cohorts = []
        # black box AFTER resolving every caller (a slow dump write must
        # never delay their failure); the ring still holds the
        # spans/events — and trace ids — leading up to the failure
        get_flight_recorder().dump(
            "generation_error", model=self.name, error=str(exc),
            error_type=type(exc).__name__, in_flight=in_flight,
            queued=len(queued))

    def _shutdown_flush(self):
        err = DrainingError(f"generation model '{self.name}' stopped")
        with self._cond:
            queued = list(self._queue)
            self._queue.clear()
            reqs = list(self._slot_req.values())
        for r in queued:
            r.stream._finish("shutdown", err)
            self.metrics.record_finish("shutdown")
        for r in reqs:
            self._finish_slot(r.cohort, r, "shutdown",
                              GenerationClosedError(
                                  "engine stopped mid-generation"))
        self._cohorts = []

    # ------------------------------------------------------------- lifecycle
    def stop(self, drain: bool = True, timeout: float = 10.0):
        """drain=True: refuse new work (503) but let queued + in-flight
        generations COMPLETE (bounded by ``timeout``); drain=False: refuse
        new work and terminate everything now. Either way every stream is
        finished — no caller is left hanging."""
        with self._cond:
            self._draining = True
            if not drain:
                for r in list(self._queue):
                    r.stream._finish("shutdown", DrainingError(
                        f"model '{self.name}' shut down before admission"))
                self._queue.clear()
                for r in self._slot_req.values():
                    r.cancelled = True
                    r.cancel_reason = "shutdown"
            self._cond.notify_all()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and \
                (self._queue or self._slot_req):
            time.sleep(0.005)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        self._shutdown_flush()    # belt-and-braces if the thread wedged
