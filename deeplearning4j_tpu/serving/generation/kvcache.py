"""Paged KV cache: fixed-size block pool + per-sequence block tables.

The central shape discipline of the decode subsystem: the cache is ONE pair
of pool arrays per model —

    k_pool / v_pool : [n_layers, num_blocks, block_len, n_heads, head_dim]

— and a sequence's cache is the set of pool blocks its (host-side) block
table points at. "Growing" a sequence's context is block *allocation*, a
bookkeeping edit to an int32 table; no device array ever changes shape, so
nothing ever recompiles (the vLLM PagedAttention idea fused with the
repo's AOT-warmed-program discipline).

Block 0 is the reserved TRASH block: inactive decode slots and the unused
tail of a prefill's table all point at it, so the fixed-shape scatter always
has a legal destination and garbage lands where nothing ever reads it
(attention masks it out regardless).

Host side: ``BlockAllocator`` — a free-list over block ids 1..num_blocks-1.
Device side: pure gather/scatter helpers used inside the jitted prefill and
decode programs; ``PagedStore`` adapts them to the ``models.decode.KVStore``
protocol.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp

from ..errors import BlockPoolExhaustedError


class BlockAllocator:
    """Free-list allocator over the pool's usable blocks (ids 1..n-1; block
    0 is the trash block). Not thread-safe by itself — the scheduler owns
    it from its single dispatch thread.

    Hardened bookkeeping (ISSUE 14): an explicit allocated set plus
    per-block refcounts (the prefix cache's sharing currency). Freeing a
    block that was never allocated, double-freeing, or freeing a block
    whose refcount is still nonzero all raise — a leak or double-free
    corrupts EVERY sequence sharing the pool, so it must die loudly at the
    first bad call, not surface later as silently-wrong tokens."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved trash)")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._allocated: set = set()
        self._refcount: dict = {}

    @property
    def total_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.total_usable - len(self._free)

    @property
    def allocated(self) -> frozenset:
        return frozenset(self._allocated)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise BlockPoolExhaustedError(
                f"block pool exhausted: need {n} blocks, "
                f"{len(self._free)}/{self.total_usable} free — retry after "
                f"in-flight generations release their blocks")
        got = [self._free.pop() for _ in range(n)]
        self._allocated.update(got)
        return got

    def free(self, ids: Sequence[int]) -> None:
        for b in ids:
            if not 1 <= b < self.num_blocks:
                raise ValueError(f"free of invalid block id {b}")
            if b not in self._allocated:
                raise ValueError(
                    f"free of unallocated block {b} (double free, or an id "
                    f"this allocator never handed out)")
            if self._refcount.get(b, 0):
                raise ValueError(
                    f"free of block {b} with refcount "
                    f"{self._refcount[b]} — shared blocks must be "
                    f"released through the prefix cache, not freed")
            self._allocated.discard(b)
            self._free.append(int(b))

    # ------------------------------------------------------------ refcounts
    def incref(self, b: int) -> int:
        if b not in self._allocated:
            raise ValueError(f"incref of unallocated block {b}")
        self._refcount[b] = self._refcount.get(b, 0) + 1
        return self._refcount[b]

    def decref(self, b: int) -> int:
        n = self._refcount.get(b, 0)
        if n < 1:
            raise ValueError(f"decref of block {b} below zero")
        n -= 1
        if n:
            self._refcount[b] = n
        else:
            del self._refcount[b]
        return n

    def refcount(self, b: int) -> int:
        return self._refcount.get(b, 0)


def make_pools(n_layers: int, num_blocks: int, block_len: int,
               n_heads: int, head_dim: int, dtype) -> Tuple:
    """Zero-filled (k_pool, v_pool)."""
    shape = (n_layers, num_blocks, block_len, n_heads, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def cow_copy(k_pool, v_pool, src, dst):
    """Copy one block's content (every layer, K and V) from ``src`` to
    ``dst`` — the copy-on-write primitive for prefix sharing. ``src``/
    ``dst`` are runtime int32 scalars, so ONE compiled program serves every
    copy; functional update keeps the read-before-write ordering a data
    dependency."""
    k_pool = k_pool.at[:, dst].set(k_pool[:, src])
    v_pool = v_pool.at[:, dst].set(v_pool[:, src])
    return k_pool, v_pool


def prefill_scatter(pool, layer_kv, tables):
    """Write a prefill's K or V for one layer into the pool.

    pool      [n_layers, nb, blk, H, Dh] (functional update)
    layer_kv  list of [P, L, H, Dh] per layer (L % blk == 0)
    tables    [P, max_blocks] int32 — first L//blk entries are the
              sequence's blocks (rest point at trash block 0).
    """
    P, L, H, Dh = layer_kv[0].shape
    blk = pool.shape[2]
    nblk = L // blk
    for i, kv in enumerate(layer_kv):
        pool = pool.at[i, tables[:, :nblk]].set(
            kv.reshape(P, nblk, blk, H, Dh))
    return pool


class PagedStore:
    """``models.decode.KVStore`` over the paged pools for ONE decode step.

    Scatter-then-gather: the current token's K/V lands in its block slot
    first, then the gathered context (position-ordered, so attention row
    ``pos`` is bit-identical to the naive causal row) includes it.
    Inactive rows scatter to the trash block."""

    def __init__(self, k_pool, v_pool, tables, pos, active, block_len: int):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.tables = tables              # [S, max_blocks] int32
        self.pos = pos                    # [S] int32
        self.active = active              # [S] bool
        self.block_len = int(block_len)
        S, mb = tables.shape
        self._ctx_len = mb * self.block_len
        bid = jnp.take_along_axis(tables, (pos // self.block_len)[:, None],
                                  axis=1)[:, 0]
        self._bid = jnp.where(active, bid, 0)      # trash for idle slots
        self._off = jnp.where(active, pos % self.block_len, 0)
        self._mask = (jnp.arange(self._ctx_len)[None, :] <= pos[:, None])

    def put_get(self, i: int, k_tok, v_tok):
        S = k_tok.shape[0]
        self.k_pool = self.k_pool.at[i, self._bid, self._off].set(k_tok)
        self.v_pool = self.v_pool.at[i, self._bid, self._off].set(v_tok)
        H, Dh = k_tok.shape[-2:]

        def gathered(pool):
            ctx = pool[i][self.tables]          # [S, mb, blk, H, Dh]
            return ctx.reshape(S, self._ctx_len, H, Dh).transpose(0, 2, 1, 3)

        return gathered(self.k_pool), gathered(self.v_pool), self._mask

    @property
    def pools(self):
        return self.k_pool, self.v_pool


class PagedWindowStore:
    """``models.decode`` window store over the paged pools for ONE
    speculative-verify pass: W = k+1 fed tokens per slot land at positions
    ``pos .. pos+W-1`` (crossing block boundaries via per-position
    (block, offset) indices), then the gathered context plus per-row key
    masks reproduce, row by row, exactly the visibility the one-token
    ``PagedStore`` gives position ``pos+i`` — which is what makes the
    batched verify bit-identical to W sequential decode steps."""

    def __init__(self, k_pool, v_pool, tables, pos, active, block_len: int,
                 window: int):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.tables = tables              # [S, max_blocks] int32
        self.block_len = int(block_len)
        S, mb = tables.shape
        self._ctx_len = mb * self.block_len
        w_pos = pos[:, None] + jnp.arange(window)[None, :]       # [S, W]
        bidx = jnp.clip(w_pos // self.block_len, 0, mb - 1)
        bid = jnp.take_along_axis(tables, bidx, axis=1)          # [S, W]
        # idle slots AND window positions past capacity (a verify window is
        # always W wide even when < W tokens of budget remain) go to trash —
        # a clipped in-range write would corrupt the last real block
        ok = active[:, None] & (w_pos < mb * self.block_len)
        self._bid = jnp.where(ok, bid, 0)
        self._off = jnp.where(ok, w_pos % self.block_len, 0)
        # row i of a slot's mask: keys at positions <= pos+i are visible
        self._mask = (jnp.arange(self._ctx_len)[None, None, :]
                      <= w_pos[:, :, None])                      # [S, W, ctx]

    def put_get(self, i: int, k_win, v_win):
        """k_win/v_win: [S, W, H, Dh] for the window. Returns
        (K [S,H,ctx,Dh], V [S,H,ctx,Dh], row_mask [S,W,ctx])."""
        S = k_win.shape[0]
        self.k_pool = self.k_pool.at[i, self._bid, self._off].set(k_win)
        self.v_pool = self.v_pool.at[i, self._bid, self._off].set(v_win)
        H, Dh = k_win.shape[-2:]

        def gathered(pool):
            ctx = pool[i][self.tables]          # [S, mb, blk, H, Dh]
            return ctx.reshape(S, self._ctx_len, H, Dh).transpose(0, 2, 1, 3)

        return gathered(self.k_pool), gathered(self.v_pool), self._mask

    @property
    def pools(self):
        return self.k_pool, self.v_pool
