"""Paged KV cache: fixed-size block pool + per-sequence block tables.

The central shape discipline of the decode subsystem: the cache is ONE pair
of pool arrays per model —

    k_pool / v_pool : [n_layers, num_blocks, block_len, n_heads, head_dim]

— and a sequence's cache is the set of pool blocks its (host-side) block
table points at. "Growing" a sequence's context is block *allocation*, a
bookkeeping edit to an int32 table; no device array ever changes shape, so
nothing ever recompiles (the vLLM PagedAttention idea fused with the
repo's AOT-warmed-program discipline).

Block 0 is the reserved TRASH block: inactive decode slots and the unused
tail of a prefill's table all point at it, so the fixed-shape scatter always
has a legal destination and garbage lands where nothing ever reads it
(attention masks it out regardless).

Host side: ``BlockAllocator`` — a free-list over block ids 1..num_blocks-1.
Device side: pure gather/scatter helpers used inside the jitted prefill and
decode programs; ``PagedStore`` adapts them to the ``models.decode.KVStore``
protocol.
"""
from __future__ import annotations

from typing import List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..errors import BlockPoolExhaustedError


class BlockAllocator:
    """Free-list allocator over the pool's usable blocks (ids 1..n-1; block
    0 is the trash block). Not thread-safe by itself — the scheduler owns
    it from its single dispatch thread.

    Hardened bookkeeping (ISSUE 14): an explicit allocated set plus
    per-block refcounts (the prefix cache's sharing currency). Freeing a
    block that was never allocated, double-freeing, or freeing a block
    whose refcount is still nonzero all raise — a leak or double-free
    corrupts EVERY sequence sharing the pool, so it must die loudly at the
    first bad call, not surface later as silently-wrong tokens."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved trash)")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._allocated: set = set()
        self._refcount: dict = {}

    @property
    def total_usable(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.total_usable - len(self._free)

    @property
    def allocated(self) -> frozenset:
        return frozenset(self._allocated)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise BlockPoolExhaustedError(
                f"block pool exhausted: need {n} blocks, "
                f"{len(self._free)}/{self.total_usable} free — retry after "
                f"in-flight generations release their blocks")
        got = [self._free.pop() for _ in range(n)]
        self._allocated.update(got)
        return got

    def free(self, ids: Sequence[int]) -> None:
        for b in ids:
            if not 1 <= b < self.num_blocks:
                raise ValueError(f"free of invalid block id {b}")
            if b not in self._allocated:
                raise ValueError(
                    f"free of unallocated block {b} (double free, or an id "
                    f"this allocator never handed out)")
            if self._refcount.get(b, 0):
                raise ValueError(
                    f"free of block {b} with refcount "
                    f"{self._refcount[b]} — shared blocks must be "
                    f"released through the prefix cache, not freed")
            self._allocated.discard(b)
            self._free.append(int(b))

    # ------------------------------------------------------------ refcounts
    def incref(self, b: int) -> int:
        if b not in self._allocated:
            raise ValueError(f"incref of unallocated block {b}")
        self._refcount[b] = self._refcount.get(b, 0) + 1
        return self._refcount[b]

    def decref(self, b: int) -> int:
        n = self._refcount.get(b, 0)
        if n < 1:
            raise ValueError(f"decref of block {b} below zero")
        n -= 1
        if n:
            self._refcount[b] = n
        else:
            del self._refcount[b]
        return n

    def refcount(self, b: int) -> int:
        return self._refcount.get(b, 0)


class QuantizedPool(NamedTuple):
    """int8-quantized block pool (ISSUE 17): the same
    [n_layers, num_blocks, block_len, n_heads, *] geometry, with each
    (token, head) vector stored as int8 codes plus ONE f32 scale —
    2*(Dh+4) bytes per token/layer/head instead of f32's 8*Dh, so the
    same ``num_blocks`` holds ~2-3.5x the tokens per byte (and every
    prefix-cache hit shares the smaller blocks). A NamedTuple is a pytree,
    so the cache stays the 2-tuple ``(k_entry, v_entry)`` the warmed
    programs, donation, and ``_cache_spec`` already handle."""
    q: jnp.ndarray        # int8 [n_layers, nb, blk, H, Dh]
    scale: jnp.ndarray    # f32  [n_layers, nb, blk, H]


def kv_quantize(x) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[..., Dh] → (int8 codes [..., Dh], f32 scales [...]) — symmetric
    per-(token, head) scales. DETERMINISTIC: prefill, decode, replay and
    verify all quantize through this exact expression, which is what makes
    quantized greedy decode self-consistent token-for-token across the
    hit/miss/speculative paths."""
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def kv_dequantize(q, scale, dtype):
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def make_pools(n_layers: int, num_blocks: int, block_len: int,
               n_heads: int, head_dim: int, dtype,
               quantized: bool = False) -> Tuple:
    """Zero-filled (k_pool, v_pool) — plain arrays, or ``QuantizedPool``
    pairs when ``quantized`` (the kv_cache_dtype="int8" tier)."""
    shape = (n_layers, num_blocks, block_len, n_heads, head_dim)
    if quantized:
        def qp():
            return QuantizedPool(jnp.zeros(shape, jnp.int8),
                                 jnp.zeros(shape[:-1], jnp.float32))
        return qp(), qp()
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def pool_bytes(pool) -> int:
    """Total device bytes of one pool entry (plain array or QuantizedPool)."""
    return sum(leaf.size * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(pool))


def cow_copy(k_pool, v_pool, src, dst):
    """Copy one block's content (every layer, K and V) from ``src`` to
    ``dst`` — the copy-on-write primitive for prefix sharing. ``src``/
    ``dst`` are runtime int32 scalars, so ONE compiled program serves every
    copy; functional update keeps the read-before-write ordering a data
    dependency. Generic over plain and quantized pools (a quantized COW
    copies codes AND scales — bit-exact sharing)."""
    copy = lambda p: p.at[:, dst].set(p[:, src])
    k_pool = jax.tree_util.tree_map(copy, k_pool)
    v_pool = jax.tree_util.tree_map(copy, v_pool)
    return k_pool, v_pool


def prefill_scatter(pool, layer_kv, tables):
    """Write a prefill's K or V for one layer into the pool.

    pool      [n_layers, nb, blk, H, Dh] (functional update; plain or
              ``QuantizedPool`` — quantized pools quantize-on-write)
    layer_kv  list of [P, L, H, Dh] per layer (L % blk == 0)
    tables    [P, max_blocks] int32 — first L//blk entries are the
              sequence's blocks (rest point at trash block 0).
    """
    P, L, H, Dh = layer_kv[0].shape
    if isinstance(pool, QuantizedPool):
        blk = pool.q.shape[2]
        nblk = L // blk
        qp, sp = pool
        for i, kv in enumerate(layer_kv):
            q, s = kv_quantize(kv)
            qp = qp.at[i, tables[:, :nblk]].set(
                q.reshape(P, nblk, blk, H, Dh))
            sp = sp.at[i, tables[:, :nblk]].set(
                s.reshape(P, nblk, blk, H))
        return QuantizedPool(qp, sp)
    blk = pool.shape[2]
    nblk = L // blk
    for i, kv in enumerate(layer_kv):
        pool = pool.at[i, tables[:, :nblk]].set(
            kv.reshape(P, nblk, blk, H, Dh))
    return pool


def _pool_write(pool, i, bid, off, tok):
    """Scatter one layer's token (or window) K/V at (bid, off) — the
    quantize-on-write seam. ``tok`` [..., H, Dh] with leading [S] or
    [S, W] index shape matching bid/off."""
    if isinstance(pool, QuantizedPool):
        q, s = kv_quantize(tok)
        return QuantizedPool(pool.q.at[i, bid, off].set(q),
                             pool.scale.at[i, bid, off].set(s))
    return pool.at[i, bid, off].set(tok)


def _pool_gather(pool, i, tables, S, ctx_len, H, Dh, dtype):
    """Gather the full context for one layer → [S, H, ctx, Dh] — the
    dequantize-in-attention seam."""
    if isinstance(pool, QuantizedPool):
        ctx = pool.q[i][tables].reshape(S, ctx_len, H, Dh)
        sc = pool.scale[i][tables].reshape(S, ctx_len, H)
        ctx = kv_dequantize(ctx, sc, dtype)
    else:
        ctx = pool[i][tables].reshape(S, ctx_len, H, Dh)
    return ctx.transpose(0, 2, 1, 3)


class QuantSimStore:
    """Full-prompt window store for the int8-KV PREFILL: records each
    layer's raw K/V (for the quantize-on-write scatter afterwards) and
    serves attention the FAKE-QUANTIZED context — dequantize(quantize(k))
    — with the causal row mask.

    Why it exists: a prefix-cache hit skips prefill and replays the
    unmatched suffix through the one-token decode program, whose
    attention sees dequantized int8 K/V. If prefill computed its logits
    from full-precision K/V, hit and miss paths would diverge token-for-
    token. Running the prefill through ``decode_window`` with this store
    makes row ``i`` see exactly what a decode step at position ``i``
    would read back from the quantized pool (quantization is
    deterministic, so the scatter stores the identical codes) — the
    quantized engine is self-consistent across prefill / decode / replay
    / speculative verify."""

    def __init__(self, n_layers: int):
        self.ks: List = [None] * n_layers
        self.vs: List = [None] * n_layers

    def put_get(self, i: int, k_win, v_win):
        """k_win/v_win: [B, W, H, Dh]. Returns (K [B,H,W,Dh],
        V [B,H,W,Dh], causal row_mask [B,W,W])."""
        self.ks[i] = k_win
        self.vs[i] = v_win
        B, W = k_win.shape[:2]

        def fakeq(x):
            q, s = kv_quantize(x)
            return kv_dequantize(q, s, x.dtype).transpose(0, 2, 1, 3)

        mask = (jnp.arange(W)[None, None, :]
                <= jnp.arange(W)[None, :, None])
        mask = jnp.broadcast_to(mask, (B, W, W))
        return fakeq(k_win), fakeq(v_win), mask


class PagedStore:
    """``models.decode.KVStore`` over the paged pools for ONE decode step.

    Scatter-then-gather: the current token's K/V lands in its block slot
    first, then the gathered context (position-ordered, so attention row
    ``pos`` is bit-identical to the naive causal row) includes it.
    Inactive rows scatter to the trash block."""

    def __init__(self, k_pool, v_pool, tables, pos, active, block_len: int):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.tables = tables              # [S, max_blocks] int32
        self.pos = pos                    # [S] int32
        self.active = active              # [S] bool
        self.block_len = int(block_len)
        S, mb = tables.shape
        self._ctx_len = mb * self.block_len
        bid = jnp.take_along_axis(tables, (pos // self.block_len)[:, None],
                                  axis=1)[:, 0]
        self._bid = jnp.where(active, bid, 0)      # trash for idle slots
        self._off = jnp.where(active, pos % self.block_len, 0)
        self._mask = (jnp.arange(self._ctx_len)[None, :] <= pos[:, None])

    def put_get(self, i: int, k_tok, v_tok):
        S = k_tok.shape[0]
        H, Dh = k_tok.shape[-2:]
        self.k_pool = _pool_write(self.k_pool, i, self._bid, self._off, k_tok)
        self.v_pool = _pool_write(self.v_pool, i, self._bid, self._off, v_tok)
        K = _pool_gather(self.k_pool, i, self.tables, S, self._ctx_len,
                         H, Dh, k_tok.dtype)
        V = _pool_gather(self.v_pool, i, self.tables, S, self._ctx_len,
                         H, Dh, v_tok.dtype)
        return K, V, self._mask

    @property
    def pools(self):
        return self.k_pool, self.v_pool


class PagedWindowStore:
    """``models.decode`` window store over the paged pools for ONE
    speculative-verify pass: W = k+1 fed tokens per slot land at positions
    ``pos .. pos+W-1`` (crossing block boundaries via per-position
    (block, offset) indices), then the gathered context plus per-row key
    masks reproduce, row by row, exactly the visibility the one-token
    ``PagedStore`` gives position ``pos+i`` — which is what makes the
    batched verify bit-identical to W sequential decode steps."""

    def __init__(self, k_pool, v_pool, tables, pos, active, block_len: int,
                 window: int):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.tables = tables              # [S, max_blocks] int32
        self.block_len = int(block_len)
        S, mb = tables.shape
        self._ctx_len = mb * self.block_len
        w_pos = pos[:, None] + jnp.arange(window)[None, :]       # [S, W]
        bidx = jnp.clip(w_pos // self.block_len, 0, mb - 1)
        bid = jnp.take_along_axis(tables, bidx, axis=1)          # [S, W]
        # idle slots AND window positions past capacity (a verify window is
        # always W wide even when < W tokens of budget remain) go to trash —
        # a clipped in-range write would corrupt the last real block
        ok = active[:, None] & (w_pos < mb * self.block_len)
        self._bid = jnp.where(ok, bid, 0)
        self._off = jnp.where(ok, w_pos % self.block_len, 0)
        # row i of a slot's mask: keys at positions <= pos+i are visible
        self._mask = (jnp.arange(self._ctx_len)[None, None, :]
                      <= w_pos[:, :, None])                      # [S, W, ctx]

    def put_get(self, i: int, k_win, v_win):
        """k_win/v_win: [S, W, H, Dh] for the window. Returns
        (K [S,H,ctx,Dh], V [S,H,ctx,Dh], row_mask [S,W,ctx])."""
        S = k_win.shape[0]
        H, Dh = k_win.shape[-2:]
        self.k_pool = _pool_write(self.k_pool, i, self._bid, self._off, k_win)
        self.v_pool = _pool_write(self.v_pool, i, self._bid, self._off, v_win)
        K = _pool_gather(self.k_pool, i, self.tables, S, self._ctx_len,
                         H, Dh, k_win.dtype)
        V = _pool_gather(self.v_pool, i, self.tables, S, self._ctx_len,
                         H, Dh, v_win.dtype)
        return K, V, self._mask

    @property
    def pools(self):
        return self.k_pool, self.v_pool
