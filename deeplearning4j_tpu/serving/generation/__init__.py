"""serving/generation — autoregressive decode subsystem.

Paged-KV-cache incremental decode with continuous batching and per-token
streaming (ROADMAP open item 4): the serving engine's
precompiled-fixed-shape-program discipline (cuDNN's shape-specialized
primitives, arXiv:1410.0759, applied to whole XLA programs) extended to
generation, where the working set GROWS per token. The trick is vLLM-style
paging: the KV cache is a fixed block pool + per-sequence block tables, so
context growth is block allocation — no array ever changes shape, nothing
ever recompiles after warm-up.

Pillars:
  - kvcache.py      block pool, refcounted free-list allocator,
                    gather/scatter, the PagedStore / PagedWindowStore
                    bridges into models/decode.py, the COW block copy
  - prefix.py       copy-on-write prefix-cache sharing: rolling
                    prompt-prefix hash chain over immutable full blocks,
                    refcounts + LRU + eviction under pool pressure
  - speculative.py  draft-propose k tokens / one batched target verify:
                    dense (truncated transformer) + state (LSTM) draft
                    adapters, exact greedy acceptance rule
  - programs.py     GenerationConfig + AOT-warmed prefill (bucketed),
                    decode-step, cow, draft-prefill/propose/rewind and
                    verify executables, buffer-donated cache, jit-carried
                    PRNG
  - sampling.py     greedy / temperature / top-k, in-program
  - scheduler.py    continuous batching: step-boundary admission (prefix
                    matched, suffix replayed), slot backfill, verify-step
                    interleave, TokenStream per request, cohort-pinned
                    hot-swap, armed RecompileDetector, block-accounting
                    quiesce invariant
  - metrics.py      TTFT (uncached AND cached), decode/verify latency,
                    tokens/sec, slot occupancy, block-pool economics
                    (shared/COW/LRU/evictions), accepted-per-verify ->
                    GET /metrics + telemetry registry
  - engine.py       GenerationEngine facade (multi-model, hot-swap, drain)

Model math lives in models/decode.py (TransformerDecodeSpec /
LSTMDecodeSpec + decode_window + the naive_generate bit-exactness
reference); the HTTP streaming surface is serving/http.py
(POST /generate).
"""
from .engine import GenerationEngine
from .kvcache import (BlockAllocator, PagedStore, PagedWindowStore,
                      cow_copy, make_pools)
from .metrics import GenerationMetrics
from .prefix import PrefixCache
from .programs import GenerationConfig, GenerationProgramSet
from .sampling import sample_tokens
from .scheduler import ModelRuntime, TokenStream
from .speculative import DenseDraftStore, accept_greedy

__all__ = [
    "GenerationEngine", "GenerationConfig", "GenerationProgramSet",
    "GenerationMetrics", "ModelRuntime", "TokenStream", "BlockAllocator",
    "PagedStore", "PagedWindowStore", "PrefixCache", "DenseDraftStore",
    "accept_greedy", "cow_copy", "make_pools", "sample_tokens",
]
