"""serving/generation — autoregressive decode subsystem.

Paged-KV-cache incremental decode with continuous batching and per-token
streaming (ROADMAP open item 4): the serving engine's
precompiled-fixed-shape-program discipline (cuDNN's shape-specialized
primitives, arXiv:1410.0759, applied to whole XLA programs) extended to
generation, where the working set GROWS per token. The trick is vLLM-style
paging: the KV cache is a fixed block pool + per-sequence block tables, so
context growth is block allocation — no array ever changes shape, nothing
ever recompiles after warm-up.

Pillars:
  - kvcache.py    block pool, free-list allocator, gather/scatter, the
                  PagedStore bridge into models/decode.py
  - programs.py   GenerationConfig + AOT-warmed prefill (bucketed) and
                  decode-step executables, buffer-donated cache,
                  jit-carried PRNG
  - sampling.py   greedy / temperature / top-k, in-program
  - scheduler.py  continuous batching: step-boundary admission, slot
                  backfill, TokenStream per request, cohort-pinned
                  hot-swap, armed RecompileDetector
  - metrics.py    TTFT, decode-step latency, tokens/sec, slot occupancy,
                  block usage -> GET /metrics + telemetry registry
  - engine.py     GenerationEngine facade (multi-model, hot-swap, drain)

Model math lives in models/decode.py (TransformerDecodeSpec /
LSTMDecodeSpec + the naive_generate bit-exactness reference); the HTTP
streaming surface is serving/http.py (POST /generate).
"""
from .engine import GenerationEngine
from .kvcache import BlockAllocator, PagedStore, make_pools
from .metrics import GenerationMetrics
from .programs import GenerationConfig, GenerationProgramSet
from .sampling import sample_tokens
from .scheduler import ModelRuntime, TokenStream

__all__ = [
    "GenerationEngine", "GenerationConfig", "GenerationProgramSet",
    "GenerationMetrics", "ModelRuntime", "TokenStream", "BlockAllocator",
    "PagedStore", "make_pools", "sample_tokens",
]
