"""AOT-warmed generation programs: bucketed prefill + ONE decode step.

Extends the ``serving/programs.py`` discipline to autoregressive decode:
every program the steady-state loop can ever need is lowered and compiled at
``warm()`` —

  - one **prefill** executable per (admission-batch rung P, prompt rung L):
    padded prompt -> per-position logits via the graph's own ``apply_fn``
    (bit-identical to ``net.output``), K/V scattered into the paged pools,
    first token sampled in-program;
  - one **decode-step** executable: one token per in-flight slot, gather via
    block tables, scatter the step's K/V, sample the next token — cache
    buffers donated so the pool updates in place on real devices.

Params/state are arguments, not constants, so hot-swap reuses executables
exactly as the forward-serving ProgramSet does (``with_params_from``).
The PRNG key is carried through every program and split in-program.

Model support is adapter-based: ``models.decode.TransformerDecodeSpec``
(paged KV cache) and ``models.decode.LSTMDecodeSpec`` (the cache is the
fixed-shape recurrent state; the block machinery degenerates to zero-block
bookkeeping but the program/scheduler contract is identical).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...models.decode import LSTMDecodeSpec, TransformerDecodeSpec
from ...parallel.tensor_parallel import (MODEL_AXIS, build_param_specs,
                                         model_axis_size, per_replica_bytes,
                                         shard_params)
from ..programs import _arch_key, _tree_signature
from .kvcache import (PagedStore, QuantSimStore, make_pools,
                      prefill_scatter)
from .sampling import sample_tokens


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@dataclass
class GenerationConfig:
    """Shape/capacity plan for one generation model. Everything here is
    trace-time static — the warmed program set covers the full plan, so
    admission-time work is array fills only."""
    block_len: int = 16
    max_seq_len: int = 128            # prompt + generated tokens, per request
    decode_slots: int = 8             # in-flight sequences per decode step
    prefill_batches: Tuple[int, ...] = (1, 2, 4)
    prompt_rungs: Optional[Tuple[int, ...]] = None   # default: (capacity,)
    num_blocks: Optional[int] = None  # pool size; default: full occupancy + 1
    queue_limit: int = 256
    default_timeout_s: float = 30.0
    default_max_tokens: int = 32
    seed: int = 0
    # prefix-cache sharing (ISSUE 14): None = on for the paged adapter,
    # off for the state adapter (no blocks to share); True/False overrides
    prefix_cache: Optional[bool] = None
    # longest unmatched prompt suffix (tokens) a cache hit may REPLAY
    # through the one-token decode program; a shorter match is treated as
    # a miss — sequential replay of a long suffix would cost far more
    # than the batched prefill it "saves". None = 2 * block_len.
    prefix_max_replay: Optional[int] = None
    # speculative decoding: draft proposals per verify window; 0 with a
    # draft model attached defaults to 4 at program-set construction
    spec_k: int = 0
    # quantized KV tier (ISSUE 17): "int8" stores the paged block pool as
    # int8 codes + per-(token, head) f32 scales — quantize-on-write /
    # dequantize-in-attention inside the warmed programs, so the same
    # num_blocks holds ~2x+ the tokens per byte. None = full precision.
    kv_cache_dtype: Optional[str] = None

    def __post_init__(self):
        if self.block_len < 1 or self.decode_slots < 1:
            raise ValueError("block_len and decode_slots must be >= 1")
        if self.kv_cache_dtype not in (None, "int8"):
            raise ValueError(f"kv_cache_dtype must be None or 'int8', got "
                             f"{self.kv_cache_dtype!r}")
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        self.capacity = _ceil_to(self.max_seq_len, self.block_len)
        self.blocks_per_seq = self.capacity // self.block_len
        self.prefill_batches = tuple(sorted(set(
            int(b) for b in self.prefill_batches)))
        if not self.prefill_batches or self.prefill_batches[0] < 1:
            raise ValueError("prefill_batches must be positive")
        rungs = self.prompt_rungs or (self.capacity,)
        rungs = tuple(sorted({min(_ceil_to(int(r), self.block_len),
                                  self.capacity) for r in rungs}))
        if rungs[-1] != self.capacity:
            rungs = rungs + (self.capacity,)
        self.prompt_rungs = rungs
        if self.num_blocks is None:
            self.num_blocks = self.decode_slots * self.blocks_per_seq + 1
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is trash)")
        if self.prefix_max_replay is None:
            self.prefix_max_replay = 2 * self.block_len
        elif self.prefix_max_replay < 1:
            raise ValueError("prefix_max_replay must be >= 1 (the final "
                             "prompt token always replays)")

    @property
    def max_prompt_len(self) -> int:
        return self.prompt_rungs[-1]

    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        return math.ceil((prompt_len + max_new) / self.block_len)

    def prefill_rung(self, n: int) -> int:
        for b in self.prefill_batches:
            if n <= b:
                return b
        return self.prefill_batches[-1]

    def prompt_rung(self, plen: int) -> int:
        for r in self.prompt_rungs:
            if plen <= r:
                return r
        raise ValueError(f"prompt length {plen} exceeds the largest prompt "
                         f"rung {self.prompt_rungs[-1]}")


def _donate_argnums() -> Tuple[int, ...]:
    # cache donation is a no-op (with a warning) on the CPU test backend
    return (2,) if jax.default_backend() in ("tpu", "gpu") else ()


class GenerationProgramSet:
    """One model version's warmed generation executables + its params.

    Immutable after ``warm()`` — the engine swaps whole sets atomically and
    the scheduler pins each in-flight cohort to the set it was admitted
    under (the hot-swap cutover rule)."""

    def __init__(self, net, *, config: GenerationConfig,
                 adapter: str = "auto", draft_net=None,
                 trace_hook: Optional[Callable[[], None]] = None,
                 cost_path: Optional[str] = None,
                 mesh: Optional[Mesh] = None):
        self.net = net
        self.config = config
        self._trace_hook = trace_hook
        self.cost_path = cost_path    # e.g. "generation.<model>": enables
        # cost-index registration of the warmed executables (perf.py)
        self.adapter = self._resolve_adapter(net, adapter)
        self.spec = (TransformerDecodeSpec(net) if self.adapter == "paged"
                     else LSTMDecodeSpec(net))
        # sharded decode (ISSUE 20): a ``(data, model)`` mesh with m > 1
        # shards the Q/K/V/O projections and the paged KV pools by HEAD
        # across the model axis — one decode step spans chips, the
        # host-side block tables / allocator / prefix cache are untouched
        # (they index blocks, and blocks keep their ids under sharding).
        self.model_shards = model_axis_size(mesh)
        self.mesh = mesh if self.model_shards > 1 else None
        if self.model_shards > 1:
            if self.adapter != "paged":
                raise ValueError(
                    "model-sharded decode requires the paged (transformer) "
                    "adapter — the recurrent-state cache has no head axis "
                    "to split")
            if not self.spec.supports_head_sharding(self.model_shards):
                raise ValueError(
                    f"n_heads={self.spec.n_heads} does not divide by the "
                    f"model axis ({self.model_shards}) — the paged pools "
                    f"shard whole heads")
        self.params = jax.tree.map(jnp.asarray, net.params)
        self.state = jax.tree.map(jnp.asarray, net.state)
        if self.mesh is not None:
            self.params = shard_params(
                self.mesh, self.params,
                build_param_specs(net, self.model_shards))
            rep = NamedSharding(self.mesh, PartitionSpec())
            self.state = jax.tree.map(
                lambda a: jax.device_put(a, rep), self.state)
        self.dtype = self.spec.dtype
        self.vocab = self.spec.vocab
        # prefix-cache sharing only exists where there are blocks to share
        self.prefix_enabled = (self.adapter == "paged"
                               if config.prefix_cache is None
                               else bool(config.prefix_cache)
                               and self.adapter == "paged")
        # int8-quantized KV tier: paged pools only (the state adapter's
        # carry is recurrent state, not a token cache)
        self.kv_quantized = config.kv_cache_dtype == "int8"
        if self.kv_quantized and self.adapter != "paged":
            raise ValueError("kv_cache_dtype='int8' requires the paged "
                             "(transformer) adapter — the state adapter "
                             "has no KV block pool to quantize")
        # speculative decoding: active iff a draft model is attached
        self.draft_net = draft_net
        self.spec_k = 0
        self.draft_adapter: Optional[str] = None
        self.draft_spec = None
        if draft_net is not None:
            if self.adapter != "paged":
                raise ValueError(
                    "speculative decoding requires a paged (transformer) "
                    "TARGET — the verify window runs over the block tables")
            self.spec_k = int(config.spec_k) or 4
            da = self._resolve_adapter(draft_net, "auto")
            self.draft_adapter = "dense" if da == "paged" else "state"
            self.draft_spec = (TransformerDecodeSpec(draft_net)
                               if da == "paged" else LSTMDecodeSpec(draft_net))
            if self.draft_spec.vocab != self.vocab:
                raise ValueError(
                    f"draft vocab {self.draft_spec.vocab} != target vocab "
                    f"{self.vocab} — proposals must share the token space")
            self.draft_params = jax.tree.map(jnp.asarray, draft_net.params)
            self.draft_state = jax.tree.map(jnp.asarray, draft_net.state)
            if self.mesh is not None:
                # the dense-transformer draft shards exactly like the
                # target (same head recipe); a draft whose head count
                # doesn't divide (or an LSTM draft) stays replicated —
                # GSPMD keeps it correct, just not memory-split
                self._draft_sharded = (
                    self.draft_adapter == "dense"
                    and self.draft_spec.supports_head_sharding(
                        self.model_shards))
                dspecs = (build_param_specs(draft_net, self.model_shards)
                          if self._draft_sharded else
                          jax.tree.map(lambda _: PartitionSpec(),
                                       self.draft_params))
                self.draft_params = shard_params(self.mesh,
                                                 self.draft_params, dspecs)
                rep = NamedSharding(self.mesh, PartitionSpec())
                self.draft_state = jax.tree.map(
                    lambda a: jax.device_put(a, rep), self.draft_state)
            if self.draft_adapter == "state":
                self._draft_init_states = self.draft_spec.init_states(
                    config.decode_slots + 1)
        draft_sig = None if draft_net is None else (
            _tree_signature(self.draft_params),
            _tree_signature(self.draft_state), _arch_key(draft_net),
            self.draft_adapter, self.spec_k)
        mesh_sig = None if self.mesh is None else (
            tuple(self.mesh.devices.shape), tuple(self.mesh.axis_names),
            tuple(d.id for d in self.mesh.devices.flat))
        self.signature = (_tree_signature(self.params),
                          _tree_signature(self.state), _arch_key(net),
                          self.adapter, config.block_len, config.capacity,
                          config.decode_slots, config.prefill_batches,
                          config.prompt_rungs, config.num_blocks,
                          self.prefix_enabled, config.kv_cache_dtype,
                          mesh_sig, draft_sig)
        self._compiled: Dict[Any, Any] = {}
        self.kv_pool_chip_bytes: Optional[int] = None   # set by warm()
        if self.adapter == "state":
            self._init_states = self.spec.init_states(config.decode_slots + 1)

    @staticmethod
    def _resolve_adapter(net, adapter: str) -> str:
        if adapter in ("paged", "transformer"):
            return "paged"
        if adapter in ("state", "lstm"):
            return "state"
        if adapter != "auto":
            raise ValueError(f"unknown adapter {adapter!r}")
        # ComputationGraph transformer vs MultiLayerNetwork recurrent stack
        if hasattr(net, "vertex_names") and "b0_attn" in net.vertex_names:
            return "paged"
        return "state"

    # ---------------------------------------------------------------- cache
    def _pool_sharding(self) -> Optional[NamedSharding]:
        """Head-axis sharding for the paged pools: every pool-shaped array
        in the decode subsystem carries its heads on axis 3 —
        k/v pools [n_layers, nb, blk, H, Dh], int8 scales
        [n_layers, nb, blk, H], dense draft caches
        [n_layers, slots+1, cap, H, Dh] — so ONE spec serves them all
        (PartitionSpec pads trailing axes with None)."""
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh,
                             PartitionSpec(None, None, None, MODEL_AXIS))

    def make_cache(self):
        """Fresh cache pytree: (k_pool, v_pool) for the paged adapter, the
        zeroed recurrent-state carry (decode_slots + 1 rows, last row is
        the prefill-padding trash slot) for the state adapter."""
        c = self.config
        if self.adapter == "paged":
            cache = make_pools(self.spec.n_blocks, c.num_blocks,
                               c.block_len, self.spec.n_heads,
                               self.spec.head_dim, self.dtype,
                               quantized=self.kv_quantized)
            sh = self._pool_sharding()
            if sh is not None:
                cache = jax.tree.map(lambda a: jax.device_put(a, sh), cache)
        else:
            cache = jax.tree.map(jnp.zeros_like, self._init_states)
        try:     # memprof owner hint: the block pool dominates live HBM
            from ...telemetry import memprof
            memprof.tag(cache, (self.cost_path or "generation")
                        + ".kvcache")
        except Exception:       # pragma: no cover - defensive
            pass
        return cache

    def fresh_key(self):
        return jax.random.PRNGKey(self.config.seed)

    def kv_bytes_per_token(self) -> Optional[float]:
        """Block-pool device bytes per token SLOT (K + V, all layers/
        heads) — the capacity-per-byte currency the quantized tier
        moves; published as ``generation.<m>.kv_bytes_per_token``.
        None for the state adapter (no token-addressed pool)."""
        if self.adapter != "paged":
            return None
        s = self.spec
        if self.kv_quantized:
            per_head = s.head_dim * 1 + 4          # int8 codes + f32 scale
        else:
            per_head = s.head_dim * jnp.dtype(self.dtype).itemsize
        return float(2 * s.n_blocks * s.n_heads * per_head)

    def make_draft_cache(self):
        """Fresh draft cache: dense per-slot K/V for a transformer draft,
        zeroed recurrent states (slots + 1 rows) for an LSTM draft; None
        when speculation is off."""
        if self.draft_adapter is None:
            return None
        from .speculative import make_dense_draft_cache
        if self.draft_adapter == "dense":
            dcache = make_dense_draft_cache(self.draft_spec,
                                            self.config.decode_slots,
                                            self.config.capacity)
            sh = self._pool_sharding()
            if sh is not None and self._draft_sharded:
                dcache = jax.tree.map(lambda a: jax.device_put(a, sh),
                                      dcache)
            return dcache
        return jax.tree.map(jnp.zeros_like, self._draft_init_states)

    def kv_pool_bytes_per_chip(self, cache=None) -> int:
        """Device bytes of the block pool resident on ONE chip — the
        m×-reduction number the sharded-decode tier is bought for
        (``generation.<m>.kv_pool_bytes_per_chip``). With no mesh this is
        simply the full pool size."""
        return per_replica_bytes(cache if cache is not None
                                 else self.make_cache())

    # ------------------------------------------------------------- programs
    def _prefill_fn(self):
        spec = self.spec

        def fn(params, state, cache, tokens, lengths, tables, slots, key,
               temp, topk):
            if self._trace_hook is not None:
                self._trace_hook()
            if self.adapter == "paged":
                k_pool, v_pool = cache
                if self.kv_quantized:
                    # int8 tier: compute the prefill logits through FAKE-
                    # QUANTIZED attention (QuantSimStore) so the first
                    # sampled token matches what a decode-step replay of
                    # the same prompt would produce — the prefix-cache
                    # hit path replays the unmatched suffix through the
                    # decode program, and both must see identical K/V
                    store = QuantSimStore(spec.n_blocks)
                    logits = spec.decode_window(
                        params, state, tokens,
                        jnp.zeros((tokens.shape[0],), jnp.int32), store)
                    ks, vs = store.ks, store.vs
                else:
                    logits, ks, vs = spec.prefill_forward(params, state,
                                                          tokens)
                k_pool = prefill_scatter(k_pool, ks, tables)
                v_pool = prefill_scatter(v_pool, vs, tables)
                last = jnp.take_along_axis(
                    logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
                tok, key = sample_tokens(last, key, temp, topk)
                return tok, (k_pool, v_pool), key
            P = tokens.shape[0]
            zero = jax.tree.map(
                lambda c: jnp.zeros((P,) + c.shape[1:], c.dtype), cache)
            logits, final = spec.prefill_scan(params, state, tokens, lengths,
                                              zero)
            cache = jax.tree.map(lambda c, n: c.at[slots].set(n), cache,
                                 final)
            tok, key = sample_tokens(logits, key, temp, topk)
            return tok, cache, key
        return fn

    def _decode_fn(self):
        spec, blk = self.spec, self.config.block_len

        def fn(params, state, cache, tokens, pos, tables, active, key,
               temp, topk):
            if self._trace_hook is not None:
                self._trace_hook()
            if self.adapter == "paged":
                store = PagedStore(cache[0], cache[1], tables, pos, active,
                                   blk)
                logits = spec.decode_step(params, state, tokens, pos, store)
                tok, key = sample_tokens(logits, key, temp, topk)
                return tok, store.pools, key
            S = tokens.shape[0]
            cur = jax.tree.map(lambda c: c[:S], cache)
            logits, new = spec.decode_step(params, state, tokens, cur)

            def merge(c, n):
                keep = active.reshape((S,) + (1,) * (n.ndim - 1))
                return jnp.concatenate(
                    [jnp.where(keep, n, c[:S]), c[S:]], axis=0)
            cache = jax.tree.map(merge, cache, new)
            tok, key = sample_tokens(logits, key, temp, topk)
            return tok, cache, key
        return fn

    def _sds(self, a):
        # under a mesh the cache argument's layout is part of the AOT
        # contract: lowering against the sharded spec is what compiles the
        # one cross-chip decode step (and what keeps re-dispatch from
        # recompiling — the runtime pools carry the same sharding)
        if self.mesh is not None and hasattr(a, "sharding") \
                and isinstance(a.sharding, NamedSharding):
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=a.sharding)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    def _cache_spec(self):
        return jax.tree.map(self._sds, self.make_cache())

    def _draft_cache_spec(self):
        return jax.tree.map(self._sds, self.make_draft_cache())

    def _key_spec(self):
        k = self.fresh_key()
        return jax.ShapeDtypeStruct(k.shape, k.dtype)

    def _cow_fn(self):
        from .kvcache import cow_copy

        def fn(cache, src, dst):
            if self._trace_hook is not None:
                self._trace_hook()
            return cow_copy(cache[0], cache[1], src, dst)
        return fn

    def _spec_fns(self):
        """(draft_prefill, propose, rewind_or_None, verify) builders."""
        from . import speculative as sp
        tgt, blk, k = self.spec, self.config.block_len, self.spec_k
        hook = self._trace_hook

        def hooked(f):
            def g(*a):
                if hook is not None:
                    hook()
                return f(*a)
            return g

        verify = hooked(sp.verify_fn(tgt, blk, k))
        if self.draft_adapter == "dense":
            return (hooked(sp.draft_prefill_dense_fn(self.draft_spec)),
                    hooked(sp.propose_dense_fn(self.draft_spec, k)),
                    None, verify)
        return (hooked(sp.draft_prefill_state_fn(self.draft_spec)),
                hooked(sp.propose_state_fn(self.draft_spec, k)),
                hooked(sp.rewind_state_fn()), verify)

    # --------------------------------------------------------------- warm-up
    def warm(self) -> "GenerationProgramSet":
        """Compile every prefill rung and the decode step; touch each once
        so first traffic pays no one-time dispatch setup. NEVER called on
        the decode hot path."""
        c = self.config
        i32 = jnp.int32
        cache_spec, key_spec = self._cache_spec(), self._key_spec()
        mb = c.blocks_per_seq
        prefill = self._prefill_fn()
        decode = self._decode_fn()
        for P in c.prefill_batches:
            for L in c.prompt_rungs:
                jitted = jax.jit(prefill, donate_argnums=_donate_argnums())
                self._compiled[("prefill", P, L)] = jitted.lower(
                    self.params, self.state, cache_spec,
                    jax.ShapeDtypeStruct((P, L), i32),
                    jax.ShapeDtypeStruct((P,), i32),
                    jax.ShapeDtypeStruct((P, mb), i32),
                    jax.ShapeDtypeStruct((P,), i32),
                    key_spec,
                    jax.ShapeDtypeStruct((P,), jnp.float32),
                    jax.ShapeDtypeStruct((P,), i32)).compile()
        S = c.decode_slots
        jitted = jax.jit(decode, donate_argnums=_donate_argnums())
        self._compiled[("decode",)] = jitted.lower(
            self.params, self.state, cache_spec,
            jax.ShapeDtypeStruct((S,), i32),
            jax.ShapeDtypeStruct((S,), i32),
            jax.ShapeDtypeStruct((S, mb), i32),
            jax.ShapeDtypeStruct((S,), jnp.bool_),
            key_spec,
            jax.ShapeDtypeStruct((S,), jnp.float32),
            jax.ShapeDtypeStruct((S,), i32)).compile()
        if self.prefix_enabled:
            # the copy-on-write block copy: src/dst are runtime scalars, so
            # ONE executable serves every copy
            donate = (0,) if jax.default_backend() in ("tpu", "gpu") else ()
            self._compiled[("cow",)] = jax.jit(
                self._cow_fn(), donate_argnums=donate).lower(
                cache_spec, jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32)).compile()
        if self.spec_k:
            self._warm_spec(cache_spec, i32)
        # one touch per executable: first real traffic must not pay
        # dispatch-setup either
        cache, key = self.make_cache(), self.fresh_key()
        self.kv_pool_chip_bytes = self.kv_pool_bytes_per_chip(cache)
        for P in c.prefill_batches:
            for L in c.prompt_rungs:
                _, cache, key = self.run_prefill(
                    cache, np.zeros((P, L), np.int32),
                    np.ones((P,), np.int32), np.zeros((P, mb), np.int32),
                    np.full((P,), S, np.int32), key,
                    np.zeros((P,), np.float32), np.zeros((P,), np.int32))
        _, cache, key = self.run_decode(
            cache, np.zeros((S,), np.int32), np.zeros((S,), np.int32),
            np.zeros((S, mb), np.int32), np.zeros((S,), np.bool_), key,
            np.zeros((S,), np.float32), np.zeros((S,), np.int32))
        if self.prefix_enabled:
            cache = self.run_cow(cache, 0, 0)
        if self.spec_k:
            cache = self._touch_spec(cache)
        self._register_costs()
        return self

    def _register_costs(self) -> None:
        """Cost-model accounting (telemetry/perf.py): register every
        warmed executable's cost analysis keyed by program. The decode
        step and verify window pair with the per-step latency histograms
        the scheduler already observes (``decode_step_ms`` /
        ``verify_step_ms``), so the perf fold yields live MFU/roofline
        gauges for the decode loop; prefill rungs register cost-only
        (roofline classification without a paired timing stream). Never
        raises into warm-up."""
        if self.cost_path is None:
            return
        try:
            from ...telemetry import get_registry
            from ...telemetry.perf import (accounting_enabled,
                                           get_cost_index)
            if not (accounting_enabled() and get_registry().enabled):
                return
            idx = get_cost_index()
            base = self.cost_path
            m = self.model_shards        # per-chip share of a tp program
            idx.register(f"{base}.decode_step",
                         program=self._compiled[("decode",)],
                         items_per_step=float(self.config.decode_slots),
                         model_axis_size=m,
                         timing_metric=f"{base}.decode_step_ms")
            if ("verify",) in self._compiled:
                idx.register(f"{base}.verify",
                             program=self._compiled[("verify",)],
                             items_per_step=float(self.config.decode_slots),
                             model_axis_size=m,
                             timing_metric=f"{base}.verify_step_ms")
            for key, compiled in self._compiled.items():
                if key[0] == "prefill":
                    _, P, L = key
                    idx.register(f"{base}.prefill.b{P}xp{L}",
                                 program=compiled, items_per_step=float(P),
                                 model_axis_size=m)
        except Exception:       # pragma: no cover - defensive
            pass

    def _warm_spec(self, cache_spec, i32):
        """Compile the draft + verify executables (speculative decoding).
        Cache-carrying programs donate their cache argument on TPU/GPU,
        exactly like the decode step — the pools update in place."""
        c = self.config
        S, mb, k = c.decode_slots, c.blocks_per_seq, self.spec_k
        dcache_spec = self._draft_cache_spec()
        d_prefill, propose, rewind, verify = self._spec_fns()
        sds = jax.ShapeDtypeStruct
        donate = _donate_argnums()             # (2,) on tpu/gpu, () on cpu
        for P in c.prefill_batches:
            for L in c.prompt_rungs:
                if self.draft_adapter == "dense":
                    self._compiled[("draft_prefill", P, L)] = jax.jit(
                        d_prefill, donate_argnums=donate).lower(
                        self.draft_params, self.draft_state, dcache_spec,
                        sds((P, L), i32), sds((P,), i32)).compile()
                else:
                    self._compiled[("draft_prefill", P, L)] = jax.jit(
                        d_prefill, donate_argnums=donate).lower(
                        self.draft_params, self.draft_state, dcache_spec,
                        sds((P, L), i32), sds((P,), i32),
                        sds((P,), i32)).compile()
        if self.draft_adapter == "dense":
            self._compiled[("propose",)] = jax.jit(
                propose, donate_argnums=donate).lower(
                self.draft_params, self.draft_state, dcache_spec,
                sds((S,), i32), sds((S,), i32),
                sds((S,), jnp.bool_)).compile()
        else:
            # the state propose RETURNS its input states untouched inside
            # the stack; no donation (the scheduler still needs states_all
            # until rewind commits)
            self._compiled[("propose",)] = jax.jit(propose).lower(
                self.draft_params, self.draft_state, dcache_spec,
                sds((S,), i32)).compile()
            stack_spec = jax.tree.map(
                lambda a: sds((k + 1, S) + a.shape[1:], a.dtype),
                dcache_spec)
            rw_donate = (0,) if jax.default_backend() in ("tpu", "gpu") \
                else ()
            self._compiled[("rewind",)] = jax.jit(
                rewind, donate_argnums=rw_donate).lower(
                dcache_spec, stack_spec, sds((S,), i32),
                sds((S,), jnp.bool_)).compile()
        self._compiled[("verify",)] = jax.jit(
            verify, donate_argnums=donate).lower(
            self.params, self.state, cache_spec, sds((S, k + 1), i32),
            sds((S,), i32), sds((S, mb), i32),
            sds((S,), jnp.bool_)).compile()

    def _touch_spec(self, cache):
        c = self.config
        S, mb, k = c.decode_slots, c.blocks_per_seq, self.spec_k
        zS = np.zeros((S,), np.int32)
        dcache = self.make_draft_cache()
        for P in c.prefill_batches:
            for L in c.prompt_rungs:
                dcache = self.run_draft_prefill(
                    dcache, np.zeros((P, L), np.int32),
                    np.ones((P,), np.int32), np.full((P,), S, np.int32))
        out = self.run_propose(dcache, zS, zS, np.zeros((S,), np.bool_))
        if self.draft_adapter == "dense":
            _, dcache = out
        else:
            _, stack = out
            dcache = self.run_rewind(dcache, stack, np.ones((S,), np.int32),
                                     np.zeros((S,), np.bool_))
        _, cache = self.run_verify(cache, np.zeros((S, k + 1), np.int32),
                                   zS, np.zeros((S, mb), np.int32),
                                   np.zeros((S,), np.bool_))
        return cache

    @property
    def warmed(self) -> bool:
        c = self.config
        want = {("prefill", P, L) for P in c.prefill_batches
                for L in c.prompt_rungs} | {("decode",)}
        if self.prefix_enabled:
            want |= {("cow",)}
        if self.spec_k:
            want |= {("draft_prefill", P, L) for P in c.prefill_batches
                     for L in c.prompt_rungs} | {("propose",), ("verify",)}
            if self.draft_adapter == "state":
                want |= {("rewind",)}
        return want <= set(self._compiled)

    # ---------------------------------------------------------------- running
    def run_prefill(self, cache, tokens, lengths, tables, slots, key, temp,
                    topk):
        """Returns (first_tokens np [P], cache', key')."""
        P, L = tokens.shape
        exe = self._compiled.get(("prefill", P, L))
        if exe is None:
            from ..errors import ServingError
            raise ServingError(
                f"no warmed prefill program for (batch={P}, rung={L}) — "
                f"call warm() before serving (warmed: "
                f"{sorted(k for k in self._compiled if k[0] == 'prefill')})")
        tok, cache, key = exe(self.params, self.state, cache, tokens,
                              lengths, tables, slots, key, temp, topk)
        return np.asarray(tok), cache, key

    def run_decode(self, cache, tokens, pos, tables, active, key, temp,
                   topk):
        """Returns (next_tokens np [S], cache', key')."""
        exe = self._compiled.get(("decode",))
        if exe is None:
            from ..errors import ServingError
            raise ServingError("no warmed decode program — call warm() "
                               "before serving")
        tok, cache, key = exe(self.params, self.state, cache, tokens, pos,
                              tables, active, key, temp, topk)
        return np.asarray(tok), cache, key

    def _exe(self, key):
        exe = self._compiled.get(key)
        if exe is None:
            from ..errors import ServingError
            raise ServingError(f"no warmed {key} program — call warm() "
                               "before serving")
        return exe

    # --------------------------------------------- prefix-cache programs
    def run_cow(self, cache, src: int, dst: int):
        """Copy block ``src`` -> ``dst`` in both pools (copy-on-write)."""
        return self._exe(("cow",))(cache, np.int32(src), np.int32(dst))

    # --------------------------------------------- speculative programs
    def run_draft_prefill(self, dcache, tokens, lengths, slots):
        """Draft consumes the FULL prompt (cache-hit admissions included:
        the draft is cheap — that is the point). Returns the draft cache."""
        P, L = tokens.shape
        exe = self._exe(("draft_prefill", P, L))
        if self.draft_adapter == "dense":
            return exe(self.draft_params, self.draft_state, dcache, tokens,
                       slots)
        return exe(self.draft_params, self.draft_state, dcache, tokens,
                   lengths, slots)

    def run_propose(self, dcache, cur, pos, active):
        """Returns (proposals np [S,k], dcache') for the dense draft, or
        (proposals np [S,k], states_stack) for the state draft (the caller
        commits the stack through run_rewind after verify)."""
        exe = self._exe(("propose",))
        if self.draft_adapter == "dense":
            props, dcache = exe(self.draft_params, self.draft_state, dcache,
                                cur, pos, active)
            return np.asarray(props), dcache
        props, stack = exe(self.draft_params, self.draft_state, dcache, cur)
        return np.asarray(props), stack

    def run_rewind(self, dcache, stack, idx, mask):
        """State-draft only: commit, per slot, the stacked state matching
        what verify accepted (masked slots keep their state)."""
        return self._exe(("rewind",))(dcache, stack, idx, mask)

    def run_verify(self, cache, feeds, pos, tables, active):
        """One batched target pass over [S, k+1] fed tokens. Returns
        (greedy targets np [S,k+1], cache')."""
        tgt, cache = self._exe(("verify",))(self.params, self.state, cache,
                                            feeds, pos, tables, active)
        return np.asarray(tgt), cache

    # --------------------------------------------------------------- hot-swap
    def with_params_from(self, net, draft_net=None) -> "GenerationProgramSet":
        """Same-architecture swap: new set sharing THIS set's executables.
        The draft model (when speculating) carries over unless a new one is
        given. Raises ValueError when the signature changed (caller warms a
        fresh set before cutover)."""
        new = GenerationProgramSet(net, config=self.config,
                                   adapter=self.adapter,
                                   draft_net=draft_net or self.draft_net,
                                   trace_hook=self._trace_hook,
                                   cost_path=self.cost_path,
                                   mesh=self.mesh)
        if new.signature != self.signature:
            raise ValueError("parameter/architecture changed; full warm-up "
                             "required")
        new._compiled = self._compiled
        new.kv_pool_chip_bytes = self.kv_pool_chip_bytes
        return new
