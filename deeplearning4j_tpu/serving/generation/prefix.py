"""Copy-on-write prefix-cache sharing over the paged KV block pool.

Thousands of requests that open with the same system prompt should pay
prefill ONCE. The unit of sharing is the immutable FULL block: a prompt's
first ``floor(plen/block_len)`` blocks hold K/V that never changes after
prefill, so they are keyed by a rolling prefix hash —

    h_0 = H(tokens[0:blk])      h_i = H(h_{i-1} || tokens[i*blk:(i+1)*blk])

— which makes a chain lookup equivalent to longest-prefix matching without
ever comparing tokens twice. Admission walks the chain, bumps the matched
blocks' refcounts (the ``BlockAllocator`` owns refcounts; freeing a
refcounted block raises), points the new sequence's block table at the
shared read-only blocks, and the scheduler replays only the UNMATCHED
prompt suffix through the already-warmed decode program — TTFT for a fully
cached prefix is one decode step instead of a prefill.

Copy-on-write: when the match covers the whole prompt (block-aligned), the
final prompt token must still be fed through decode to produce the
next-token logits, and that feed WRITES K/V at ``plen-1`` — a position
inside the last shared block. The cache never lets a sequence write a
shared block: admission copies that block into a fresh one (the warmed
``cow`` program), repoints the table entry, and drops the reference on the
original. Divergent continuations after a shared prefix never COW — their
first write lands at ``matched_tokens``, which is always the first
UNSHARED table entry by construction.

Lifecycle: a block's refcount counts live sequences referencing it (the
registering owner included). At refcount 0 a cached block is NOT freed —
it parks in an LRU so the next identical prompt still hits; eviction runs
only under pool pressure (oldest first, refcount-0 only, descendants
evicted with their parent — a child can never out-ref its parent because
every sequence that matched the child matched the whole chain), and only
then does ``BlockPoolExhaustedError`` fire. Cohort-scoped: a prefix cache
lives and dies with its cohort's pool, so hot-swap can never serve K/V
computed under old params to a new-params sequence.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from .kvcache import BlockAllocator


def _block_hashes(prompt: np.ndarray, block_len: int) -> List[bytes]:
    """Rolling chain hashes for every FULL block of ``prompt``."""
    n_full = len(prompt) // block_len
    out: List[bytes] = []
    h = b""
    for i in range(n_full):
        blk = np.ascontiguousarray(
            prompt[i * block_len:(i + 1) * block_len], dtype=np.int32)
        h = hashlib.blake2b(h + blk.tobytes(), digest_size=16).digest()
        out.append(h)
    return out


class _Entry:
    __slots__ = ("block", "parent", "children")

    def __init__(self, block: int, parent: Optional[bytes]):
        self.block = block
        self.parent = parent
        self.children: Set[bytes] = set()


class PrefixCache:
    """Hash-chain index + refcounts + LRU over ONE cohort's block pool.

    Single-threaded by contract (the scheduler's dispatch thread owns it,
    exactly like the allocator)."""

    def __init__(self, allocator: BlockAllocator, block_len: int):
        self.allocator = allocator
        self.block_len = int(block_len)
        self._entries: Dict[bytes, _Entry] = {}
        self._by_block: Dict[int, bytes] = {}
        # refcount-0 cached blocks, oldest-first (move_to_end on touch)
        self._lru: "OrderedDict[bytes, None]" = OrderedDict()
        # stats (mirrored into GenerationMetrics by the scheduler)
        self.hits = 0
        self.misses = 0
        self.tokens_matched = 0
        self.cow_copies = 0
        self.evictions = 0

    # ------------------------------------------------------------- queries
    @property
    def cached_blocks(self) -> int:
        return len(self._entries)

    @property
    def lru_blocks(self) -> int:
        return len(self._lru)

    @property
    def shared_blocks(self) -> int:
        """Cached blocks currently referenced by at least one live
        sequence."""
        return len(self._entries) - len(self._lru)

    def cached_block_ids(self) -> Set[int]:
        return set(self._by_block)

    def probe(self, prompt: np.ndarray) -> int:
        """Longest cached prefix in BLOCKS, without taking references."""
        n = 0
        for h in _block_hashes(prompt, self.block_len):
            if h not in self._entries:
                break
            n += 1
        return n

    def evictable_for(self, prompt: np.ndarray) -> int:
        """LRU blocks evictable to serve THIS prompt's admission: blocks
        the prompt would match don't count — reviving them is the point."""
        matched = 0
        for h in _block_hashes(prompt, self.block_len):
            e = self._entries.get(h)
            if e is None:
                break
            if h in self._lru:
                matched += 1
        return len(self._lru) - matched

    # ----------------------------------------------------------- admission
    def match(self, prompt: np.ndarray) -> Tuple[List[int], int]:
        """Take references on the longest cached prefix. Returns
        (shared_block_ids, matched_token_count); refcount-0 matches are
        revived out of the LRU. Records the hit/miss stat."""
        shared: List[int] = []
        for h in _block_hashes(prompt, self.block_len):
            e = self._entries.get(h)
            if e is None:
                break
            self._lru.pop(h, None)
            self.allocator.incref(e.block)
            shared.append(e.block)
        if shared:
            self.hits += 1
            self.tokens_matched += len(shared) * self.block_len
        else:
            self.misses += 1
        return shared, len(shared) * self.block_len

    def release(self, block_ids: List[int]) -> None:
        """Drop one reference per block; blocks reaching refcount 0 park in
        the LRU (still allocated — only eviction frees them)."""
        for b in block_ids:
            if self.allocator.decref(b) == 0:
                h = self._by_block.get(b)
                if h is not None:
                    self._lru[h] = None
                    self._lru.move_to_end(h)
                else:       # unregistered share (COW'd original, raced reg)
                    self.allocator.free([b])

    def register(self, prompt: np.ndarray, table_row: np.ndarray,
                 owned: List[int]) -> List[int]:
        """After a prefill (or replay) completes, index the prompt's full
        blocks. Blocks newly registered move from the caller's ``owned``
        set to cache custody (refcount 1 for the live owner); blocks whose
        hash is already cached stay owned by the caller (same-batch
        duplicate prompts). Returns the block ids now cache-managed that
        the caller must release() instead of free()."""
        owned_set = set(owned)
        managed: List[int] = []
        parent: Optional[bytes] = None
        for i, h in enumerate(_block_hashes(prompt, self.block_len)):
            blk = int(table_row[i])
            e = self._entries.get(h)
            if e is not None:
                parent = h
                continue
            if blk not in owned_set:
                # this table entry is a shared block from admission (its
                # hash is cached under possibly-evicted ancestry) — never
                # steal custody of a block the caller doesn't own
                parent = h
                continue
            e = _Entry(blk, parent)
            self._entries[h] = e
            self._by_block[blk] = h
            if parent is not None and parent in self._entries:
                self._entries[parent].children.add(h)
            self.allocator.incref(blk)
            owned_set.discard(blk)
            managed.append(blk)
            parent = h
        return managed

    # ------------------------------------------------------------ eviction
    def ensure_free(self, n: int) -> int:
        """Evict oldest refcount-0 cached blocks until the allocator has
        ``n`` free blocks (descendant chains go with their parent). Returns
        blocks evicted; the caller decides whether a shortfall is
        BlockPoolExhaustedError."""
        evicted = 0
        while self.allocator.free_blocks < n and self._lru:
            h = next(iter(self._lru))
            evicted += self._evict_chain(h)
        return evicted

    def _evict_chain(self, h: bytes) -> int:
        e = self._entries.get(h)
        if e is None:
            return 0
        n = 0
        # children first (all refcount-0 by the chain-refcount invariant)
        for child in list(e.children):
            n += self._evict_chain(child)
        del self._entries[h]
        del self._by_block[e.block]
        self._lru.pop(h, None)
        if e.parent is not None and e.parent in self._entries:
            self._entries[e.parent].children.discard(h)
        self.allocator.free([e.block])
        self.evictions += 1
        n += 1
        return n

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
            "tokens_matched": self.tokens_matched,
            "cow_copies": self.cow_copies,
            "evictions": self.evictions,
            "cached_blocks": self.cached_blocks,
            "cached_lru_blocks": self.lru_blocks,
            "shared_blocks": self.shared_blocks,
        }
