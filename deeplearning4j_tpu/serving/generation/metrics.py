"""Generation observability: per-model token/latency/occupancy counters.

Same contract as ``serving.metrics.ServingMetrics`` — a local snapshot dict
(the ``GET /metrics`` payload) with every recording mirrored into the shared
telemetry registry under ``generation.<model>.*`` so training, forward
serving and decode land on ONE reporting surface. Adds the decode-specific
signals: time-to-first-token, per-decode-step latency, per-user streaming
rate, slot occupancy, block-pool usage, and the decode loop's own
recompile count (the RecompileDetector the scheduler keeps armed after
warm-up).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict

from ...telemetry import get_registry
from ...telemetry.registry import _percentile


class GenerationMetrics:
    def __init__(self, window: int = 4096, name: str = "default",
                 registry=None):
        self._lock = threading.Lock()
        self.name = name
        self._registry = registry
        self._ttft_ms = deque(maxlen=window)
        self._step_ms = deque(maxlen=window)
        self._tok_t = deque(maxlen=window)       # emission timestamps
        self.requests = 0
        self.tokens_out = 0
        self.prefills = 0
        self.prefill_rows = 0
        self.decode_steps = 0
        self.decode_slot_steps = 0              # active slots summed per step
        self.finished: Dict[str, int] = {}
        self.rejected: Dict[str, int] = {"full": 0, "exhausted": 0,
                                         "draining": 0, "deadline": 0,
                                         "error": 0}
        self.swaps = 0
        self.decode_recompiles = 0
        self.slots = 0
        self.blocks_total = 0
        self.kv_bytes_per_token = None          # quantized-KV tier (ISSUE 17)
        # prefix-cache economics (ISSUE 14)
        self._ttft_cached_ms = deque(maxlen=window)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_saved = 0
        self.cow_copies = 0
        self.prefix_evictions = 0
        self._prefix_gauges: dict = {}
        # speculative decoding
        self._verify_ms = deque(maxlen=window)
        self.verify_steps = 0
        self.verify_slot_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self._t0 = time.monotonic()
        self._rate_t = self._t0

    @property
    def registry(self):
        return self._registry if self._registry is not None else get_registry()

    # ------------------------------------------------------------- recording
    def record_request(self) -> None:
        with self._lock:
            self.requests += 1
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.requests").inc()

    def record_prefill(self, rows: int, ttft_ms_per_row,
                       emitted: int = 0) -> None:
        now = time.monotonic()
        with self._lock:
            self.prefills += 1
            self.prefill_rows += rows
            self._ttft_ms.extend(ttft_ms_per_row)
            self.tokens_out += emitted          # each row's FIRST token
            self._tok_t.extend([now] * emitted)
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.prefills").inc()
            if emitted:
                reg.counter(
                    f"generation.{self.name}.tokens_out").inc(emitted)
            h = reg.histogram(f"generation.{self.name}.ttft_ms")
            for v in ttft_ms_per_row:
                h.observe(v)

    def record_decode_step(self, step_ms: float, active_slots: int,
                           emitted: int, *, slots: int,
                           blocks_used: int, blocks_total: int,
                           queue_depth: int) -> None:
        now = time.monotonic()
        with self._lock:
            self.decode_steps += 1
            self.decode_slot_steps += active_slots
            self.tokens_out += emitted
            self._step_ms.append(step_ms)
            self._tok_t.extend([now] * emitted)
            self.slots = slots
            self.blocks_total = blocks_total
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.decode_steps").inc()
            reg.counter(f"generation.{self.name}.tokens_out").inc(emitted)
            reg.histogram(
                f"generation.{self.name}.decode_step_ms").observe(step_ms)
            reg.gauge(f"generation.{self.name}.slot_occupancy").set(
                active_slots / slots if slots else 0.0)
            reg.gauge(f"generation.{self.name}.blocks_in_use").set(
                blocks_used)
            reg.gauge(f"generation.{self.name}.queue_depth").set(queue_depth)
            # throttled: the rate scan over the timestamp ring is not free
            # and the decode step is the serving hot loop
            if now - self._rate_t >= 0.5:
                self._rate_t = now
                reg.gauge(f"generation.{self.name}.tokens_per_sec").set(
                    self._recent_tokens_per_sec(now))

    # -------------------------------------------------- prefix cache (hits)
    def record_prefix_hit(self, tokens_saved: int) -> None:
        with self._lock:
            self.prefix_hits += 1
            self.prefix_tokens_saved += tokens_saved
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.prefix.hits").inc()
            if tokens_saved:
                reg.counter(
                    f"generation.{self.name}.prefix.tokens_saved").inc(
                    tokens_saved)
            self._hit_rate_gauge(reg)

    def record_prefix_miss(self) -> None:
        with self._lock:
            self.prefix_misses += 1
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.prefix.misses").inc()
            self._hit_rate_gauge(reg)

    def _hit_rate_gauge(self, reg) -> None:
        total = self.prefix_hits + self.prefix_misses
        if total:
            reg.gauge(f"generation.{self.name}.prefix_hit_rate").set(
                round(self.prefix_hits / total, 4))

    def record_cow(self) -> None:
        with self._lock:
            self.cow_copies += 1
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.prefix.cow_copies").inc()

    def record_prefix_evictions(self, n: int) -> None:
        with self._lock:
            self.prefix_evictions += n
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.prefix.evictions").inc(n)

    def record_cached_first_token(self, ttft_ms: float) -> None:
        """TTFT for a cache-hit admission (prefill skipped; first token
        fell out of the replay's final decode step)."""
        with self._lock:
            self._ttft_cached_ms.append(ttft_ms)
        reg = self.registry
        if reg.enabled:
            reg.histogram(
                f"generation.{self.name}.ttft_cached_ms").observe(ttft_ms)

    def set_prefix_gauges(self, stats: dict) -> None:
        """Mirror the active cohort's block-pool economics (shared blocks,
        cached-LRU size) — the /metrics 'prefix' gauges."""
        with self._lock:
            self._prefix_gauges = dict(stats)
        reg = self.registry
        if reg.enabled:
            reg.gauge(f"generation.{self.name}.prefix.shared_blocks").set(
                stats.get("shared_blocks", 0))
            reg.gauge(
                f"generation.{self.name}.prefix.cached_lru_blocks").set(
                stats.get("cached_lru_blocks", 0))

    # ------------------------------------------------- speculative decoding
    def record_verify(self, step_ms: float, active_slots: int, *,
                      proposed: int, accepted: int, emitted: int,
                      slots: int, blocks_used: int, blocks_total: int,
                      queue_depth: int) -> None:
        """One draft-propose + verify window: ``accepted`` draft tokens
        matched the target's greedy choice; ``emitted`` includes each
        slot's correction token (the per-target-dispatch yield)."""
        now = time.monotonic()
        with self._lock:
            self.verify_steps += 1
            self.verify_slot_steps += active_slots
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            self.spec_emitted += emitted
            self.tokens_out += emitted
            # verify windows are k+1-token passes — kept OUT of the
            # one-token decode_step_ms population (own percentiles below)
            self._verify_ms.append(step_ms)
            self._tok_t.extend([now] * emitted)
            self.slots = slots
            self.blocks_total = blocks_total
            per_verify = (self.spec_emitted / self.verify_slot_steps
                          if self.verify_slot_steps else 0.0)
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.spec.verify_steps").inc()
            reg.counter(f"generation.{self.name}.spec.proposed").inc(proposed)
            reg.counter(f"generation.{self.name}.spec.accepted").inc(accepted)
            reg.counter(f"generation.{self.name}.tokens_out").inc(emitted)
            reg.histogram(
                f"generation.{self.name}.verify_step_ms").observe(step_ms)
            reg.gauge(
                f"generation.{self.name}.spec.accepted_per_verify").set(
                round(per_verify, 3))
            reg.gauge(f"generation.{self.name}.slot_occupancy").set(
                active_slots / slots if slots else 0.0)
            reg.gauge(f"generation.{self.name}.blocks_in_use").set(
                blocks_used)
            reg.gauge(f"generation.{self.name}.queue_depth").set(queue_depth)
            if now - self._rate_t >= 0.5:
                self._rate_t = now
                reg.gauge(f"generation.{self.name}.tokens_per_sec").set(
                    self._recent_tokens_per_sec(now))

    def record_finish(self, reason: str) -> None:
        with self._lock:
            self.finished[reason] = self.finished.get(reason, 0) + 1
        reg = self.registry
        if reg.enabled:
            reg.counter(
                f"generation.{self.name}.finished.{reason}").inc()

    def record_rejection(self, kind: str) -> None:
        with self._lock:
            self.rejected[kind] = self.rejected.get(kind, 0) + 1
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.rejected.{kind}").inc()

    def record_swap(self) -> None:
        with self._lock:
            self.swaps += 1
        reg = self.registry
        if reg.enabled:
            reg.counter(f"generation.{self.name}.hot_swaps").inc()

    def record_recompile(self, n: int) -> None:
        with self._lock:
            self.decode_recompiles = n

    def set_kv_bytes_per_token(self, v) -> None:
        """Block-pool bytes per token slot (the quantized-KV capacity
        currency); None (state adapter) publishes nothing."""
        if v is None:
            return
        with self._lock:
            self.kv_bytes_per_token = float(v)
        reg = self.registry
        if reg.enabled:
            reg.gauge(f"generation.{self.name}.kv_bytes_per_token").set(
                float(v))

    def _recent_tokens_per_sec(self, now: float, window_s: float = 5.0):
        if not self._tok_t:
            return 0.0
        cut = now - window_s
        # the ring is count-bounded: at high rates it evicts timestamps
        # still inside the window — measure over the span actually
        # retained, or the gauge saturates at maxlen/window_s
        oldest = self._tok_t[0]
        if oldest > cut:                       # evicted inside the window
            cut = oldest
            span = max(now - cut, 1e-3)
        else:
            span = max(min(window_s, now - self._t0), 1e-3)
        n = 0
        for t in reversed(self._tok_t):
            if t < cut:
                break
            n += 1
        return round(n / span, 2)

    # ------------------------------------------------------------- reporting
    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            ttft = sorted(self._ttft_ms)
            ttft_c = sorted(self._ttft_cached_ms)
            step = sorted(self._step_ms)
            verify = sorted(self._verify_ms)
            # occupancy over BOTH step kinds: a speculation-saturated
            # engine advances slots through verify windows, not plain
            # decode steps — counting only the latter read near-zero
            # under full load
            steps_all = self.decode_steps + self.verify_steps
            occ = ((self.decode_slot_steps + self.verify_slot_steps)
                   / (steps_all * self.slots)
                   if steps_all and self.slots else 0.0)
            lookups = self.prefix_hits + self.prefix_misses
            out = {
                "requests": self.requests,
                "tokens_out": self.tokens_out,
                "prefills": self.prefills,
                "prefill_rows": self.prefill_rows,
                "decode_steps": self.decode_steps,
                "ttft_ms": {"p50": round(_percentile(ttft, 0.50), 3),
                            "p99": round(_percentile(ttft, 0.99), 3)},
                "decode_step_ms": {"p50": round(_percentile(step, 0.50), 3),
                                   "p99": round(_percentile(step, 0.99), 3)},
                "slot_occupancy": round(occ, 4),
                "tokens_per_sec_recent": self._recent_tokens_per_sec(now),
                "finished": dict(self.finished),
                "rejected": dict(self.rejected),
                "hot_swaps": self.swaps,
                "decode_recompiles": self.decode_recompiles,
                "kv_bytes_per_token": self.kv_bytes_per_token,
                "uptime_s": round(now - self._t0, 1),
                # block-pool economics: who is sharing, what the cache
                # holds, what COW and eviction cost
                "prefix": {
                    "hits": self.prefix_hits,
                    "misses": self.prefix_misses,
                    "hit_rate": (round(self.prefix_hits / lookups, 4)
                                 if lookups else 0.0),
                    "tokens_saved": self.prefix_tokens_saved,
                    "cow_copies": self.cow_copies,
                    "evictions": self.prefix_evictions,
                    "shared_blocks": self._prefix_gauges.get(
                        "shared_blocks", 0),
                    "cached_lru_blocks": self._prefix_gauges.get(
                        "cached_lru_blocks", 0),
                    "cached_blocks": self._prefix_gauges.get(
                        "cached_blocks", 0),
                    "ttft_cached_ms": {
                        "p50": round(_percentile(ttft_c, 0.50), 3),
                        "p99": round(_percentile(ttft_c, 0.99), 3)},
                },
                "speculative": {
                    "verify_steps": self.verify_steps,
                    "verify_step_ms": {
                        "p50": round(_percentile(verify, 0.50), 3),
                        "p99": round(_percentile(verify, 0.99), 3)},
                    "proposed": self.spec_proposed,
                    "accepted": self.spec_accepted,
                    "emitted": self.spec_emitted,
                    "accepted_tokens_per_verify": (
                        round(self.spec_emitted / self.verify_slot_steps, 3)
                        if self.verify_slot_steps else 0.0),
                    "proposals_accepted_per_verify": (
                        round(self.spec_accepted / self.verify_slot_steps, 3)
                        if self.verify_slot_steps else 0.0),
                },
            }
            return out

    def publish(self, storage, session_id: str = "generation",
                worker_id: str = "default") -> dict:
        snap = self.snapshot()
        storage.put_update(session_id, worker_id, snap)
        return snap
