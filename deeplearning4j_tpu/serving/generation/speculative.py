"""Speculative decoding: draft-propose k tokens, verify in ONE target pass.

Plain continuous batching pays one full target-model program dispatch per
emitted token per slot. Speculative decoding buys several: a cheap DRAFT
model proposes ``k`` greedy continuations per slot, then the target runs a
single batched VERIFY window over ``[cur, p_1..p_k]`` (one program, W=k+1
positions via ``models.decode.decode_window`` + ``PagedWindowStore``) and
the scheduler accepts the longest prefix where the draft agreed with the
target's own greedy choice, plus the target's correction token at the
first disagreement. Because every accepted token IS the token plain greedy
decode would have produced (row ``i`` of the verify window sees exactly
the context one-token decode at ``pos+i`` sees), the output stream is
token-for-token identical to plain greedy decode — speculation changes the
SCHEDULE, never the tokens.

Two draft adapters, both AOT-warmed in ``GenerationProgramSet`` beside the
prefill/decode programs and cohort-pinned across hot-swap:

- ``dense``  — a (truncated) transformer draft with a fixed dense per-slot
  KV cache ``[layers, slots+1, capacity, H, Dh]`` (no paging: the draft is
  small, and a dense cache makes rewind FREE — rejected proposals' K/V are
  overwritten before any later mask can see them, so rollback is just not
  advancing ``pos``).
- ``state``  — an LSTM draft whose cache is the recurrent state. Recurrent
  state can't un-consume a token, so the propose scan stacks the state
  after EVERY fed token and a tiny rewind program gathers, per slot, the
  state matching what the verify actually accepted.

The draft proposes nothing when disabled or for sampling (temperature > 0)
requests — those ride the plain decode path unchanged.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------- dense store
class DenseDraftStore:
    """``models.decode.KVStore`` over the draft's dense per-slot cache for
    one propose step: row ``s`` writes position ``pos[s]``, inactive slots
    (and positions past capacity) write the trash row."""

    def __init__(self, k_cache, v_cache, pos, active):
        # k_cache/v_cache: [Ld, S+1, cap, H, Dh]; row S is trash
        self.k_cache = k_cache
        self.v_cache = v_cache
        S = pos.shape[0]
        cap = k_cache.shape[2]
        ok = active & (pos < cap)
        self._row = jnp.where(ok, jnp.arange(S), S)
        self._off = jnp.where(ok, pos, 0)
        self._mask = (jnp.arange(cap)[None, :] <= pos[:, None])

    def put_get(self, i: int, k_tok, v_tok):
        self.k_cache = self.k_cache.at[i, self._row, self._off].set(k_tok)
        self.v_cache = self.v_cache.at[i, self._row, self._off].set(v_tok)
        S = k_tok.shape[0]
        K = self.k_cache[i, :S].transpose(0, 2, 1, 3)   # [S,H,cap,Dh]
        V = self.v_cache[i, :S].transpose(0, 2, 1, 3)
        return K, V, self._mask

    @property
    def caches(self):
        return self.k_cache, self.v_cache


def make_dense_draft_cache(draft_spec, slots: int, capacity: int):
    """Zero-filled (k_cache, v_cache) for the dense draft adapter."""
    shape = (draft_spec.n_blocks, slots + 1, capacity,
             draft_spec.n_heads, draft_spec.head_dim)
    return (jnp.zeros(shape, draft_spec.dtype),
            jnp.zeros(shape, draft_spec.dtype))


# --------------------------------------------------------- program builders
def draft_prefill_dense_fn(draft_spec):
    """(params, state, (kc, vc), tokens [P,L], slots [P]) -> cache' —
    the draft's full-prompt prefill, rows scattered at ``slots`` (padding
    rows at the trash row)."""
    def fn(params, state, cache, tokens, slots):
        kc, vc = cache
        _, ks, vs = draft_spec.prefill_forward(params, state, tokens)
        L = tokens.shape[1]
        for i in range(draft_spec.n_blocks):
            kc = kc.at[i, slots, :L].set(ks[i])
            vc = vc.at[i, slots, :L].set(vs[i])
        return kc, vc
    return fn


def draft_prefill_state_fn(draft_spec):
    """(params, state, states_all, tokens [P,L], lengths [P], slots [P])
    -> states_all' — masked-scan prefill, final states landed at slots."""
    def fn(params, state, states_all, tokens, lengths, slots):
        P = tokens.shape[0]
        zero = jax.tree.map(
            lambda c: jnp.zeros((P,) + c.shape[1:], c.dtype), states_all)
        _, final = draft_spec.prefill_scan(params, state, tokens, lengths,
                                           zero)
        return jax.tree.map(lambda c, n: c.at[slots].set(n), states_all,
                            final)
    return fn


def propose_dense_fn(draft_spec, k: int):
    """(params, state, (kc, vc), cur [S], pos [S], active [S]) ->
    (proposals [S,k], cache'). Greedy chain: feed cur at pos -> p_1, feed
    p_1 -> p_2, ... The scan runs k+1 feeds (through p_k, whose K/V lands
    at pos+k) so a fully-accepted window leaves NO unwritten gap behind
    the next round's base position; rejected positions' K/V are
    overwritten next round before any mask can see them, so no rewind
    state is needed."""
    def fn(params, state, cache, cur, pos, active):
        kc, vc = cache

        def step(carry, _):
            kc, vc, tok, p = carry
            store = DenseDraftStore(kc, vc, p, active)
            logits = draft_spec.decode_step(params, state, tok, p, store)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            kc, vc = store.caches
            return (kc, vc, nxt, p + 1), nxt

        (kc, vc, _, _), toks = jax.lax.scan(
            step, (kc, vc, cur, pos), None, length=k + 1)
        return toks[:k].T, (kc, vc)                   # [S,k]
    return fn


def propose_state_fn(draft_spec, k: int):
    """(params, state, states_all, cur [S]) -> (proposals [S,k],
    states_stack). The scan feeds k+1 tokens (cur, p_1..p_k) so the stack
    s_1..s_{k+1} covers every possible rewind target — s_{j+1} is the
    state after consuming the j-th accepted proposal."""
    def fn(params, state, states_all, cur):
        S = cur.shape[0]
        st = jax.tree.map(lambda c: c[:S], states_all)

        def step(carry, _):
            st, tok = carry
            logits, st2 = draft_spec.decode_step(params, state, tok, st)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (st2, nxt), (nxt, st2)

        _, (toks, stack) = jax.lax.scan(step, (st, cur), None, length=k + 1)
        return toks[:k].T, stack                      # [S,k], [k+1,S,...]
    return fn


def rewind_state_fn():
    """(states_all, stack, idx [S] in 1..k+1, mask [S]) -> states_all' —
    per-slot gather of the post-acceptance draft state; masked-off slots
    (finished, sampling, inactive) keep their state."""
    def fn(states_all, stack, idx, mask):
        S = idx.shape[0]
        rows = jnp.arange(S)
        sel = jax.tree.map(lambda st: st[idx - 1, rows], stack)

        def merge(all_, s):
            keep = mask.reshape((S,) + (1,) * (s.ndim - 1))
            return jnp.concatenate(
                [jnp.where(keep, s, all_[:S]), all_[S:]], axis=0)

        return jax.tree.map(merge, states_all, sel)
    return fn


def verify_fn(target_spec, block_len: int, k: int):
    """(params, state, cache, feeds [S,k+1], pos [S], tables, active) ->
    (greedy targets [S,k+1], cache'). One batched target pass over the
    verify window; row i's greedy argmax is EXACTLY what one-token decode
    at pos+i would emit."""
    from .kvcache import PagedWindowStore

    def fn(params, state, cache, feeds, pos, tables, active):
        store = PagedWindowStore(cache[0], cache[1], tables, pos, active,
                                 block_len, k + 1)
        logits = target_spec.decode_window(params, state, feeds, pos, store)
        targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return targets, store.pools
    return fn


# ----------------------------------------------------------- host-side rule
def accept_greedy(proposals: np.ndarray,
                  targets: np.ndarray) -> Tuple[np.ndarray, List[List[int]]]:
    """The exact-output acceptance rule. ``proposals`` [S,k] (draft),
    ``targets`` [S,k+1] (target greedy per window row). Returns
    (accepted_counts [S], emitted token lists): slot s emits its accepted
    proposals plus the target's correction token at the first disagreement
    — 1..k+1 tokens, each identical to what plain greedy decode emits."""
    S, k = proposals.shape
    agree = proposals == targets[:, :k]
    counts = np.where(agree.all(axis=1), k,
                      np.argmin(agree, axis=1)).astype(np.int64)
    emitted = [list(proposals[s, :counts[s]]) + [int(targets[s, counts[s]])]
               for s in range(S)]
    return counts, emitted
