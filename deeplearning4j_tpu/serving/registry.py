"""Multi-model registry: named, versioned serving entries.

Models load from the training side's own persistence formats — a model zip
(util/serialization.restore_model, which also sniffs reference-format DL4J
zips) or a util/checkpointing checkpoint directory (newest
``checkpoint_epoch{N}.zip`` wins) — so the path from `fit` to serving is
the artifacts that already exist, not a new export step.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from .errors import UnknownModelError


def load_net(path: str):
    """Restore a network from a model zip OR a checkpoint directory."""
    if os.path.isdir(path):
        from ..util.checkpointing import latest_checkpoint
        ckpt = latest_checkpoint(path)
        if ckpt is None:
            raise FileNotFoundError(f"no checkpoint_epoch*.zip in {path}")
        path = ckpt
    from ..util.serialization import restore_model
    return restore_model(path)


class _Entry:
    """One served model: its batcher + atomically-swappable program set.
    ``active`` is replaced by reference assignment (atomic in CPython);
    in-flight batches keep the set they snapshotted at dispatch."""

    def __init__(self, name: str, program_set, batcher, metrics):
        self.name = name
        self.active = program_set
        self.batcher = batcher
        self.metrics = metrics
        self.version = 1
        self.swap_lock = threading.Lock()   # serializes swaps, not serving

    def info(self) -> dict:
        ps = self.active
        return {"name": self.name, "version": self.version,
                "buckets": list(ps.ladder.rungs),
                "feature_shape": list(ps.feature_shape),
                "dtype": str(ps.dtype), "warmed": ps.warmed,
                "sharded": ps.mesh is not None,
                "queue_depth": self.batcher.queue_depth,
                "draining": self.batcher.draining}


class ModelRegistry:
    def __init__(self):
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self.default_name: Optional[str] = None

    def add(self, entry: _Entry, default: bool = False) -> None:
        with self._lock:
            if entry.name in self._entries:
                raise ValueError(f"model '{entry.name}' already registered "
                                 "(use hot_swap to replace)")
            self._entries[entry.name] = entry
            if default or self.default_name is None:
                self.default_name = entry.name

    def get(self, name: Optional[str] = None) -> _Entry:
        with self._lock:
            name = name or self.default_name
            if name is None or name not in self._entries:
                raise UnknownModelError(f"unknown model '{name}'; "
                                        f"registered: {sorted(self._entries)}")
            return self._entries[name]

    def remove(self, name: str) -> _Entry:
        with self._lock:
            if name not in self._entries:
                raise UnknownModelError(f"unknown model '{name}'")
            entry = self._entries.pop(name)
            if self.default_name == name:
                self.default_name = next(iter(sorted(self._entries)), None)
            return entry

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def entries(self) -> List[_Entry]:
        with self._lock:
            return list(self._entries.values())
