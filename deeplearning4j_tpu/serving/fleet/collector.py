"""FleetCollector: cross-process trace stitching + metrics aggregation.

PR 13's observability is per-process; the fleet fragments it across N
replica OS processes. The collector is the supervisor-side half that
puts it back together:

- **Trace stitching** — incremental pulls of every replica's trace ring
  over the pooled :class:`~...util.httpjson.HTTPClient`
  (``GET /debug/trace?since_seq=<cursor>``: each replica ships only the
  delta past the collector's watermark), every event stamped with
  ``args.replica`` for attribution. Span timestamps are epoch-anchored
  (telemetry/spans.py ``_EPOCH_NS``), so merging by ``ts`` across
  processes yields a true end-to-end timeline: front-door ingress,
  ``fleet.route`` events, replica ``generation.*`` spans — one request,
  one trace id, one chronology.
- **Black-box recovery** — a DEAD replica cannot answer a pull; its
  last :class:`~..telemetry.spool.TraceSpool` spill is ingested instead
  (events past the cursor only), so a SIGKILLed replica's final spans
  still appear in stitched timelines.
- **Honest aggregation** — per-replica ``/debug/metrics`` raws carry
  cumulative ``le`` buckets, merged by elementwise sum on ONE canonical
  bucket ladder (mismatched ladders raise
  :class:`~..telemetry.registry.HistogramLadderMismatch` — loudly, per
  the merge-correctness pin). Fleet p99 is read off the merged buckets
  (:func:`~..telemetry.registry.bucket_quantile`), never an average of
  per-replica percentiles.
- **Fleet SLOs** — :meth:`FleetCollector.aggregate_registry` is a
  registry-shaped view over the merged data (reads aggregate, writes
  land in the front door's local registry), so the existing
  ``SLOWatchdog`` burn-rate/breach-edge/flight-dump machinery runs
  unmodified at fleet level; :meth:`make_watchdog` wires it, and the
  autoscaler's ``slo_breached`` input reads it.
"""
from __future__ import annotations

import threading
from bisect import bisect_left
from collections import deque
from typing import Dict, List, Optional

from ...telemetry import get_registry
from ...telemetry.registry import (MetricsRegistry, bucket_quantile,
                                   escape_label_value,
                                   merge_cumulative_buckets,
                                   sanitize_metric_name)
from ...telemetry.slo import SLOWatchdog
from ...telemetry.spool import read_spool
from ...telemetry.tracecontext import normalize_trace_id
from ...util.httpjson import HTTPClient

__all__ = ["FleetCollector", "AggregateRegistry",
           "merge_raw_metrics"]

# the replica label the collector stamps on the supervisor process's own
# events (front-door admission spans, fleet.route markers)
FRONT_DOOR = "front"


def merge_raw_metrics(raws: Dict[str, dict]) -> dict:
    """Fold per-replica ``raw_metrics()`` dicts into fleet aggregates:
    counters summed, histograms merged by elementwise-summed cumulative
    ``le`` buckets (one canonical ladder enforced — mismatches raise),
    gauges kept per-replica (a last-write-wins value has no honest
    fleet-wide sum; consumers read them labelled)."""
    counters: Dict[str, float] = {}
    hists: Dict[str, dict] = {}
    for rid in sorted(raws):
        raw = raws[rid] or {}
        for n, v in (raw.get("counters") or {}).items():
            counters[n] = counters.get(n, 0) + v
        for n, h in (raw.get("histograms") or {}).items():
            bounds = list(h.get("bounds") or ())
            agg = hists.get(n)
            if agg is None:
                hists[n] = {"bounds": bounds,
                            "cumulative": merge_cumulative_buckets(
                                bounds, [h.get("cumulative") or []]),
                            "count": int(h.get("count", 0)),
                            "sum": float(h.get("sum", 0.0))}
                continue
            if bounds != agg["bounds"]:
                from ...telemetry.registry import HistogramLadderMismatch
                raise HistogramLadderMismatch(
                    f"histogram {n!r}: replica {rid!r} observes on ladder "
                    f"{bounds} but the fleet ladder is {agg['bounds']} — "
                    "pin one canonical bucket ladder fleet-wide")
            agg["cumulative"] = merge_cumulative_buckets(
                agg["bounds"], [agg["cumulative"],
                                h.get("cumulative") or []])
            agg["count"] += int(h.get("count", 0))
            agg["sum"] += float(h.get("sum", 0.0))
    return {"counters": counters, "histograms": hists,
            "replicas": sorted(raws)}


# --------------------------------------------------- registry-shaped view
class _AggregateHistogram:
    """Read side of one merged histogram; the SLOWatchdog's LatencySLO
    reads ``count_le_and_total`` exactly like a local Histogram."""

    def __init__(self, collector: "FleetCollector", name: str):
        self._collector = collector
        self.name = name

    def _merged(self) -> dict:
        return self._collector.merged_histogram(self.name)

    @property
    def bounds(self) -> tuple:
        return tuple(self._merged()["bounds"])

    @property
    def count(self) -> int:
        return self._merged()["count"]

    @property
    def sum(self) -> float:
        return self._merged()["sum"]

    def cumulative_buckets(self) -> List[int]:
        return list(self._merged()["cumulative"])

    def count_le_and_total(self, threshold: float):
        m = self._merged()
        bounds, cum = m["bounds"], m["cumulative"]
        if not bounds:
            return 0, 0
        idx = bisect_left(bounds, float(threshold))
        total = cum[-1] if cum else 0
        return (cum[idx] if idx < len(cum) else total), total

    def count_le(self, threshold: float) -> int:
        return self.count_le_and_total(threshold)[0]

    def count_and_sum(self):
        m = self._merged()
        return m["count"], m["sum"]

    def percentiles(self) -> Dict[str, float]:
        m = self._merged()
        return {k: bucket_quantile(m["bounds"], m["cumulative"], q)
                for k, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))}

    def observe(self, v: float) -> None:
        # writes land locally: the front door's own latency samples merge
        # back in through merged_histogram's local fold
        self._collector.local_registry.histogram(self.name).observe(v)

    def stats(self) -> Dict[str, float]:
        m = self._merged()
        p = self.percentiles()
        p["count"] = m["count"]
        p["sum"] = round(m["sum"], 6)
        p["mean"] = m["sum"] / m["count"] if m["count"] else 0.0
        return p


class _AggregateCounter:
    __slots__ = ("_collector", "name")

    def __init__(self, collector: "FleetCollector", name: str):
        self._collector = collector
        self.name = name

    @property
    def value(self):
        agg = self._collector.aggregate()["counters"].get(self.name, 0)
        local = self._collector.local_registry._counters.get(self.name)
        return agg + (local.value if local is not None else 0)

    def inc(self, n: int = 1) -> None:
        self._collector.local_registry.counter(self.name).inc(n)


class AggregateRegistry:
    """Registry-shaped facade over the collector's merged metrics.

    Reads (histogram buckets, counter values) come from the fleet
    aggregate — every replica plus the front door's local registry;
    writes (the watchdog's ``slo.*`` gauges, breach counters) go to the
    local registry, so they surface on the front door's own scrape and
    dashboard. This is the seam that lets ``SLOWatchdog`` run at fleet
    level without a single changed line in slo.py."""

    def __init__(self, collector: "FleetCollector"):
        self._collector = collector

    @property
    def enabled(self) -> bool:
        return self._collector.local_registry.enabled

    def histogram(self, name: str) -> _AggregateHistogram:
        return _AggregateHistogram(self._collector, name)

    def histogram_if_exists(self, name: str):
        if name in self._collector.aggregate()["histograms"] or \
                self._collector.local_registry.histogram_if_exists(name) \
                is not None:
            return _AggregateHistogram(self._collector, name)
        return None

    def counter(self, name: str) -> _AggregateCounter:
        return _AggregateCounter(self._collector, name)

    def gauge(self, name: str):
        return self._collector.local_registry.gauge(name)

    def gauge_if_exists(self, name: str):
        return self._collector.local_registry.gauge_if_exists(name)

    def gauges_matching(self, prefix: str, suffix: str = ""):
        return self._collector.local_registry.gauges_matching(prefix,
                                                              suffix)


# --------------------------------------------------------------- collector
class FleetCollector:
    """Incremental puller + merger for one FleetRouter's replicas.

        collector = FleetCollector(router).start()
        events = collector.events_for_trace(trace_id)
        wd = collector.make_watchdog([LatencySLO(...)])

    ``capacity_per_replica`` bounds the stitched-event memory per
    replica (a deque — old spans age out, the bound is the contract).
    The collector reuses the router's pooled HTTP client by default, so
    pulls ride the same keep-alive sockets as forwards."""

    def __init__(self, router, *, period_s: float = 0.5,
                 capacity_per_replica: int = 16384,
                 client: Optional[HTTPClient] = None,
                 registry: Optional[MetricsRegistry] = None,
                 timeout_s: float = 5.0):
        self.router = router
        self.client = client or router.client
        self.period_s = float(period_s)
        self.capacity_per_replica = int(capacity_per_replica)
        self.timeout_s = float(timeout_s)
        self._local = registry
        self.watchdog: Optional[SLOWatchdog] = None
        self._lock = threading.Lock()
        self._events: Dict[str, deque] = {}
        self._cursors: Dict[str, int] = {}
        self._metrics: Dict[str, dict] = {}     # rid -> raw_metrics
        self._spool_seqs: Dict[str, int] = {}   # rid -> last ingested seq
        self.pulls = 0
        self.events_pulled = 0
        self.pull_errors = 0
        self.spools_recovered = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def local_registry(self) -> MetricsRegistry:
        """The supervisor process's own registry (front-door spans and
        fleet.* metrics live here; watchdog writes land here too)."""
        return self._local if self._local is not None else get_registry()

    # ------------------------------------------------------------- pulling
    def pull_once(self) -> int:
        """One incremental sweep over the membership table. Live
        replicas answer ``/debug/trace`` + ``/debug/metrics``; dead ones
        are recovered from their spool spill. Returns events ingested."""
        self.pulls += 1
        got = 0
        for row in self.router.replicas():
            rid = row["id"]
            if row["state"] == "ready" and row.get("url"):
                got += self._pull_replica(rid, row["url"])
            elif row["state"] == "dead" and row.get("spool_path"):
                got += self.ingest_spool(rid, row["spool_path"])
        self._publish_gauges()
        reg = self.local_registry
        if reg.enabled:
            reg.counter("fleet.collector.pulls").inc()
            if got:
                reg.counter("fleet.collector.events").inc(got)
        return got

    def _pull_replica(self, rid: str, url: str) -> int:
        cursor = self._cursors.get(rid, 0)
        try:
            status, headers, events = self.client.request_ndjson(
                "GET", f"{url}/debug/trace?since_seq={cursor}",
                timeout=self.timeout_s)
            if status != 200:
                raise ConnectionError(f"/debug/trace answered {status}")
            mstatus, metrics = self.client.request_json(
                "GET", f"{url}/debug/metrics", timeout=self.timeout_s)
        except Exception:
            # transport flake: the router's health machinery owns
            # membership — the collector just tries again next period
            self.pull_errors += 1
            return 0
        watermark = int(headers.get("X-Trace-Seq", 0) or 0)
        got = self._ingest(rid, events, watermark)
        if mstatus == 200 and isinstance(metrics, dict):
            with self._lock:
                self._metrics[rid] = metrics
        return got

    def ingest_spool(self, rid: str, path: str) -> int:
        """Black-box recovery: ingest a dead replica's last spill. Only
        events past the HTTP cursor count — a spool that the live pulls
        already covered adds nothing (exactly-once by seq watermark)."""
        spill = read_spool(path)
        if spill is None:
            return 0
        seq = int(spill.get("seq", 0))
        if self._spool_seqs.get(rid) == seq:
            return 0                    # this spill is already ingested
        got = self._ingest(rid, spill.get("events") or [], seq)
        self._spool_seqs[rid] = seq
        if isinstance(spill.get("metrics"), dict):
            with self._lock:
                self._metrics[rid] = spill["metrics"]
        self.spools_recovered += 1
        reg = self.local_registry
        if reg.enabled:
            reg.counter("fleet.collector.spools_recovered").inc()
        return got

    def _ingest(self, rid: str, events: List[dict], watermark: int) -> int:
        cursor = self._cursors.get(rid, 0)
        fresh = []
        for e in events:
            if not isinstance(e, dict) or e.get("seq", 0) <= cursor:
                continue
            e.setdefault("args", {})["replica"] = rid
            fresh.append(e)
        with self._lock:
            dq = self._events.get(rid)
            if dq is None:
                dq = self._events[rid] = deque(
                    maxlen=self.capacity_per_replica)
            dq.extend(fresh)
        top = max([e["seq"] for e in fresh], default=cursor)
        self._cursors[rid] = max(cursor, top, watermark)
        self.events_pulled += len(fresh)
        return len(fresh)

    def _publish_gauges(self) -> None:
        """Per-replica steering summary gauges into the LOCAL registry —
        the dashboard's fleet card and the front-door Prometheus dump
        read these without touching the collector object."""
        reg = self.local_registry
        if not reg.enabled:
            return
        with self._lock:
            raws = dict(self._metrics)
        for rid, raw in raws.items():
            gauges = (raw or {}).get("gauges") or {}
            hit, queue, occ = [], 0.0, []
            for n, g in gauges.items():
                v = (g or {}).get("value", 0.0)
                if n.endswith(".prefix_hit_rate"):
                    hit.append(v)
                elif n.endswith(".queue_depth"):
                    queue += v
                elif n.endswith(".slot_occupancy"):
                    occ.append(v)
            base = f"fleet.replica.{rid}"
            if hit:
                reg.gauge(f"{base}.prefix_hit_rate").set(
                    round(max(hit), 4))
            reg.gauge(f"{base}.queue_depth").set(queue)
            if occ:
                reg.gauge(f"{base}.slot_occupancy").set(
                    round(max(occ), 4))

    # ----------------------------------------------------------- stitching
    def events_for_trace(self, trace_id: str) -> List[dict]:
        """One request's events across every process, chronological.
        Replica events carry their pulled ``args.replica``; the
        supervisor's own events (front-door ingress span, fleet.route)
        are stamped ``front`` on the way out (copies — the local ring is
        never mutated). Epoch-anchored ``ts`` makes the cross-process
        sort meaningful."""
        want = normalize_trace_id(trace_id)
        if want is None:
            return []
        out: List[dict] = []
        with self._lock:
            pools = [list(dq) for dq in self._events.values()]
        for pool in pools:
            out.extend(e for e in pool
                       if e.get("args", {}).get("trace_id") == want)
        for e in self.local_registry.trace_events():
            args = e.get("args", {})
            if args.get("trace_id") == want:
                e = dict(e)
                e["args"] = {**args}
                e["args"].setdefault("replica", FRONT_DOOR)
                out.append(e)
        out.sort(key=lambda e: (e.get("ts", 0), e.get("seq", 0)))
        return out

    def trace_ids(self) -> List[str]:
        """Distinct trace ids currently held (replica pools + local)."""
        ids = set()
        with self._lock:
            pools = [list(dq) for dq in self._events.values()]
        for pool in pools:
            for e in pool:
                tid = e.get("args", {}).get("trace_id")
                if tid:
                    ids.add(tid)
        for e in self.local_registry.trace_events():
            tid = e.get("args", {}).get("trace_id")
            if tid:
                ids.add(tid)
        return sorted(ids)

    # --------------------------------------------------------- aggregation
    def aggregate(self) -> dict:
        """Fleet-merged counters + histograms over the latest per-replica
        raws (see :func:`merge_raw_metrics`; ladder mismatches raise)."""
        with self._lock:
            raws = dict(self._metrics)
        return merge_raw_metrics(raws)

    def merged_histogram(self, name: str) -> dict:
        """One histogram merged across replicas AND the local registry
        (the front door's own ``fleet.latency_ms`` folds in), in raw
        wire format."""
        agg = self.aggregate()["histograms"].get(name)
        local = self.local_registry.histogram_if_exists(name)
        if local is not None:
            raws = {"_local": {"histograms": {name: local.raw()}}}
            if agg is not None:
                raws["_agg"] = {"histograms": {name: agg}}
            agg = merge_raw_metrics(raws)["histograms"][name]
        if agg is None:
            return {"bounds": [], "cumulative": [], "count": 0,
                    "sum": 0.0}
        return agg

    def aggregate_registry(self) -> AggregateRegistry:
        return AggregateRegistry(self)

    def make_watchdog(self, objectives, **kwargs) -> SLOWatchdog:
        """Fleet-level SLOs: the standard watchdog over the aggregate
        view. Burn-rate gauges and breach dumps land in the local
        (front-door) registry/flight recorder; the autoscaler's
        ``watchdog=`` parameter takes the return value directly."""
        self.watchdog = SLOWatchdog(
            objectives, registry=self.aggregate_registry(), **kwargs)
        return self.watchdog

    # ----------------------------------------------------------- exposition
    def to_prometheus_text(self, prefix: str = "dl4j_tpu") -> str:
        """Front-door registry text + per-replica samples with
        ``replica=`` labels + ``fleet_``-prefixed aggregates whose
        histogram buckets are the merged cumulative counts (fleet p99
        quantile queries over these are honest by construction)."""
        san = sanitize_metric_name
        lines = [self.local_registry.to_prometheus_text(prefix).rstrip()]
        with self._lock:
            raws = {rid: self._metrics[rid] for rid in sorted(self._metrics)}
        for rid, raw in raws.items():
            lab = f'replica="{escape_label_value(rid)}"'
            for n, v in sorted((raw.get("counters") or {}).items()):
                lines.append(f"{prefix}_{san(n)}{{{lab}}} {v}")
            for n, g in sorted((raw.get("gauges") or {}).items()):
                lines.append(
                    f"{prefix}_{san(n)}{{{lab}}} {(g or {}).get('value', 0)}")
            for n, h in sorted((raw.get("histograms") or {}).items()):
                full = f"{prefix}_{san(n)}"
                cum = h.get("cumulative") or []
                total = cum[-1] if cum else h.get("count", 0)
                for bound, cnt in zip(h.get("bounds") or (), cum):
                    le = escape_label_value(f"{float(bound):g}")
                    lines.append(
                        f'{full}_bucket{{{lab},le="{le}"}} {cnt}')
                lines.append(f'{full}_bucket{{{lab},le="+Inf"}} {total}')
                lines.append(f"{full}_sum{{{lab}}} {h.get('sum', 0.0)}")
                lines.append(f"{full}_count{{{lab}}} {total}")
        agg = merge_raw_metrics(raws)
        for n, v in sorted(agg["counters"].items()):
            full = f"{prefix}_fleet_{san(n)}"
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {v}")
        for n, h in sorted(agg["histograms"].items()):
            full = f"{prefix}_fleet_{san(n)}"
            lines.append(f"# TYPE {full} histogram")
            cum = h["cumulative"]
            total = cum[-1] if cum else 0
            for bound, cnt in zip(h["bounds"], cum):
                le = escape_label_value(f"{float(bound):g}")
                lines.append(f'{full}_bucket{{le="{le}"}} {cnt}')
            lines.append(f'{full}_bucket{{le="+Inf"}} {total}')
            lines.append(f"{full}_sum {h['sum']}")
            lines.append(f"{full}_count {total}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Collector health for ``GET /metrics``'s ``collector`` key and
        the fleet_report tool."""
        with self._lock:
            per = {rid: {"events": len(dq),
                         "cursor": self._cursors.get(rid, 0)}
                   for rid, dq in self._events.items()}
        return {"pulls": self.pulls,
                "events_pulled": self.events_pulled,
                "pull_errors": self.pull_errors,
                "spools_recovered": self.spools_recovered,
                "period_s": self.period_s,
                "traces": len(self.trace_ids()),
                "per_replica": per}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetCollector":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="fleet-collector")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.pull_once()
            except Exception:           # pragma: no cover - keep pulling
                self.pull_errors += 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
