"""Prefix-cache-affinity routing: N replicas as N× cache capacity.

Each replica's prefix cache (serving/generation/prefix.py) is
per-process, so a load balancer that sprays requests uniformly turns N
replicas into N× cache MISSES — every replica re-prefills every popular
system prompt, and the pool pressure evicts N copies of everything. The
affinity policy routes on the prompt's block-aligned prefix chain
instead, computed with the SAME rolling chain hash the prefix cache
itself keys blocks by (imported, not re-implemented — the two can never
drift):

    h_0 = H(tokens[0:blk])   h_i = H(h_{i-1} || tokens[i*blk:(i+1)*blk])

Routing is learned longest-prefix matching over a bounded LRU map from
chain hash -> replica: a request walks its chain deepest-first and
follows the deepest hash the router has routed before — exactly the
replica whose cache already holds those blocks. Unseen prefixes fall
back to rendezvous (highest-random-weight) hashing on the chain head,
which (a) spreads DISTINCT system prompts across the fleet so the
aggregate cache capacity actually multiplies, and (b) is stable under
membership churn — adding or losing a replica remaps only the keys that
scored highest on it, not the whole keyspace.

Affinity is a preference, not a law: a target that is draining, dead, or
overloaded (deep queue, starved block pool — read from the ``/health``
steering payload) is skipped and the request spills to the next
candidate, which then LEARNS the prefix so the hot prompt's blocks
simply live on two replicas from then on.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

# the cache's own rolling chain hash — shared on purpose, see module doc
from ..generation.prefix import _block_hashes

DEFAULT_BLOCK_LEN = 16


def prompt_chain(prompt: Sequence[int], block_len: int) -> List[bytes]:
    """Rolling chain hashes for every FULL block of ``prompt`` (identical
    to the prefix cache's block keys for the same tokens)."""
    arr = np.asarray(list(prompt), dtype=np.int32)
    return _block_hashes(arr, int(block_len))


def rendezvous_order(key: bytes, replica_ids: Iterable[str]) -> List[str]:
    """Replica ids by descending highest-random-weight score for ``key``.
    Deterministic, stateless, minimally disruptive under membership
    change."""
    return sorted(
        replica_ids,
        key=lambda rid: hashlib.blake2b(
            key + b"\x00" + rid.encode(), digest_size=8).digest(),
        reverse=True)


class AffinityMap:
    """Bounded LRU of chain hash -> replica id (the learned half of the
    policy). Single-router-owned; guarded by the router's lock."""

    def __init__(self, capacity: int = 8192):
        self.capacity = int(capacity)
        self._map: "OrderedDict[bytes, str]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._map)

    def longest(self, chain: Sequence[bytes]
                ) -> Tuple[Optional[str], int]:
        """Deepest recorded hash in ``chain``: (replica_id, depth in
        blocks), or (None, 0). Touches the match (LRU refresh)."""
        for depth in range(len(chain), 0, -1):
            rid = self._map.get(chain[depth - 1])
            if rid is not None:
                self._map.move_to_end(chain[depth - 1])
                return rid, depth
        return None, 0

    def record(self, chain: Sequence[bytes], replica_id: str) -> None:
        for h in chain:
            self._map[h] = replica_id
            self._map.move_to_end(h)
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)

    def forget_replica(self, replica_id: str) -> int:
        """Drop every entry pointing at a dead/removed replica (its cache
        died with it); returns entries dropped."""
        stale = [h for h, rid in self._map.items() if rid == replica_id]
        for h in stale:
            del self._map[h]
        return len(stale)

    def stats(self) -> dict:
        owners: Dict[str, int] = {}
        for rid in self._map.values():
            owners[rid] = owners.get(rid, 0) + 1
        return {"entries": len(self._map), "capacity": self.capacity,
                "entries_per_replica": owners}


class AffinityPolicy:
    """Candidate ordering for one admission.

    ``views`` are lightweight router records exposing ``.id``, ``.ready``
    (health-gated: starting/draining/dead replicas are never candidates)
    and ``.steering`` (the replica's last ``/health`` steering payload).
    Overload (queue deeper than ``queue_hi`` or block-pool free fraction
    under ``min_free_frac``) demotes a replica behind every non-overloaded
    one without removing it — under total fleet pressure requests still
    land somewhere and the replica's own 429 backpressure takes over."""

    def __init__(self, *, map_capacity: int = 8192, queue_hi: int = 8,
                 min_free_frac: float = 0.05):
        self.map = AffinityMap(map_capacity)
        self.queue_hi = int(queue_hi)
        self.min_free_frac = float(min_free_frac)

    def overloaded(self, view) -> bool:
        s = view.steering or {}
        if s.get("queue_depth", 0) > self.queue_hi:
            return True
        return s.get("block_pool_free_frac", 1.0) < self.min_free_frac

    def candidates(self, chain: Sequence[bytes], views: Sequence
                   ) -> Tuple[List[str], str]:
        """Ordered candidate replica ids + the route reason
        (``affinity`` / ``rendezvous`` / ``spill`` / ``none``)."""
        ready = [v for v in views if v.ready]
        if not ready:
            return [], "none"
        key = chain[0] if chain else b"short-prompt"
        order = rendezvous_order(key, [v.id for v in ready])
        by_id = {v.id: v for v in ready}
        # stable partition: non-overloaded first, overloaded as last resort
        order = ([r for r in order if not self.overloaded(by_id[r])]
                 + [r for r in order if self.overloaded(by_id[r])])
        target, _depth = self.map.longest(chain)
        reason = "rendezvous"
        if target is not None and target in by_id:
            if not self.overloaded(by_id[target]):
                order.remove(target)
                order.insert(0, target)
                reason = "affinity"
            else:
                reason = "spill"
        return order, reason

    def record(self, chain: Sequence[bytes], replica_id: str) -> None:
        self.map.record(chain, replica_id)

    def forget_replica(self, replica_id: str) -> int:
        return self.map.forget_replica(replica_id)

    def stats(self) -> dict:
        return {"queue_hi": self.queue_hi,
                "min_free_frac": self.min_free_frac, **self.map.stats()}
