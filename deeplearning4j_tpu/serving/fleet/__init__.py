"""serving/fleet/ — elastic multi-process replica pool.

One replica = one OS process running the single-process serving stack
(GenerationEngine + ServingHTTPServer); the fleet layer adds what a
single process cannot give you — fault isolation (a replica SIGKILL
loses only its in-flight streams, each closed with an explicit reason),
horizontal decode throughput, and elasticity:

  - replica.py    process supervisor + replica child entrypoint
                  (spawn, ready-file + /health readiness gate, drain-
                  then-stop SIGTERM, chaos SIGKILL, restart)
  - affinity.py   prefix-cache-affinity routing: learned longest-prefix
                  map + rendezvous hashing over the SAME rolling chain
                  hash the prefix cache keys blocks by
  - router.py     health-gated admission, capped-backoff failover
                  (retry ONLY before the first token), DEAD_AFTER=3
                  mark-dead discipline, drain-then-stop scale-in
  - autoscale.py  pure decide() on SLO burn rate + queue depth, one-step
                  moves under cooldowns; actuator thread
  - collector.py  supervisor-side observability: incremental trace-ring
                  pulls with replica attribution, cross-process timeline
                  stitching, dead-replica spool recovery, merged-bucket
                  fleet metrics + fleet-level SLO watchdog
  - coldstart.py  load-not-compile cold start via the persistent
                  compilation cache (DL4J_TPU_COMPILE_CACHE)
  - http.py       the front door: single-replica wire protocol, fleet
                  semantics
"""
from .affinity import AffinityMap, AffinityPolicy, prompt_chain, \
    rendezvous_order
from .autoscale import Autoscaler, AutoscalePolicy, decide
from .coldstart import (configure_compile_cache, configured_cache_dir,
                        fresh_compile_count)
from .collector import AggregateRegistry, FleetCollector, merge_raw_metrics
from .http import FleetHTTPServer
from .replica import ReplicaProcess
from .router import (DEAD_AFTER, FleetError, FleetHTTPError, FleetRouter,
                     NoReadyReplicaError)

__all__ = [
    "AffinityMap", "AffinityPolicy", "prompt_chain", "rendezvous_order",
    "Autoscaler", "AutoscalePolicy", "decide",
    "AggregateRegistry", "FleetCollector", "merge_raw_metrics",
    "configure_compile_cache", "configured_cache_dir",
    "fresh_compile_count",
    "FleetHTTPServer", "ReplicaProcess",
    "DEAD_AFTER", "FleetError", "FleetHTTPError", "FleetRouter",
    "NoReadyReplicaError",
]
