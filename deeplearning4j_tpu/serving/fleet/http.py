"""Fleet front door: one listener, N replicas behind the router.

Clients speak the SAME wire protocol as a single replica
(serving/http.py) — chunked NDJSON token streams, the 400/404/429/503/
504 status taxonomy, X-Trace-Id echo — so pointing an existing client at
the fleet is a URL change, not a client change. What the fleet adds is
invisible until a replica dies: pre-first-token failures are replayed on
a survivor (the client just sees a slower admission), post-first-token
losses close the stream with ``reason: "replica_lost"``.

  POST /generate[/model]   routed + failover (stream and blocking)
  POST /predict[/model]    routed + failover
  GET  /health             200 while >= 1 replica is READY, else 503;
                           per-replica states + fleet counters
  GET  /metrics            router metrics (+ per-replica /metrics scrape
                           with {"scrape": false} absent — the
                           fleet_report tool folds these; with a
                           collector attached also "slo" + "collector")
  GET  /metrics/prometheus fleet Prometheus text: front-door samples,
                           per-replica samples with replica="<id>"
                           labels, and fleet_* aggregates whose
                           histogram buckets are merged cumulative
                           counts (honest fleet p99)
  GET  /debug/trace        distinct stitched trace ids the collector
                           currently holds
  GET  /debug/trace/<id>   one request's cross-process timeline —
                           front-door ingress + fleet routing + replica
                           decode spans merged chronologically
  GET  /fleet              membership table (states, steering, restarts)
  POST /scale              {"op": "drain"|"kill", "replica": id} — ops
                           scale-in and chaos injection share the door

Trace/aggregation routes need a :class:`~.collector.FleetCollector`
(``FleetHTTPServer(router, collector=...)``); without one they answer
503 so a collector-less fleet still serves everything else.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Optional

from ...telemetry import get_registry
from ...telemetry.tracecontext import (event, new_trace_context,
                                       use_trace_context)
from .router import FleetHTTPError, FleetRouter, NoReadyReplicaError


class FleetHTTPServer:
    def __init__(self, router: FleetRouter, port: int = 0,
                 host: str = "127.0.0.1", collector=None):
        self.router = router
        self.collector = collector      # FleetCollector or None
        self.host = host
        self._port = port
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> int:
        import http.server as hs

        from ...util.httpjson import read_json, write_json
        router = self.router
        collector = self.collector

        class Handler(hs.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            _trace_ctx = None

            def _traced(self):
                # the SAME trace id flows client -> fleet -> replica:
                # router forwards it via X-Trace-Id, so one trace stitches
                # the front-door admission to the replica's decode spans
                ctx = new_trace_context(self.headers.get("X-Trace-Id"))
                self._trace_ctx = ctx
                return use_trace_context(ctx)

            def end_headers(self):
                ctx = self._trace_ctx
                if ctx is not None:
                    self.send_header("X-Trace-Id", ctx.trace_id)
                super().end_headers()

            def do_GET(self):       # noqa: N802
                try:
                    with self._traced():
                        self._route_get()
                finally:
                    self._trace_ctx = None

            def do_POST(self):      # noqa: N802
                try:
                    with self._traced():
                        event("fleet.request", method="POST",
                              route=self.path)
                        self._route_post()
                finally:
                    self._trace_ctx = None

            # ---------------------------------------------------- routes
            def _route_get(self):
                if self.path == "/health":
                    rows = router.replicas()
                    ready = sum(1 for r in rows if r["state"] == "ready")
                    body = {"status": "ok" if ready else "unavailable",
                            "ready": ready, "replicas": len(rows),
                            "states": {r["id"]: r["state"] for r in rows},
                            "policy": router.policy}
                    write_json(self, 200 if ready else 503, body)
                # collector-backed routes dispatch BEFORE the
                # startswith("/metrics") catch-all below
                elif self.path == "/metrics/prometheus":
                    self._prometheus()
                elif self.path == "/debug/trace" or \
                        self.path.startswith("/debug/trace/"):
                    self._stitched_trace()
                elif self.path.startswith("/metrics"):
                    body = router.metrics()
                    body["replica_metrics"] = self._scrape()
                    if collector is not None:
                        body["collector"] = collector.snapshot()
                        if collector.watchdog is not None:
                            body["slo"] = collector.watchdog.check()
                    write_json(self, 200, body)
                elif self.path == "/fleet":
                    write_json(self, 200, {"replicas": router.replicas(),
                                           "policy": router.policy,
                                           "block_len": router.block_len})
                else:
                    write_json(self, 404,
                               {"error": f"no route {self.path}"})

            def _prometheus(self):
                """Fleet Prometheus text dump. A bucket-ladder mismatch
                is refused loudly (500 naming the offending histogram)
                rather than silently mis-merged — the merge-correctness
                contract the regression tests pin."""
                from ...telemetry.registry import HistogramLadderMismatch
                try:
                    if collector is not None:
                        text = collector.to_prometheus_text()
                    else:
                        text = get_registry().to_prometheus_text()
                except HistogramLadderMismatch as e:
                    write_json(self, 500, {
                        "error": str(e), "kind": "HistogramLadderMismatch"})
                    return
                data = text.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _stitched_trace(self):
                if collector is None:
                    write_json(self, 503, {
                        "error": "no FleetCollector attached to this "
                                 "front door"})
                    return
                collector.pull_once()   # serve fresh, not period-stale
                if self.path == "/debug/trace":
                    write_json(self, 200,
                               {"traces": collector.trace_ids()})
                    return
                tid = self.path[len("/debug/trace/"):]
                events = collector.events_for_trace(tid)
                if not events:
                    write_json(self, 404,
                               {"error": f"no events for trace {tid!r}"})
                    return
                write_json(self, 200, {"trace_id": tid, "events": events})

            def _scrape(self) -> dict:
                """Per-replica /metrics snapshots (best effort — a dead
                replica yields its last known nothing, not a 500 here)."""
                out = {}
                for r in router.replicas():
                    if r["state"] != "ready" or not r["url"]:
                        continue
                    try:
                        _, m = router.client.request_json(
                            "GET", r["url"] + "/metrics", timeout=5.0)
                        out[r["id"]] = m
                    except Exception:
                        pass
                return out

            def _route_post(self):
                if self.path == "/generate" or \
                        self.path.startswith("/generate/"):
                    self._generate()
                elif self.path == "/predict" or \
                        self.path.startswith("/predict/"):
                    self._forward()
                elif self.path == "/scale":
                    self._scale()
                else:
                    self._drain_body()
                    write_json(self, 404,
                               {"error": f"no route {self.path}"})

            def _drain_body(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                if n:
                    try:
                        self.rfile.read(n)
                    except OSError:
                        self.close_connection = True

            def _model_suffix(self, prefix: str) -> Optional[str]:
                if self.path.startswith(prefix + "/"):
                    return self.path[len(prefix) + 1:] or None
                return None

            def _generate(self):
                model = self._model_suffix("/generate")
                try:
                    req = read_json(self)
                    if not isinstance(req, dict) or "prompt" not in req:
                        raise ValueError("body must carry 'prompt'")
                    stream = bool(req.get("stream", True))
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                t0 = time.monotonic()
                if not stream:
                    status, body = router.generate_blocking(req, model)
                    self._observe(t0, status)
                    write_json(self, status, body)
                    return
                it = router.stream_generate(req, model)
                try:
                    first = next(it)
                except FleetHTTPError as e:
                    self._observe(t0, e.status)
                    write_json(self, e.status, e.body)
                    return
                except NoReadyReplicaError as e:
                    self._observe(t0, 503)
                    write_json(self, 503, {"error": str(e),
                                           "kind": "NoReadyReplica"})
                    return
                except StopIteration:   # pragma: no cover - defensive
                    write_json(self, 500, {"error": "empty stream"})
                    return
                self._stream(it, first)
                self._observe(t0, 200)

            def _stream(self, it, first):
                """Re-emit the router's NDJSON dicts as a chunked body —
                the terminator ALWAYS arrives (done/deadline/replica_lost
                alike), so fleet clients never hang on a dead replica."""
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj) -> bool:
                    data = (json.dumps(obj) + "\n").encode()
                    try:
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data + b"\r\n")
                        self.wfile.flush()
                        return True
                    except (BrokenPipeError, ConnectionResetError,
                            OSError):
                        return False
                alive = chunk(first)
                for obj in it:
                    if alive and not chunk(obj):
                        alive = False   # keep draining: frees the slot
                if alive:
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError,
                            OSError):
                        self.close_connection = True
                else:
                    self.close_connection = True

            def _forward(self):
                try:
                    req = read_json(self)
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                t0 = time.monotonic()
                status, body = router.forward_json("POST", self.path, req)
                self._observe(t0, status)
                write_json(self, status, body)

            def _scale(self):
                try:
                    req = read_json(self)
                    op = req["op"]
                    rid = req["replica"]
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                if op == "drain":
                    drained = router.drain_replica(rid)
                    write_json(self, 200, {"replica": rid, "op": "drain",
                                           "drained": drained})
                elif op == "kill":
                    try:
                        router.kill_replica(rid)
                    except Exception as e:
                        write_json(self, 404, {"error": str(e)})
                        return
                    write_json(self, 200, {"replica": rid, "op": "kill"})
                else:
                    write_json(self, 400, {"error": f"unknown op {op!r}"})

            @staticmethod
            def _observe(t0: float, status: int) -> None:
                reg = get_registry()
                if reg.enabled:
                    reg.histogram("fleet.latency_ms").observe(
                        (time.monotonic() - t0) * 1e3)
                    reg.counter(f"fleet.http_{status // 100}xx").inc()

            def log_message(self, *a):
                pass

        self._httpd = hs.ThreadingHTTPServer((self.host, self._port),
                                             Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="fleet-http")
        self._thread.start()
        return self.port

    def stop(self, *, close_router: bool = False) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if close_router:
            if self.collector is not None:
                self.collector.stop()
            self.router.close()
