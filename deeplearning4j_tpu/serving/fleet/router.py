"""FleetRouter: health-gated admission + affinity routing + retries.

The router owns the fleet membership table: each replica is either a
supervised :class:`~.replica.ReplicaProcess` (the router can restart and
kill it) or a bare URL (an externally managed process — tests route
across in-process servers this way). A background poller scrapes every
replica's ``/health`` steering payload on a short period; admission is
gated on that state — a replica is a candidate only while READY, and a
replica that fails ``DEAD_AFTER`` consecutive transport attempts (health
polls and forwards both count) is marked DEAD, dropped from the affinity
map (its cache died with it), black-boxed via the flight recorder, and —
with ``autorestart`` — respawned.

Retry discipline (the part chaos tests pin): a generation forward that
dies BEFORE any token reached the client is replayed on the next
candidate with capped backoff (``util/retry.py`` delays); once a token
is on the client's wire the stream can never be replayed — it is closed
with an explicit ``{"done": true, "reason": "replica_lost"}`` terminator
so the client-visible stream is always a single clean sequence, never a
spliced or double-emitted one. Every replay lands a ``fleet.retry``
trace event; a replayed request's done line carries ``retries``.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from ...telemetry import get_registry
from ...telemetry.flightrec import get_flight_recorder
from ...telemetry.tracecontext import current_trace_context, event
from ...util.httpjson import HTTPClient
from ...util.retry import RetryPolicy
from .affinity import DEFAULT_BLOCK_LEN, AffinityPolicy, prompt_chain
from .replica import ReplicaProcess

# consecutive transport failures after which a replica is DEAD (the
# bench_smoke guard pins this: flapping sockets must not flap membership,
# and a hard-killed replica must stop receiving traffic within 3 strikes)
DEAD_AFTER = 3

STARTING, READY, DRAINING, DEAD = "starting", "ready", "draining", "dead"


class FleetError(RuntimeError):
    pass


class NoReadyReplicaError(FleetError):
    """No candidate could serve the request (fleet-level 503)."""


class FleetHTTPError(FleetError):
    """A replica answered with a non-retryable HTTP error — forwarded to
    the client verbatim (status + body)."""

    def __init__(self, status: int, body: dict):
        super().__init__(f"replica answered {status}")
        self.status = status
        self.body = body


class _Replica:
    """Router-side view of one replica."""

    __slots__ = ("id", "url", "proc", "state", "steering", "fails",
                 "restarts", "forwarded", "last_poll_s", "_restarting",
                 "_dying")

    def __init__(self, rid: str, url: Optional[str],
                 proc: Optional[ReplicaProcess]):
        self.id = rid
        self.url = url
        self.proc = proc
        self.state = STARTING
        self.steering: dict = {}
        self.fails = 0
        self.restarts = 0
        self.forwarded = 0
        self.last_poll_s: Optional[float] = None
        self._restarting = False
        self._dying = False

    @property
    def ready(self) -> bool:
        return self.state == READY and self.url is not None

    def row(self) -> dict:
        return {"id": self.id, "url": self.url, "state": self.state,
                "pid": self.proc.pid if self.proc else None,
                "consecutive_failures": self.fails,
                "restarts": self.restarts, "forwarded": self.forwarded,
                "spool_path": getattr(self.proc, "spool_path", None),
                "steering": self.steering}


class FleetRouter:
    def __init__(self, *, policy: str = "affinity",
                 block_len: Optional[int] = None,
                 client: Optional[HTTPClient] = None,
                 health_period_s: float = 0.2,
                 retry: Optional[RetryPolicy] = None,
                 queue_hi: int = 8, min_free_frac: float = 0.05,
                 autorestart: bool = False):
        if policy not in ("affinity", "round_robin", "least_loaded"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self.policy = policy
        self._block_len = block_len     # None: adopt from first steering
        self.client = client or HTTPClient(max_per_host=8, timeout=30.0)
        self.health_period_s = float(health_period_s)
        self.retry = retry or RetryPolicy(max_attempts=3,
                                          base_delay_s=0.02,
                                          max_delay_s=0.2)
        self.autorestart = autorestart
        self.affinity = AffinityPolicy(queue_hi=queue_hi,
                                       min_free_frac=min_free_frac)
        self._replicas: Dict[str, _Replica] = {}
        self._lock = threading.RLock()
        self._rr = 0                    # round-robin cursor
        self._poll_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._counters = {"requests": 0, "retries": 0, "streams_lost": 0,
                          "replica_deaths": 0, "rejected": 0}

    # ---------------------------------------------------------- membership
    def add_url(self, url: str, replica_id: Optional[str] = None) -> str:
        """Register an externally managed replica by base URL."""
        with self._lock:
            rid = replica_id or f"r{len(self._replicas)}"
            if rid in self._replicas:
                raise ValueError(f"replica {rid!r} already registered")
            self._replicas[rid] = _Replica(rid, url.rstrip("/"), None)
        self.poll_replica(rid)
        return rid

    def add_process(self, proc: ReplicaProcess, *,
                    wait_ready: bool = True,
                    timeout: float = 120.0) -> str:
        """Register (and readiness-gate) a supervised replica process."""
        with self._lock:
            if proc.id in self._replicas:
                raise ValueError(f"replica {proc.id!r} already registered")
            r = _Replica(proc.id, None, proc)
            self._replicas[proc.id] = r
        if not proc.alive:
            proc.start()
        if wait_ready:
            proc.wait_ready(timeout=timeout, client=self.client)
            r.url = proc.base_url
            self.poll_replica(proc.id)
        return proc.id

    def remove_replica(self, rid: str) -> None:
        with self._lock:
            r = self._replicas.pop(rid, None)
        if r is not None:
            self.affinity.forget_replica(rid)

    def replicas(self) -> List[dict]:
        with self._lock:
            return [r.row() for r in self._replicas.values()]

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values() if r.ready)

    @property
    def block_len(self) -> int:
        return self._block_len or DEFAULT_BLOCK_LEN

    # -------------------------------------------------------------- health
    def start(self) -> "FleetRouter":
        """Start the background health poller."""
        if self._poll_thread is None:
            self._stop.clear()
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True, name="fleet-health")
            self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
            self._poll_thread = None

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.health_period_s):
            self.poll_once()

    def poll_once(self) -> None:
        with self._lock:
            rids = list(self._replicas)
        for rid in rids:
            self.poll_replica(rid)

    def poll_replica(self, rid: str) -> None:
        with self._lock:
            r = self._replicas.get(rid)
        if r is None:
            return
        # a supervised child that EXITED is unambiguously dead — no need
        # to burn three strikes on connection-refused
        if r.proc is not None and r.proc.proc is not None \
                and not r.proc.alive and r.state != DEAD:
            self._mark_dead(r, reason="process_exit")
            return
        if r.url is None:
            # still starting: adopt the URL once the ready file lands
            if r.proc is not None:
                try:
                    with open(r.proc.ready_path) as f:
                        r.proc.ready_info = json.load(f)
                    r.url = r.proc.base_url
                except (OSError, ValueError):
                    return
            else:
                return
        try:
            status, body = self.client.request_json(
                "GET", r.url + "/health", timeout=5.0)
        except Exception:
            self._note_failure(r)
            return
        r.fails = 0
        r.last_poll_s = time.monotonic()
        if isinstance(body, dict):
            r.steering = body.get("steering", {}) or {}
            if self._block_len is None and r.steering.get("block_len"):
                self._block_len = int(r.steering["block_len"])
        if r.state != DRAINING:     # router-initiated drains are sticky
            r.state = READY if status == 200 else \
                (DRAINING if status == 503 else r.state)

    def _note_failure(self, r: _Replica) -> None:
        r.fails += 1
        if r.fails >= DEAD_AFTER and r.state != DEAD:
            self._mark_dead(r, reason="transport_failures")

    def _mark_dead(self, r: _Replica, *, reason: str) -> None:
        with self._lock:                # at-most-once across threads
            if r.state == DEAD or r._dying:
                return
            r._dying = True
        self._counters["replica_deaths"] += 1
        dropped = self.affinity.forget_replica(r.id)
        reg = get_registry()
        if reg.enabled:
            reg.counter("fleet.replica_deaths").inc()
        event("fleet.replica_dead", replica=r.id, reason=reason)
        # black box: what was the fleet doing when it lost this replica —
        # plus what the VICTIM was doing, recovered from its crash-durable
        # spool spill (telemetry/spool.py). A SIGKILLed replica cannot dump
        # anything itself; its last periodic spill speaks for it.
        get_flight_recorder().dump(
            "fleet_replica_lost", replica=r.id, reason=reason,
            consecutive_failures=r.fails, affinity_entries_dropped=dropped,
            restarts=r.restarts, victim_spill=self._victim_spill(r))
        # state flips LAST: an observer that polls to "dead" may rely on
        # the black box already being on disk (the chaos tests do)
        r.state = DEAD
        r._dying = False
        if self.autorestart and r.proc is not None and not r._restarting:
            r._restarting = True
            threading.Thread(target=self._restart, args=(r,),
                             daemon=True, name=f"fleet-restart-{r.id}").start()

    @staticmethod
    def _victim_spill(r: _Replica, cap: int = 512) -> Optional[dict]:
        """The dead replica's last spool spill, event tail capped so the
        dump stays readable; None when no black box survived."""
        path = getattr(r.proc, "spool_path", None)
        if not path:
            return None
        from ...telemetry.spool import read_spool
        spill = read_spool(path)
        if spill is None:
            return None
        events = spill.get("events") or []
        if len(events) > cap:
            spill = {**spill, "events": events[-cap:],
                     "events_truncated": len(events) - cap}
        return spill

    def _restart(self, r: _Replica) -> None:
        try:
            r.proc.kill()           # reap if half-dead
            r.proc.restart()
            r.restarts += 1
            r.state = STARTING
            r.url = None
            r.fails = 0
            info = r.proc.wait_ready(timeout=300.0, client=self.client)
            r.url = r.proc.base_url
            r.state = READY
            event("fleet.replica_restarted", replica=r.id,
                  ready_s=info.get("ready_s"))
        except Exception as e:      # pragma: no cover - host-dependent
            event("fleet.replica_restart_failed", replica=r.id,
                  error=str(e))
        finally:
            r._restarting = False

    # -------------------------------------------------------------- routing
    def candidates(self, prompt) -> Tuple[List[str], str]:
        """Ordered candidate replica ids for this prompt + route reason."""
        with self._lock:
            views = list(self._replicas.values())
            if self.policy == "affinity":
                chain = prompt_chain(prompt or [], self.block_len)
                return self.affinity.candidates(chain, views)
            ready = [v.id for v in views if v.ready]
            if not ready:
                return [], "none"
            if self.policy == "round_robin":
                self._rr += 1
                k = self._rr % len(ready)
                return ready[k:] + ready[:k], "round_robin"
            # least_loaded: shallowest queue + in-flight first
            ready.sort(key=lambda rid: (
                self._replicas[rid].steering.get("queue_depth", 0)
                + self._replicas[rid].steering.get("in_flight", 0)))
            return ready, "least_loaded"

    def _record_route(self, prompt, rid: str) -> None:
        if self.policy == "affinity":
            with self._lock:
                self.affinity.record(
                    prompt_chain(prompt or [], self.block_len), rid)

    @staticmethod
    def _trace_headers() -> Dict[str, str]:
        ctx = current_trace_context()
        hdrs = {"Content-Type": "application/json"}
        if ctx is not None:
            hdrs["X-Trace-Id"] = ctx.trace_id   # per-replica propagation
        return hdrs

    def stream_generate(self, payload: dict, model: Optional[str] = None):
        """Generator of parsed NDJSON dicts for one /generate admission.

        Pre-stream failures (transport errors, 429/500/503 admissions)
        fail over to the next candidate under the capped-backoff retry
        budget; post-first-token failures close the stream with
        ``reason: "replica_lost"``. Raises :class:`FleetHTTPError` for
        non-retryable replica answers and :class:`NoReadyReplicaError`
        when the budget or the candidate list runs out."""
        prompt = payload.get("prompt") or []
        path = "/generate" + (f"/{model}" if model else "")
        body = json.dumps({**payload, "stream": True}).encode()
        self._counters["requests"] += 1
        reg = get_registry()
        if reg.enabled:
            reg.counter("fleet.requests").inc()
        delays = self.retry.delays()
        tried: set = set()
        retries = 0
        last_err: Optional[BaseException] = None
        while True:
            ids, reason = self.candidates(prompt)
            ids = [i for i in ids if i not in tried]
            if not ids:
                self._counters["rejected"] += 1
                raise NoReadyReplicaError(
                    f"no ready replica after {retries} retries "
                    f"({len(tried)} tried)") from last_err
            rid = ids[0]
            with self._lock:
                r = self._replicas.get(rid)
            if r is None or r.url is None:
                tried.add(rid)
                continue
            emitted = 0
            try:
                with self.client.stream("POST", r.url + path, body=body,
                                        headers=self._trace_headers()) \
                        as resp:
                    if resp.status != 200:
                        data = resp.read()
                        try:
                            err = json.loads(data)
                        except ValueError:
                            err = {"error": data.decode("utf-8", "replace")}
                        if resp.status in (429, 500, 503):
                            raise _RetryableAdmission(resp.status, err)
                        raise FleetHTTPError(resp.status, err)
                    r.fails = 0
                    r.forwarded += 1
                    self._record_route(prompt, rid)
                    event("fleet.route", replica=rid, reason=reason,
                          retries=retries)
                    for line in resp:
                        if not line.strip():
                            continue
                        obj = json.loads(line)
                        if "token" in obj:
                            emitted += 1
                        if obj.get("done"):
                            obj.setdefault("replica", rid)
                            if retries:
                                obj["retries"] = retries
                            yield obj
                            return
                        yield obj
                # replica stream ended without a done line: the engine
                # contract says streams ALWAYS end with one, so this is a
                # mid-stream connection loss surfaced as clean EOF
                raise ConnectionError("stream ended without done line")
            except FleetHTTPError:
                raise
            except _RetryableAdmission as e:
                tried.add(rid)
                last_err = e
                # replica alive but busy/draining/failing: NOT a strike
                if not self._backoff(delays):
                    self._counters["rejected"] += 1
                    raise FleetHTTPError(e.status, e.body) from None
                retries += 1
                self._on_retry(rid, f"http_{e.status}")
            except Exception as e:
                self._note_failure(r)
                last_err = e
                if emitted:
                    # token(s) already on the client's wire: never replay
                    self._counters["streams_lost"] += 1
                    if reg.enabled:
                        reg.counter("fleet.streams_lost").inc()
                    event("fleet.stream_lost", replica=rid,
                          tokens=emitted, error=str(e))
                    yield {"done": True, "reason": "replica_lost",
                           "tokens": emitted, "replica": rid,
                           "error": str(e)}
                    return
                tried.add(rid)
                if not self._backoff(delays):
                    self._counters["rejected"] += 1
                    raise NoReadyReplicaError(
                        f"retry budget exhausted after {retries + 1} "
                        f"attempts: {e}") from e
                retries += 1
                self._on_retry(rid, str(e))

    def _backoff(self, delays) -> bool:
        d = next(delays, None)
        if d is None:
            return False
        time.sleep(d)
        return True

    def _on_retry(self, rid: str, why: str) -> None:
        self._counters["retries"] += 1
        reg = get_registry()
        if reg.enabled:
            reg.counter("fleet.retries").inc()
        # the explicit retry marker the idempotency tests pin
        event("fleet.retry", replica=rid, error=why)

    def generate_blocking(self, payload: dict,
                          model: Optional[str] = None) -> Tuple[int, dict]:
        """Non-streaming /generate: nothing reaches the client until the
        request completed, so a stream lost mid-decode is safely replayed
        in full on a survivor (the replay decodes again — duplicated
        work, never duplicated output)."""
        replays = 0
        while True:
            tokens: List[int] = []
            done: Optional[dict] = None
            try:
                for obj in self.stream_generate(payload, model):
                    if "token" in obj:
                        tokens.append(obj["token"])
                    if obj.get("done"):
                        done = obj
            except FleetHTTPError as e:
                return e.status, e.body
            except NoReadyReplicaError as e:
                return 503, {"error": str(e), "kind": "NoReadyReplica"}
            if done is not None and done.get("reason") == "replica_lost" \
                    and replays < self.retry.max_attempts - 1:
                replays += 1
                self._on_retry(done.get("replica", "?"), "blocking_replay")
                continue
            body = {"tokens": tokens,
                    "reason": (done or {}).get("reason", "error"),
                    "replica": (done or {}).get("replica")}
            if replays or (done or {}).get("retries"):
                body["retries"] = replays + int((done or {}).get(
                    "retries", 0))
            return 200, body

    def forward_json(self, method: str, path: str, payload=None,
                     *, prompt=None) -> Tuple[int, dict]:
        """Failover forward for non-streaming routes (/predict, admin):
        capped-backoff retries through util/retry.py, candidates in
        routing-policy order."""
        def attempt():
            ids, _reason = self.candidates(prompt)
            if not ids:
                raise NoReadyReplicaError("no ready replica")
            rid = ids[0]
            with self._lock:
                r = self._replicas.get(rid)
            if r is None or r.url is None:
                raise NoReadyReplicaError(f"replica {rid} has no URL")
            try:
                status, body = self.client.request_json(
                    method, r.url + path, payload=payload,
                    headers=self._trace_headers())
            except Exception:
                self._note_failure(r)
                raise
            r.fails = 0
            r.forwarded += 1
            return status, body

        from ...util.retry import RetryError
        try:
            return self.retry.call(attempt)
        except RetryError as e:
            self._counters["rejected"] += 1
            return 503, {"error": f"fleet forward failed: {e.last}",
                         "kind": "NoReadyReplica"}

    # -------------------------------------------------------------- scaling
    def drain_replica(self, rid: str, *, timeout: float = 30.0,
                      stop_process: bool = True,
                      poll_s: float = 0.05) -> bool:
        """Drain-then-stop scale-in: stop routing to ``rid``, wait for its
        queue and in-flight slots to empty, then SIGTERM the process (the
        child drains its engines again on the way out — belt and braces).
        Returns True if the replica emptied within ``timeout``."""
        with self._lock:
            r = self._replicas.get(rid)
        if r is None:
            return False
        r.state = DRAINING          # candidates() stops offering it NOW
        event("fleet.drain", replica=rid)
        drained = False
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                _, body = self.client.request_json(
                    "GET", r.url + "/health", timeout=5.0)
                s = (body or {}).get("steering", {})
                if s.get("queue_depth", 0) == 0 \
                        and s.get("in_flight", 0) == 0:
                    drained = True
                    break
            except Exception:
                break               # already gone
            time.sleep(poll_s)
        if stop_process and r.proc is not None:
            r.proc.terminate(drain=True)
        self.remove_replica(rid)
        return drained

    def kill_replica(self, rid: str) -> None:
        """Chaos: SIGKILL a supervised replica, no drain, no cleanup —
        detection is the router's problem (that is the test)."""
        with self._lock:
            r = self._replicas.get(rid)
        if r is None or r.proc is None:
            raise FleetError(f"no supervised replica {rid!r}")
        r.proc.kill()

    # ------------------------------------------------------- observability
    def metrics(self) -> dict:
        with self._lock:
            rows = [r.row() for r in self._replicas.values()]
            counters = dict(self._counters)
        ready = [r for r in rows if r["state"] == READY]
        lookups = sum(r["steering"].get("prefix_lookups", 0) for r in ready)
        hits = sum(r["steering"].get("prefix_hit_rate", 0.0)
                   * r["steering"].get("prefix_lookups", 0) for r in ready)
        return {
            "policy": self.policy,
            "block_len": self.block_len,
            "replicas": {r["id"]: r for r in rows},
            "ready": len(ready),
            "aggregate_prefix_hit_rate": (round(hits / lookups, 4)
                                          if lookups else 0.0),
            "affinity": (self.affinity.stats()
                         if self.policy == "affinity" else None),
            **counters,
        }

    def close(self) -> None:
        """Stop polling and drain-stop every supervised replica."""
        self.stop()
        with self._lock:
            rs = list(self._replicas.values())
            self._replicas.clear()
        for r in rs:
            if r.proc is not None:
                try:
                    r.proc.terminate(drain=True, timeout=10.0)
                except Exception:   # pragma: no cover - defensive
                    pass
        self.client.close()


class _RetryableAdmission(Exception):
    def __init__(self, status: int, body: dict):
        super().__init__(f"retryable admission {status}")
        self.status = status
        self.body = body
