"""SLO-driven autoscaling: burn rate and queue depth in, replicas out.

The decision function is pure (state in, ``(delta, reason)`` out) so the
policy is unit-testable without processes or clocks; the
:class:`Autoscaler` thread is a thin actuator around it. Scale-out is
driven by the signals the serving stack already publishes — the SLO
watchdog's burn-rate breach list (telemetry/slo.py) and the fleet-wide
queue depth from ``/health`` steering — and is only as useful as cold
start is fast, which is why replicas share a persistent compilation
cache (coldstart.py): the replica the autoscaler adds mid-spike loads
its program set instead of compiling it. Scale-in is deliberately
timid (deeper cooldown, requires an idle fleet) and always drains:
``router.drain_replica`` stops admissions first and SIGTERMs only after
the replica's queue and slots are empty, so scale-in is invisible to
in-flight requests.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ...telemetry.tracecontext import event


@dataclass(frozen=True)
class AutoscalePolicy:
    min_replicas: int = 1
    max_replicas: int = 4
    # scale OUT when fleet queue depth exceeds queue_hi * ready replicas
    # (i.e. everyone's admission queue is backing up), or the SLO
    # watchdog reports a burn-rate breach
    queue_hi: int = 4
    # scale IN only when the fleet is idle: no queue and mean decode-slot
    # occupancy under this floor
    occupancy_lo: float = 0.25
    scale_out_cooldown_s: float = 5.0
    scale_in_cooldown_s: float = 30.0


def decide(policy: AutoscalePolicy, *, ready: int, starting: int,
           queue_depth: int, slot_occupancy: float, slo_breached: bool,
           now_s: float, last_out_s: float = float("-inf"),
           last_in_s: float = float("-inf")) -> Tuple[int, str]:
    """Pure scaling decision: ``(delta, reason)`` with delta in
    {-1, 0, +1}. One step per tick — the cooldowns make convergence a
    sequence of small observable moves, never a thundering herd."""
    total = ready + starting
    if total < policy.min_replicas:
        return 1, "below_min"
    out_cool = now_s - last_out_s < policy.scale_out_cooldown_s
    in_cool = now_s - last_in_s < policy.scale_in_cooldown_s
    if total < policy.max_replicas and not out_cool and starting == 0:
        if slo_breached:
            return 1, "slo_burn"
        if ready and queue_depth > policy.queue_hi * ready:
            return 1, "queue_depth"
    if (ready > policy.min_replicas and starting == 0 and not in_cool
            and not slo_breached and queue_depth == 0
            and slot_occupancy < policy.occupancy_lo):
        return -1, "idle"
    return 0, "steady"


class Autoscaler:
    """Actuator loop: scrape router state, decide, add or drain replicas.

        scaler = Autoscaler(router, spec_factory=make_replica,
                            watchdog=watchdog).start()

    ``spec_factory(index)`` returns an UNSTARTED
    :class:`~.replica.ReplicaProcess` for the index-th replica ever
    launched; the scaler starts it without blocking the loop (the
    router's health poller flips it READY when its ready file + /health
    land). ``watchdog`` is a telemetry/slo.py ``SLOWatchdog`` (or any
    object with ``check() -> {"breached": [...]}``); None means
    queue-depth-only scaling. For FLEET-level objectives — burn rate over
    every replica's latency buckets merged honestly, not one process's
    view — pass ``FleetCollector.make_watchdog(objectives)`` (see
    collector.py): same check() contract, fleet-wide data."""

    def __init__(self, router, spec_factory: Callable[[int], object], *,
                 policy: Optional[AutoscalePolicy] = None,
                 watchdog=None, period_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.router = router
        self.spec_factory = spec_factory
        self.policy = policy or AutoscalePolicy()
        self.watchdog = watchdog
        self.period_s = float(period_s)
        self.clock = clock
        self.launched = 0           # monotonic index for spec_factory
        self.history: List[dict] = []
        self._last_out_s = float("-inf")
        self._last_in_s = float("-inf")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- loop
    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="fleet-autoscale")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.tick()
            except Exception as e:      # pragma: no cover - keep looping
                event("fleet.autoscale_error", error=str(e))

    # ------------------------------------------------------------- tick
    def observe(self) -> dict:
        """Fleet-wide signals for one decision, from router state."""
        rows = list(self.router.metrics()["replicas"].values())
        ready = [r for r in rows if r["state"] == "ready"]
        starting = [r for r in rows if r["state"] == "starting"]
        queue = sum(r["steering"].get("queue_depth", 0) for r in ready)
        occ = ([r["steering"].get("slot_occupancy", 0.0) for r in ready]
               or [0.0])
        breached: list = []
        if self.watchdog is not None:
            try:
                breached = self.watchdog.check().get("breached", [])
            except Exception:           # watchdog flake must not stall scaling
                breached = []
        return {"ready": len(ready), "starting": len(starting),
                "queue_depth": queue,
                "slot_occupancy": sum(occ) / len(occ),
                "slo_breached": bool(breached), "breached": breached,
                "ready_rows": ready}

    def tick(self) -> Tuple[int, str]:
        obs = self.observe()
        now = self.clock()
        delta, reason = decide(
            self.policy, ready=obs["ready"], starting=obs["starting"],
            queue_depth=obs["queue_depth"],
            slot_occupancy=obs["slot_occupancy"],
            slo_breached=obs["slo_breached"], now_s=now,
            last_out_s=self._last_out_s, last_in_s=self._last_in_s)
        if delta > 0:
            self._scale_out(now, reason, obs)
        elif delta < 0:
            self._scale_in(now, reason, obs)
        if delta:
            self.history.append({"delta": delta, "reason": reason,
                                 "ready": obs["ready"],
                                 "queue_depth": obs["queue_depth"],
                                 "breached": obs["breached"]})
        return delta, reason

    def _scale_out(self, now: float, reason: str, obs: dict) -> None:
        proc = self.spec_factory(self.launched)
        self.launched += 1
        self._last_out_s = now
        event("fleet.scale_out", reason=reason, replica=proc.id,
              queue_depth=obs["queue_depth"], breached=obs["breached"])
        # non-blocking: the router's poller flips it READY when warm
        self.router.add_process(proc, wait_ready=False)

    def _scale_in(self, now: float, reason: str, obs: dict) -> None:
        # drain the least-loaded ready replica; never the last min_replicas
        rows = sorted(obs["ready_rows"],
                      key=lambda r: (r["steering"].get("in_flight", 0)
                                     + r["steering"].get("queue_depth", 0),
                                     r["forwarded"]))
        if not rows:
            return
        rid = rows[0]["id"]
        self._last_in_s = now
        event("fleet.scale_in", reason=reason, replica=rid)
        threading.Thread(target=self.router.drain_replica, args=(rid,),
                         daemon=True,
                         name=f"fleet-drain-{rid}").start()
