"""Replica process supervisor + the replica child entrypoint.

One replica = one OS process running the EXISTING single-process serving
stack (``GenerationEngine`` behind ``ServingHTTPServer``) — the fleet
adds supervision around it, it does not fork the engine. The SparkNet
shape (arXiv 1511.06051): a coordinator supervising workers that each
hold warm state, coupled only through cheap periodic state publication
(here: the ``/health`` steering payload), never through tight RPC.

Child lifecycle (``python -m deeplearning4j_tpu.serving.fleet.replica``):
  1. configure the persistent compilation cache (coldstart.py) BEFORE
     any program is built, so warm-cache replicas load instead of
     compile;
  2. build the model from the spec — a checkpoint/model-zip ``path``
     (serving.registry.load_net) or a deterministic ``zoo`` constructor
     (same seed -> identical params in every replica, no weight
     distribution step needed for benches and tests);
  3. construct + AOT-warm the GenerationEngine, start the HTTP server;
  4. atomically write the ready file (port, pid, ready_s, cold-start
     accounting) — the supervisor's readiness gate, then double-gated by
     ``GET /health`` 200;
  5. wait for SIGTERM/SIGINT -> drain-then-stop (in-flight generations
     finish, new admissions see 503) -> exit 0.

The supervisor (:class:`ReplicaProcess`) owns spawn/readiness/terminate/
kill/restart and keeps each replica's stdout+stderr in a per-replica log
file for post-mortems.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional

from .coldstart import ENV_CACHE


def _default_spec_model() -> dict:
    """The tiny deterministic LM used when a spec omits ``model`` —
    bench/test scaffolding, not a production default."""
    return {"zoo": "transformer_lm",
            "kwargs": {"vocab_size": 64, "d_model": 16, "n_heads": 2,
                       "n_blocks": 1, "max_length": 64, "seed": 7,
                       "dtype": "float32", "token_input": True}}


class ReplicaProcess:
    """Spawn/supervise one replica child.

        proc = ReplicaProcess(spec, "r0", workdir=tmp).start()
        info = proc.wait_ready(timeout=60)     # {"port": ..., ...}
        ...
        proc.terminate(drain=True)             # SIGTERM -> drain -> exit

    ``spec`` keys: ``model`` ({"path": ...} or {"zoo": name,
    "kwargs": {...}}), ``model_name``, ``generation`` (GenerationConfig
    kwargs), ``host``, ``port``, ``compile_cache`` (falls back to the
    ``DL4J_TPU_COMPILE_CACHE`` env knob).
    """

    def __init__(self, spec: dict, replica_id: str, *, workdir: str,
                 env: Optional[dict] = None, python: str = sys.executable):
        self.spec = dict(spec)
        self.id = str(replica_id)
        self.spec.setdefault("replica_id", self.id)
        self.workdir = workdir
        self.env = dict(env or {})
        self.python = python
        self.proc: Optional[subprocess.Popen] = None
        self.ready_info: Optional[dict] = None
        self._log_file = None
        os.makedirs(workdir, exist_ok=True)
        self.spec_path = os.path.join(workdir, f"replica-{self.id}.spec.json")
        self.ready_path = os.path.join(workdir,
                                       f"replica-{self.id}.ready.json")
        self.log_path = os.path.join(workdir, f"replica-{self.id}.log")
        # crash-durable black box: the child periodically spills its trace
        # ring + raw metrics here (telemetry/spool.py); survives SIGKILL
        self.spool_path = os.path.join(workdir,
                                       f"replica-{self.id}.spool.json")
        self.spec.setdefault("spool_path", self.spool_path)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ReplicaProcess":
        if self.alive:
            raise RuntimeError(f"replica {self.id} already running")
        self.ready_info = None
        try:
            os.unlink(self.ready_path)
        except FileNotFoundError:
            pass
        with open(self.spec_path, "w") as f:
            json.dump(self.spec, f)
        env = {**os.environ, **self.env}
        # chaos dumps from the child must land beside its log, never in
        # the caller's working tree (the conftest discipline, fleet-wide)
        env.setdefault("DL4J_TPU_FLIGHTREC_DIR",
                       os.path.join(self.workdir, "flightrec"))
        self._log_file = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [self.python, "-m", "deeplearning4j_tpu.serving.fleet.replica",
             "--spec", self.spec_path, "--ready-file", self.ready_path],
            stdout=self._log_file, stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
        return self

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    @property
    def base_url(self) -> Optional[str]:
        if self.ready_info is None:
            return None
        host = self.spec.get("host", "127.0.0.1")
        return f"http://{host}:{self.ready_info['port']}"

    def wait_ready(self, timeout: float = 120.0, *, client=None,
                   poll_s: float = 0.05) -> dict:
        """Block until the child wrote its ready file AND answers
        ``GET /health`` 200. Raises RuntimeError (with the log tail) if
        the child exits first, TimeoutError on the deadline."""
        deadline = time.monotonic() + timeout
        while self.ready_info is None:
            if not self.alive:
                raise RuntimeError(
                    f"replica {self.id} exited rc={self.proc.returncode} "
                    f"before ready:\n{self.log_tail()}")
            try:
                with open(self.ready_path) as f:
                    self.ready_info = json.load(f)
            except (FileNotFoundError, json.JSONDecodeError):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica {self.id} not ready after {timeout}s:\n"
                        f"{self.log_tail()}") from None
                time.sleep(poll_s)
        # health gate: the listener is up, now require a 200 (not 503)
        from ...util.httpjson import HTTPClient
        own = client is None
        client = client or HTTPClient(max_per_host=1, timeout=5.0)
        try:
            while True:
                try:
                    status, _ = client.request_json(
                        "GET", self.base_url + "/health", timeout=2.0)
                    if status == 200:
                        return self.ready_info
                except Exception:
                    pass
                if not self.alive:
                    raise RuntimeError(
                        f"replica {self.id} died during health gate:\n"
                        f"{self.log_tail()}")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica {self.id} /health not 200 after "
                        f"{timeout}s:\n{self.log_tail()}")
                time.sleep(poll_s)
        finally:
            if own:
                client.close()

    def terminate(self, drain: bool = True, timeout: float = 15.0) -> int:
        """Drain-then-stop: SIGTERM (child drains engines, finishes
        in-flight generations, exits 0); SIGKILL only past ``timeout``.
        ``drain=False`` goes straight to SIGKILL. Returns the exit code."""
        if self.proc is None:
            return 0
        if self.proc.poll() is None:
            if drain:
                self.proc.send_signal(signal.SIGTERM)
                try:
                    self.proc.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    self.proc.kill()
            else:
                self.proc.kill()
            self.proc.wait()
        self._close_log()
        return self.proc.returncode

    def kill(self) -> None:
        """Chaos path: immediate SIGKILL, no drain, no goodbye — the
        router must notice on its own."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self._close_log()

    def restart(self) -> "ReplicaProcess":
        """Respawn after death (the supervisor's autorestart path)."""
        if self.alive:
            raise RuntimeError(f"replica {self.id} still alive")
        self._close_log()
        return self.start()

    def _close_log(self) -> None:
        if self._log_file is not None:
            try:
                self._log_file.close()
            except OSError:     # pragma: no cover - defensive
                pass
            self._log_file = None

    def log_tail(self, lines: int = 40) -> str:
        try:
            with open(self.log_path, "rb") as f:
                return b"\n".join(
                    f.read().splitlines()[-lines:]).decode("utf-8",
                                                           "replace")
        except OSError:
            return "<no log>"


# ----------------------------------------------------------- child process
def _build_net(model_spec: dict):
    if "path" in model_spec:
        from ..registry import load_net
        return load_net(model_spec["path"])
    if model_spec.get("zoo") == "transformer_lm":
        from ...models.zoo_extra import transformer_lm
        return transformer_lm(**model_spec.get("kwargs", {})).init()
    raise ValueError(f"unsupported model spec: {model_spec!r}")


def _tupled(cfg: dict) -> dict:
    """JSON round-trips tuples as lists; GenerationConfig wants tuples."""
    return {k: tuple(v) if isinstance(v, list) else v
            for k, v in cfg.items()}


def _child_main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="deeplearning4j_tpu fleet replica")
    ap.add_argument("--spec", required=True)
    ap.add_argument("--ready-file", required=True)
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)

    t0 = time.monotonic()
    # cache config must precede the first compile (see coldstart.py)
    from . import coldstart
    cache_dir = coldstart.configure_compile_cache(spec.get("compile_cache"))
    from ...telemetry import ensure_monitoring_hook
    ensure_monitoring_hook()

    from ..generation import GenerationEngine
    from ..http import ServingHTTPServer
    net = _build_net(spec.get("model") or _default_spec_model())
    engine = GenerationEngine(net,
                              model_name=spec.get("model_name", "lm"),
                              **_tupled(spec.get("generation", {})))

    replica_info = {"id": spec.get("replica_id"),
                    "pid": os.getpid(),
                    "ready_s": None,        # filled below, served forever
                    "coldstart": None}
    srv = ServingHTTPServer(
        generation=engine, host=spec.get("host", "127.0.0.1"),
        port=int(spec.get("port", 0)),
        health_extra=lambda: {"replica": replica_info})
    port = srv.start()
    replica_info["ready_s"] = round(time.monotonic() - t0, 3)
    replica_info["coldstart"] = coldstart.snapshot()
    ready = {"port": port, "pid": os.getpid(),
             "ready_s": replica_info["ready_s"],
             "cache_dir": cache_dir, **replica_info["coldstart"]}
    tmp = args.ready_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ready, f)
    os.replace(tmp, args.ready_file)    # atomic: never a half-read ready

    spool = None
    if spec.get("spool_path"):
        from ...telemetry.spool import TraceSpool
        spool = TraceSpool(spec["spool_path"],
                           replica_id=str(spec.get("replica_id") or ""),
                           period_s=float(spec.get("spool_period_s", 0.25))
                           ).start()

    import threading
    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    # Orphan watchdog: the child runs in its own session, so a SIGKILLed
    # supervisor delivers no signal here — without this check the replica
    # would serve nobody forever (the router died with the supervisor).
    # Reparenting (ppid change) is the orphan signal; drain and exit.
    parent = os.getppid()
    while not stop.wait(1.0):
        if os.getppid() != parent:
            break
    srv.stop(drain=True)                # finish in-flight, 503 the rest
    if spool is not None:
        spool.stop()                    # final spill covers the drain tail
    return 0


if __name__ == "__main__":      # pragma: no cover - subprocess entry
    sys.exit(_child_main())
