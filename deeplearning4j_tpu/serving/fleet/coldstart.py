"""Cold start as load-not-compile: the persistent compilation cache knob.

Scaling out on an SLO burn-rate signal only works if a fresh replica is
serving before the traffic spike is over — and a generation replica's
startup cost is dominated by compiling its prefill/decode/replay/COW
program set, not by loading weights. The JAX persistent compilation
cache turns that compile storm into file loads: every replica points at
one shared cache directory (the ``DL4J_TPU_COMPILE_CACHE`` env knob, or
an explicit path in the replica spec), the FIRST replica to see a
program pays the compile and writes the executable, and every later
replica — including one spawned mid-spike by the autoscaler — warms the
identical program set in checkpoint-load time.

Accounting: on this jax line the ``backend_compile_duration`` monitoring
event fires even when the executable was answered from the cache, so
"did this replica compile anything NEW" is ``xla_compile_count() -
xla_cache_hit_count()`` — :func:`fresh_compile_count`. The fleet bench's
cold-start acceptance pins that a warm-cache replica reaches ready with
ZERO fresh compiles for already-seen programs.
"""
from __future__ import annotations

import os
from typing import Optional

ENV_CACHE = "DL4J_TPU_COMPILE_CACHE"

_configured_dir: Optional[str] = None


def configure_compile_cache(path: Optional[str] = None, *,
                            min_compile_time_s: float = 0.0
                            ) -> Optional[str]:
    """Point jax's persistent compilation cache at ``path`` (or the
    ``DL4J_TPU_COMPILE_CACHE`` env var when ``path`` is None). ``"0"`` or
    empty disables. ``min_compile_time_s=0.0`` caches EVERY program —
    tiny CPU-tier executables included, which is what makes the
    cold-start pin testable off-TPU; a production TPU fleet can raise it
    to skip sub-second compiles. Returns the configured directory (also
    recorded for :func:`snapshot`), or None when disabled. Idempotent;
    call it BEFORE the first compile or already-compiled programs stay
    uncached."""
    global _configured_dir
    cache = os.environ.get(ENV_CACHE, "") if path is None else path
    if not cache or cache == "0":
        return None
    os.makedirs(cache, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_s))
    _configured_dir = cache
    return cache


def configured_cache_dir() -> Optional[str]:
    return _configured_dir


def fresh_compile_count() -> int:
    """Programs this process actually compiled (cache hits excluded)."""
    from ...telemetry import xla_cache_hit_count, xla_compile_count
    return max(0, xla_compile_count() - xla_cache_hit_count())


def snapshot() -> dict:
    """The cold-start accounting block replicas publish at ready time."""
    from ...telemetry import xla_cache_hit_count, xla_compile_count
    compiles = xla_compile_count()
    hits = xla_cache_hit_count()
    return {"cache_dir": _configured_dir,
            "compiles": compiles,
            "cache_hits": hits,
            "fresh_compiles": max(0, compiles - hits)}
