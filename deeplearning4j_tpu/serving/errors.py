"""Serving error taxonomy — each maps to ONE HTTP status code (http.py), so
admission decisions made deep in the batcher surface as the right wire
response instead of the legacy blanket 400."""
from __future__ import annotations


class ServingError(RuntimeError):
    """Base class; http.py maps subclasses to status codes."""


class QueueFullError(ServingError):
    """Admission refused: the model's bounded queue is at capacity (429)."""


class DrainingError(ServingError):
    """Admission refused: the engine/model is draining or stopped (503)."""


class DeadlineExceededError(ServingError):
    """The caller's deadline expired before a result was ready (504)."""


class UnknownModelError(ServingError):
    """No model registered under the requested name (404)."""


class ShapeMismatchError(ServingError):
    """Request feature shape/dtype doesn't match the model's warmed
    programs (400) — the ladder is compiled for one trailing shape."""


class BlockPoolExhaustedError(QueueFullError):
    """Generation admission refused: the paged KV-cache block pool cannot
    supply the blocks the request needs (429, like its parent).
    ``retryable=False`` marks the PERMANENT flavor — the request needs more
    blocks than the pool has at all, so retrying can never help and
    http.py omits the ``retry_after_ms`` hint."""

    def __init__(self, *args, retryable: bool = True):
        super().__init__(*args)
        self.retryable = retryable


class GenerationClosedError(ServingError):
    """The generation was terminated before completing (shutdown or
    internal failure); streaming callers see the stream close with this
    as the error, blocking callers get it raised (500/503)."""
