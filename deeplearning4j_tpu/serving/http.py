"""HTTP surface for the inference + generation engines.

Replaces the legacy ModelServingServer's predict/health pair with a full
serving API and REAL status codes (the legacy route collapsed every failure
to 400):

  POST /predict            {"features": [[...]], "timeout_ms"?: int}
  POST /predict/<model>    same, routed to a named model
  POST /generate           {"prompt": [ids], "max_tokens"?, "temperature"?,
                            "top_k"?, "stop"?: [ids], "timeout_ms"?,
                            "stream"?: bool (default true),
                            "speculative"?: bool (default true — opt a
                            request out of draft-verify decode on a
                            speculating model)}
                           stream=true -> chunked NDJSON: one
                           {"token": id} line per generated token, then a
                           {"done": true, "reason": ..., "tokens": n}
                           terminator (also on mid-stream deadline/shutdown
                           — the stream always ends cleanly, clients never
                           hang). stream=false -> single JSON body.
  POST /generate/<model>   same, routed to a named generation model
  GET  /health             200 ok / 503 draining, queue depths per model
  GET  /metrics            per-model serving metrics (+ "generation" key
                           when a generation engine is attached)
  GET  /models             registry listing (version, buckets, warm state)
  GET  /debug/trace        the registry's trace ring as NDJSON, one event
                           per line with its ``seq`` stamp;
                           ``?since_seq=N`` returns only events past the
                           cursor (the fleet collector's incremental pull)
  GET  /debug/metrics      raw mergeable metrics: counters, gauges, and
                           histograms as cumulative ``le`` buckets —
                           the fleet-aggregation wire format
  POST /reload             {"model": name, "path": zip-or-checkpoint-dir}
                           -> zero-downtime hot-swap (forward-serving OR
                           generation model), returns new version
  POST /debug/flightrec    explicit flight-recorder dump (black box)
  POST /debug/memprof      live memory profile: top-K live-array groups
                           by (shape, dtype, owner) + per-device totals
                           ({"top_k": n} optional body)

Status mapping: malformed payload -> 400, unknown model -> 404, queue full
OR KV block-pool exhaustion -> 429 (the latter with a retry_after_ms hint),
model/device-side failure -> 500, draining/stopped -> 503, deadline expired
before ANY output -> 504 (a deadline expiring mid-stream terminates the
stream with reason "deadline" instead — HTTP status is already on the
wire).
"""
from __future__ import annotations

import json
import threading
import zipfile
from typing import Optional

import numpy as np

from ..telemetry import get_registry
from ..telemetry.flightrec import get_flight_recorder
from ..telemetry.perf import perf_snapshot
from ..telemetry.slo import get_slo_watchdog
from ..telemetry.tracecontext import (event, new_trace_context,
                                      use_trace_context)
from ..util.retry import RetryError, RetryPolicy
from .engine import InferenceEngine
from .errors import (BlockPoolExhaustedError, DeadlineExceededError,
                     DrainingError, QueueFullError, ShapeMismatchError,
                     UnknownModelError)

# /reload checkpoint loads ride shared storage that can flake mid-read
# (NFS hiccup, object-store gateway timeout, a checkpoint zip still
# landing): retry transient I/O with capped backoff before answering
# 500. A missing path is NOT transient — FileNotFoundError stays a fast
# 400 (util/retry's `retryable` filter, not a blanket except).
_RELOAD_RETRY = RetryPolicy(
    max_attempts=3, base_delay_s=0.05, max_delay_s=0.25,
    retryable=lambda e: (isinstance(e, (OSError, zipfile.BadZipFile))
                         and not isinstance(e, FileNotFoundError)))

_STATUS = ((ShapeMismatchError, 400), (UnknownModelError, 404),
           (QueueFullError, 429), (DrainingError, 503),
           (DeadlineExceededError, 504))


def status_for(exc: BaseException) -> int:
    for cls, code in _STATUS:
        if isinstance(exc, cls):
            return code
    return 500


def _error_body(exc: BaseException) -> dict:
    body = {"error": str(exc), "kind": type(exc).__name__}
    if isinstance(exc, BlockPoolExhaustedError) and \
            getattr(exc, "retryable", True):
        body["retry_after_ms"] = 100       # decode steps free blocks fast
    return body


class ServingHTTPServer:
    def __init__(self, engine: Optional[InferenceEngine] = None,
                 port: int = 0, host: str = "127.0.0.1", *,
                 generation=None, health_extra=None):
        if engine is None and generation is None:
            raise ValueError("need an InferenceEngine and/or a "
                             "GenerationEngine to serve")
        self.engine = engine
        self.generation = generation
        self.host = host
        self._port = port
        self._httpd = None
        self._thread = None
        # extra keys merged into every /health body — the fleet replica
        # wrapper publishes its identity + cold-start accounting there
        self._health_extra = health_extra

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> int:
        import http.server as hs

        from ..util.httpjson import read_json, write_json
        engine = self.engine
        generation = self.generation
        health_extra = self._health_extra

        class Handler(hs.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"    # required for chunked replies

            # ------------------------------------------------ request tracing
            # Every request runs under a TraceContext: the inbound
            # X-Trace-Id header when present (normalized hex), a fresh
            # 128-bit id otherwise — echoed back on EVERY response (the
            # end_headers override covers write_json AND the chunked
            # streaming path), and stamped by every span/event the
            # request touches on its way through admission, batching,
            # prefill and decode.
            _trace_ctx = None

            def _traced(self):
                ctx = new_trace_context(self.headers.get("X-Trace-Id"))
                self._trace_ctx = ctx
                return use_trace_context(ctx)

            def end_headers(self):
                ctx = self._trace_ctx
                if ctx is not None:
                    self.send_header("X-Trace-Id", ctx.trace_id)
                super().end_headers()

            def do_GET(self):       # noqa: N802
                try:
                    with self._traced():
                        self._route_get()
                finally:
                    # keep-alive: a later malformed request on this
                    # connection answered via send_error (outside any
                    # _traced scope) must not echo THIS request's id
                    self._trace_ctx = None

            def do_POST(self):      # noqa: N802
                try:
                    with self._traced():
                        event("http.request", method="POST",
                              route=self.path)
                        self._route_post()
                finally:
                    self._trace_ctx = None

            def _route_get(self):
                # query strings only exist on the /debug/trace cursor
                # route; every exact-match route below keeps seeing the
                # bare path
                path, _, query = self.path.partition("?")
                if path == "/debug/trace":
                    self._debug_trace(query)
                    return
                if path == "/debug/metrics":
                    # mergeable raw metrics (cumulative le buckets, not
                    # percentiles) — what the fleet collector aggregates
                    write_json(self, 200, get_registry().raw_metrics())
                    return
                if self.path == "/health":
                    depths = engine.queue_depths() if engine else {}
                    gdepths = generation.queue_depths() if generation else {}
                    draining = bool(
                        (engine.draining if engine else False)
                        or (generation.draining if generation else False))
                    body = {"status": "draining" if draining else "ok",
                            "draining": draining,
                            "models": (engine.registry.names()
                                       if engine else []),
                            "queue_depth": depths,
                            "queue_depth_total": sum(depths.values())}
                    if generation is not None:
                        body["generation_models"] = generation.names()
                        body["generation_queue_depth"] = gdepths
                        # steering payload (ISSUE 18): the fleet router's
                        # admission signals — prefix hit rate, slot
                        # occupancy, block-pool free fraction — WITHOUT
                        # the cost of a full /metrics scrape per route
                        body["steering"] = generation.steering()
                    if health_extra is not None:
                        try:
                            body.update(health_extra())
                        except Exception:   # pragma: no cover - defensive
                            pass
                    write_json(self, 503 if draining else 200, body)
                elif self.path == "/metrics":
                    body = engine.metrics() if engine else {}
                    if generation is not None:
                        body = dict(body)
                        body["generation"] = generation.metrics()
                    wd = get_slo_watchdog()
                    if wd is not None:
                        # fresh evaluation per scrape: burn rates move
                        # with the counters, not with a stale snapshot
                        body = dict(body)
                        body["slo"] = wd.check()
                    # performance observability (telemetry/perf.py):
                    # per-program MFU/roofline table + step decomposition
                    # + memory profile, folded fresh per scrape (host
                    # arithmetic over already-recorded metrics)
                    if get_registry().enabled:
                        body = dict(body)
                        body["perf"] = perf_snapshot()
                    write_json(self, 200, body)
                elif self.path == "/metrics/prometheus":
                    wd = get_slo_watchdog()
                    if wd is not None:
                        wd.check()        # refresh slo.* gauges pre-dump
                    if get_registry().enabled:
                        # refresh perf.* gauges too: a deployment scraped
                        # only through this route would otherwise never
                        # fold the cost index (and a ThroughputSLO on a
                        # perf.*.mfu gauge would stay cold forever)
                        from ..telemetry.perf import get_cost_index
                        get_cost_index().fold(get_registry())
                    text = get_registry().to_prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(text)))
                    self.end_headers()
                    self.wfile.write(text)
                elif self.path == "/models":
                    body = engine.models() if engine else {}
                    if generation is not None:
                        body = dict(body)
                        body["generation"] = generation.models()
                    write_json(self, 200, body)
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

            def _route_post(self):
                if self.path == "/predict" or \
                        self.path.startswith("/predict/"):
                    self._predict()
                elif self.path == "/generate" or \
                        self.path.startswith("/generate/"):
                    self._generate()
                elif self.path == "/reload":
                    self._reload()
                elif self.path == "/debug/flightrec":
                    self._flightrec()
                elif self.path == "/debug/memprof":
                    self._memprof()
                else:
                    self._drain_body()
                    write_json(self, 404, {"error": f"no route {self.path}"})

            def _debug_trace(self, query: str):
                """Incremental trace-ring export: NDJSON, one Chrome-trace
                event per line, each carrying its registry ``seq`` stamp.
                ``?since_seq=N`` returns only events past the cursor —
                the fleet collector pulls deltas, never the full ring.
                ``X-Trace-Seq`` echoes the registry watermark so an empty
                body still advances the caller's cursor."""
                from urllib.parse import parse_qs
                reg = get_registry()
                try:
                    q = parse_qs(query)
                    since = int(q.get("since_seq", ["0"])[0])
                except (ValueError, TypeError):
                    write_json(self, 400,
                               {"error": "since_seq must be an integer"})
                    return
                events = reg.trace_events_since(since)
                body = "".join(json.dumps(e) + "\n"
                               for e in events).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("X-Trace-Seq", str(reg.last_seq))
                self.send_header("X-Trace-Dropped",
                                 str(reg.trace_dropped))
                self.end_headers()
                self.wfile.write(body)

            def _memprof(self):
                """Live memory profile (telemetry/memprof.py): top-K
                live-array groups by (shape, dtype, owner) + per-device
                totals. Optional JSON body: {"top_k": n}."""
                try:
                    info = read_json(self)
                    top_k = int(info.get("top_k", 10)) \
                        if isinstance(info, dict) else 10
                except Exception:
                    top_k = 10
                from ..telemetry import memprof
                try:
                    body = memprof.snapshot(top_k=max(1, min(top_k, 100)))
                except Exception as e:     # pragma: no cover - defensive
                    write_json(self, 500, {"error": str(e)})
                    return
                write_json(self, 200, body)

            def _flightrec(self):
                """Explicit black-box dump: the operator's 'what has this
                process been doing' button. Body (optional JSON) fields
                land in the dump's info block."""
                try:
                    info = read_json(self)
                    if not isinstance(info, dict):
                        info = {"note": info}
                except Exception:
                    info = {}
                # body keys must not collide with dump()'s own parameters
                # (a {"trigger": ...} or {"self": ...} body would
                # TypeError, {"force": false} would silently rate-limit)
                safe = {("body_" + k if k in ("self", "trigger", "force")
                         else str(k)): v for k, v in info.items()}
                path = get_flight_recorder().dump("http_debug", **safe)
                if path is None:
                    write_json(self, 503,
                               {"error": "flight recorder unavailable "
                                         "(telemetry disabled or dump "
                                         "failed)"})
                    return
                write_json(self, 200, {"dumped": path})

            def _drain_body(self):
                """Error paths that respond BEFORE parsing must still
                consume the request body: under HTTP/1.1 keep-alive an
                unread body would be parsed as the next request line."""
                n = int(self.headers.get("Content-Length", 0) or 0)
                if n:
                    try:
                        self.rfile.read(n)
                    except OSError:
                        self.close_connection = True

            def _predict(self):
                if engine is None:
                    self._drain_body()
                    write_json(self, 404,
                               {"error": "no forward-serving engine"})
                    return
                model: Optional[str] = None
                if self.path.startswith("/predict/"):
                    model = self.path[len("/predict/"):] or None
                try:                                   # parse phase -> 400
                    req = read_json(self)
                    feats = req["features"]
                    x = np.asarray(feats, np.dtype(engine.dtype))
                    timeout = req.get("timeout_ms")
                    timeout = None if timeout is None else float(timeout) / 1e3
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                try:                                   # serve phase -> taxonomy
                    out = engine.predict(x, model=model, timeout=timeout)
                except Exception as e:
                    write_json(self, status_for(e), _error_body(e))
                    return
                write_json(self, 200, {"output": np.asarray(out).tolist(),
                                       "model": model
                                       or engine.registry.default_name})

            # ------------------------------------------------- generation
            def _generate(self):
                if generation is None:
                    self._drain_body()
                    write_json(self, 404, {"error": "no generation engine"})
                    return
                model: Optional[str] = None
                if self.path.startswith("/generate/"):
                    model = self.path[len("/generate/"):] or None
                try:                                   # parse phase -> 400
                    req = read_json(self)
                    prompt = [int(t) for t in req["prompt"]]
                    max_tokens = req.get("max_tokens")
                    max_tokens = None if max_tokens is None \
                        else int(max_tokens)
                    temperature = float(req.get("temperature", 0.0))
                    top_k = int(req.get("top_k", 0))
                    stop = [int(t) for t in req.get("stop", [])]
                    timeout = req.get("timeout_ms")
                    timeout = None if timeout is None \
                        else float(timeout) / 1e3
                    stream = bool(req.get("stream", True))
                    speculative = bool(req.get("speculative", True))
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                try:                         # admission phase -> taxonomy
                    ts = generation.generate(
                        prompt, model=model, max_tokens=max_tokens,
                        temperature=temperature, top_k=top_k, stop=stop,
                        timeout=timeout, stream=True,
                        speculative=speculative)
                except Exception as e:
                    write_json(self, status_for(e), _error_body(e))
                    return
                if stream:
                    self._stream_tokens(ts)
                    return
                tokens, reason = ts.result(raise_on_error=False)
                if ts.error is not None and reason in ("error", "shutdown"):
                    # no bytes on the wire yet: the blocking flavor CAN
                    # report the failure properly (partial tokens included)
                    body = _error_body(ts.error)
                    body["tokens"] = tokens
                    body["reason"] = reason
                    write_json(self, status_for(ts.error), body)
                    return
                if reason == "deadline" and not tokens:
                    write_json(self, 504, _error_body(
                        ts.error or DeadlineExceededError(
                            "deadline expired before any output")))
                    return
                write_json(self, 200, {"tokens": tokens, "reason": reason,
                                       "model": model
                                       or generation.default_name})

            def _stream_tokens(self, ts):
                """Chunked NDJSON: flushed per token so callers see tokens
                as they decode; ALWAYS closed with a done line + chunk
                terminator (deadline/shutdown mid-stream included)."""
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj) -> bool:
                    data = (json.dumps(obj) + "\n").encode()
                    try:
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data + b"\r\n")
                        self.wfile.flush()
                        return True
                    except (BrokenPipeError, ConnectionResetError,
                            OSError):
                        return False
                alive = True
                for tok in ts:
                    if alive and not chunk({"token": int(tok)}):
                        alive = False
                        ts.cancel()     # client went away: free the slot
                done = {"done": True, "reason": ts.finish_reason,
                        "tokens": ts.emitted}
                if ts.error is not None:
                    done["error"] = str(ts.error)
                if alive:
                    chunk(done)
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError, OSError):
                        self.close_connection = True
                else:
                    self.close_connection = True

            def _reload(self):
                try:
                    req = read_json(self)
                    name = req["model"]
                    path = req["path"]
                    if not isinstance(name, str) or not isinstance(path, str):
                        raise TypeError("'model' and 'path' must be strings")
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                targets = []
                if engine is not None and name in engine.registry.names():
                    targets.append(("serving", engine))
                if generation is not None and name in generation.names():
                    targets.append(("generation", generation))
                if not targets:
                    write_json(self, 404,
                               {"error": f"no model {name!r} in any engine"})
                    return
                # load the checkpoint ONCE: both engines swap to the same
                # params object (no double deserialization, no skew if the
                # file changes between loads)
                try:
                    from .registry import load_net
                    net = _RELOAD_RETRY.call(load_net, path)
                except FileNotFoundError as e:
                    write_json(self, 400, {"error": str(e)})
                    return
                except RetryError as e:
                    write_json(self, 500,
                               {"error": f"failed to load {path!r} after "
                                         f"{e.attempts} attempts: {e.last}"})
                    return
                except Exception as e:
                    write_json(self, 500,
                               {"error": f"failed to load {path!r}: {e}"})
                    return
                # per-engine outcomes: a partial failure (swapped in one
                # engine, failed in the other) must be VISIBLE, not a bare
                # 500 that implies nothing changed
                versions, errors = {}, {}
                for label, t in targets:
                    try:
                        versions[label] = t.hot_swap(name, net)
                    except Exception as e:
                        errors[label] = e
                if errors:
                    write_json(self, 500,
                               {"model": name, "swapped": versions,
                                "failed": {k: str(v)
                                           for k, v in errors.items()},
                                "error": "; ".join(
                                    f"{k}: {v}" for k, v in errors.items()),
                                "status": ("partially swapped" if versions
                                           else "failed")})
                    return
                body = {"model": name, "status": "swapped",
                        "version": next(iter(versions.values()))}
                if len(versions) > 1:
                    body["versions"] = versions
                write_json(self, 200, body)

            def log_message(self, *a):
                pass

        self._httpd = hs.ThreadingHTTPServer((self.host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="serving-http")
        self._thread.start()
        return self.port

    def stop(self, drain: bool = True) -> None:
        """Drain-then-stop: new requests see 503 while queued work flushes,
        then the listener goes down."""
        if self.engine is not None:
            self.engine.stop(drain=drain)
        if self.generation is not None:
            self.generation.stop(drain=drain)
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
