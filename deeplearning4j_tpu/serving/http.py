"""HTTP surface for the inference engine.

Replaces the legacy ModelServingServer's predict/health pair with a full
serving API and REAL status codes (the legacy route collapsed every failure
to 400):

  POST /predict            {"features": [[...]], "timeout_ms"?: int}
  POST /predict/<model>    same, routed to a named model
  GET  /health             200 ok / 503 draining, queue depths per model
  GET  /metrics            per-model p50/p99, occupancy, waste, rejections
  GET  /models             registry listing (version, buckets, warm state)
  POST /reload             {"model": name, "path": zip-or-checkpoint-dir}
                           -> zero-downtime hot-swap, returns new version

Status mapping: malformed payload -> 400, unknown model -> 404, queue full
-> 429, model/device-side failure -> 500, draining/stopped -> 503,
deadline expired -> 504.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from .engine import InferenceEngine
from .errors import (DeadlineExceededError, DrainingError, QueueFullError,
                     ShapeMismatchError, UnknownModelError)

_STATUS = ((ShapeMismatchError, 400), (UnknownModelError, 404),
           (QueueFullError, 429), (DrainingError, 503),
           (DeadlineExceededError, 504))


def status_for(exc: BaseException) -> int:
    for cls, code in _STATUS:
        if isinstance(exc, cls):
            return code
    return 500


class ServingHTTPServer:
    def __init__(self, engine: InferenceEngine, port: int = 0,
                 host: str = "127.0.0.1"):
        self.engine = engine
        self.host = host
        self._port = port
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> int:
        import http.server as hs

        from ..util.httpjson import read_json, write_json
        engine = self.engine

        class Handler(hs.BaseHTTPRequestHandler):
            def do_GET(self):       # noqa: N802
                if self.path == "/health":
                    depths = engine.queue_depths()
                    body = {"status": ("draining" if engine.draining
                                       else "ok"),
                            "draining": engine.draining,
                            "models": engine.registry.names(),
                            "queue_depth": depths,
                            "queue_depth_total": sum(depths.values())}
                    write_json(self, 503 if engine.draining else 200, body)
                elif self.path == "/metrics":
                    write_json(self, 200, engine.metrics())
                elif self.path == "/models":
                    write_json(self, 200, engine.models())
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

            def do_POST(self):      # noqa: N802
                if self.path == "/predict" or \
                        self.path.startswith("/predict/"):
                    self._predict()
                elif self.path == "/reload":
                    self._reload()
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

            def _predict(self):
                model: Optional[str] = None
                if self.path.startswith("/predict/"):
                    model = self.path[len("/predict/"):] or None
                try:                                   # parse phase -> 400
                    req = read_json(self)
                    feats = req["features"]
                    x = np.asarray(feats, np.dtype(engine.dtype))
                    timeout = req.get("timeout_ms")
                    timeout = None if timeout is None else float(timeout) / 1e3
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                try:                                   # serve phase -> taxonomy
                    out = engine.predict(x, model=model, timeout=timeout)
                except Exception as e:
                    write_json(self, status_for(e),
                               {"error": str(e),
                                "kind": type(e).__name__})
                    return
                write_json(self, 200, {"output": np.asarray(out).tolist(),
                                       "model": model
                                       or engine.registry.default_name})

            def _reload(self):
                try:
                    req = read_json(self)
                    name = req["model"]
                    path = req["path"]
                    if not isinstance(name, str) or not isinstance(path, str):
                        raise TypeError("'model' and 'path' must be strings")
                except Exception as e:
                    write_json(self, 400, {"error": f"bad request: {e}"})
                    return
                try:
                    version = engine.hot_swap(name, path)
                except UnknownModelError as e:
                    write_json(self, 404, {"error": str(e)})
                except FileNotFoundError as e:
                    write_json(self, 400, {"error": str(e)})
                except Exception as e:
                    write_json(self, 500, {"error": str(e)})
                else:
                    write_json(self, 200, {"model": name, "version": version,
                                           "status": "swapped"})

            def log_message(self, *a):
                pass

        self._httpd = hs.ThreadingHTTPServer((self.host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="serving-http")
        self._thread.start()
        return self.port

    def stop(self, drain: bool = True) -> None:
        """Drain-then-stop: new requests see 503 while queued work flushes,
        then the listener goes down."""
        self.engine.stop(drain=drain)
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
