"""Bucket ladder: the fixed menu of batch shapes the engine ever runs.

Every request lands in the smallest ladder rung that fits the merged rows;
the pad-to-rung waste is the price of never compiling at request time
(shape-specialized programs, the cuDNN tradeoff — arXiv:1410.0759). The
ladder is the ONLY set of batch shapes that exist after warm-up, which is
what makes the zero-recompile guarantee checkable.
"""
from __future__ import annotations

from typing import Sequence, Tuple


class BucketLadder:
    """Sorted, deduplicated ladder of merged-batch sizes (e.g. 1/8/32/128)."""

    def __init__(self, buckets: Sequence[int] = (1, 8, 32, 128)):
        rungs = sorted(set(int(b) for b in buckets))
        if not rungs or rungs[0] < 1:
            raise ValueError(f"bucket ladder must be positive ints, got {buckets}")
        self.rungs: Tuple[int, ...] = tuple(rungs)

    @property
    def max(self) -> int:
        return self.rungs[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n. Callers must pre-chunk n > max (batcher does)."""
        if n < 1:
            raise ValueError("empty batch")
        for b in self.rungs:
            if n <= b:
                return b
        raise ValueError(f"{n} rows exceed the largest bucket {self.max}")

    def padding_waste(self, n: int) -> float:
        """Wasted fraction of the padded batch: (bucket - n) / bucket."""
        b = self.bucket_for(n)
        return (b - n) / b

    def validate_for_mesh(self, mesh, axis: str = "data") -> None:
        """Mesh-sharded serving lands the merged batch on the data axis, so
        every rung must divide evenly across it."""
        size = mesh.shape[axis]
        bad = [b for b in self.rungs if b % size]
        if bad:
            raise ValueError(
                f"buckets {bad} not divisible by mesh '{axis}' axis ({size})")

    def __repr__(self):
        return f"BucketLadder{self.rungs}"

    def __iter__(self):
        return iter(self.rungs)
