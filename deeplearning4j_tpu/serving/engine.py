"""InferenceEngine: the serving facade.

Routing, warm-up, hot-swap and lifecycle over the other serving modules.
The dispatch path is: HTTP/caller -> engine.predict -> per-model
ShapeBucketedBatcher (coalesce + pad to a ladder bucket) -> the model's
ACTIVE ProgramSet (AOT-compiled executable for that bucket). The active
set is read per dispatched batch, so a hot-swap is one atomic reference
assignment: in-flight batches finish on the old params, the next batch
runs the new ones — zero downtime, zero failed requests.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from .batcher import ShapeBucketedBatcher
from .buckets import BucketLadder
from .errors import DrainingError
from .metrics import ServingMetrics, xla_compile_count
from .programs import ProgramSet
from .registry import ModelRegistry, _Entry, load_net


class InferenceEngine:
    def __init__(self, net=None, *, model_name: str = "default",
                 feature_shape: Optional[Tuple[int, ...]] = None,
                 buckets: Sequence[int] = (1, 8, 32, 128),
                 dtype="float32", mesh=None, data_axis: str = "data",
                 batch_window_ms: float = 2.0, queue_limit: int = 256,
                 default_timeout_s: float = 30.0, warm: bool = True,
                 forward_fn: Optional[Callable] = None):
        self.registry = ModelRegistry()
        self.buckets = tuple(buckets)
        self.dtype = dtype
        self.mesh = mesh
        self.data_axis = data_axis
        self.batch_window_ms = batch_window_ms
        self.queue_limit = queue_limit
        self.default_timeout_s = default_timeout_s
        self._trace_count = 0          # trace-time hook: ++ per program trace
        self._draining = False
        self._lock = threading.Lock()
        if net is not None:
            if feature_shape is None:
                raise ValueError("feature_shape is required to warm the "
                                 "bucket programs ahead of traffic")
            self.add_model(model_name, net, feature_shape=feature_shape,
                           warm=warm, forward_fn=forward_fn)

    # ----------------------------------------------------------------- models
    def add_model(self, name: str, net, *, feature_shape: Tuple[int, ...],
                  buckets: Optional[Sequence[int]] = None, dtype=None,
                  warm: bool = True, default: bool = False,
                  forward_fn: Optional[Callable] = None) -> "_Entry":
        if name in self.registry.names():   # fail BEFORE warming/threading
            raise ValueError(f"model '{name}' already registered "
                             "(use hot_swap to replace)")
        ladder = BucketLadder(buckets or self.buckets)
        metrics = ServingMetrics(name=name)
        ps = ProgramSet(net, feature_shape=feature_shape, ladder=ladder,
                        dtype=dtype or self.dtype, mesh=self.mesh,
                        data_axis=self.data_axis, forward_fn=forward_fn,
                        trace_hook=self._on_trace,
                        cost_path=f"serving.{name}")
        if warm:
            ps.warm()

        entry_box = {}

        def runner(padded: np.ndarray) -> np.ndarray:
            # resolve the ACTIVE set per batch — the hot-swap seam
            return entry_box["entry"].active.run(padded)

        batcher = ShapeBucketedBatcher(
            runner, ladder, feature_shape, dtype=np.dtype(dtype or self.dtype),
            queue_limit=self.queue_limit,
            batch_window_ms=self.batch_window_ms,
            default_timeout_s=self.default_timeout_s,
            metrics=metrics, name=name)
        entry = _Entry(name, ps, batcher, metrics)
        entry_box["entry"] = entry
        try:
            self.registry.add(entry, default=default)
        except ValueError:          # registration race: don't leak the thread
            batcher.stop(drain=False)
            raise
        return entry

    def remove_model(self, name: str) -> None:
        entry = self.registry.remove(name)
        entry.batcher.stop(drain=True)

    # ---------------------------------------------------------------- serving
    def predict(self, x, *, model: Optional[str] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        if self._draining:
            raise DrainingError("engine is draining")
        entry = self.registry.get(model)
        return entry.batcher.submit(x, timeout=timeout)

    def warm_up(self, model: Optional[str] = None) -> None:
        entry = self.registry.get(model)
        if not entry.active.warmed:
            entry.active.warm()

    # --------------------------------------------------------------- hot-swap
    def hot_swap(self, name: str, net_or_path) -> int:
        """Replace model ``name`` with zero downtime. A checkpoint path /
        directory is restored first; same-architecture swaps reuse the
        already-compiled executables (pure reference assignment), changed
        architectures warm a FULL new program set before the swap — either
        way no request ever waits on a compile or fails.
        Returns the new version number."""
        entry = self.registry.get(name)       # unknown name fails fast,
        net = load_net(net_or_path) if isinstance(net_or_path, str) \
            else net_or_path                  # before the checkpoint restore
        with entry.swap_lock:
            old = entry.active
            try:
                new_set = old.with_params_from(net)       # same shapes: free
            except ValueError:
                new_set = ProgramSet(
                    net, feature_shape=old.feature_shape, ladder=old.ladder,
                    dtype=old.dtype, mesh=old.mesh, data_axis=old.data_axis,
                    forward_fn=old._custom_fwd, trace_hook=self._on_trace,
                    cost_path=old.cost_path).warm()       # warm BEFORE swap
            entry.active = new_set                        # atomic cutover
            entry.version += 1
            entry.metrics.record_swap()
            return entry.version

    def reload_from_checkpoint(self, name: str, path: str) -> int:
        return self.hot_swap(name, load_net(path))

    # ------------------------------------------------------------ observability
    def models(self) -> Dict[str, dict]:
        return {e.name: e.info() for e in self.registry.entries()}

    def metrics(self) -> Dict[str, dict]:
        return {e.name: e.metrics.snapshot()
                for e in self.registry.entries()}

    def publish_metrics(self, storage, session_id: str = "serving") -> None:
        """Push every model's snapshot into a StatsStorage backend (the
        ui/ listener-stats machinery)."""
        for e in self.registry.entries():
            e.metrics.publish(storage, session_id=session_id,
                              worker_id=e.name)

    @property
    def trace_count(self) -> int:
        """Traces of serving programs (warm-up compiles count; steady state
        must not move this)."""
        return self._trace_count

    def _on_trace(self):
        self._trace_count += 1

    @staticmethod
    def compile_count() -> int:
        """Process-wide XLA backend compiles (jax.monitoring) — the
        strongest zero-recompile assertion available."""
        return xla_compile_count()

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_depths(self) -> Dict[str, int]:
        return {e.name: e.batcher.queue_depth
                for e in self.registry.entries()}

    # ---------------------------------------------------------------- lifecycle
    def stop(self, drain: bool = True, timeout: float = 10.0) -> None:
        """drain=True: reject new work (503), flush every queued request,
        then stop; drain=False: reject new work and FAIL queued requests
        immediately. Either way no caller is left hanging."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + timeout
        for e in self.registry.entries():
            e.batcher.stop(drain=drain,
                           timeout=max(0.1, deadline - time.monotonic()))
