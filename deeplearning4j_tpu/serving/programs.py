"""AOT-warmed forward programs: one compiled XLA executable per bucket.

Warm-up lowers and compiles ``jax.jit(forward)`` for every rung of the
bucket ladder up front (``jit(...).lower(...).compile()``), so the serving
hot path only ever CALLS executables — it never traces. Params/state are
arguments, not constants, which is what makes hot-swap free: a new model
with identical param/state shapes reuses the same executables and the swap
is a reference assignment; a changed architecture warms a fresh set BEFORE
the swap, so serving never waits on a compile.

Mesh mode: the merged batch lands sharded on the 'data' axis, params/state
replicated — the same mapping parallel/inference.py documents (batching and
multi-device dispatch are the same operation on TPU).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .buckets import BucketLadder


def _tree_signature(tree) -> Tuple:
    """Hashable (structure, shapes, dtypes) signature of a pytree — two
    models with equal signatures can share compiled executables."""
    leaves, treedef = jax.tree.flatten(tree)
    return (str(treedef),
            tuple((tuple(l.shape), str(jnp.asarray(l).dtype)) for l in leaves))


def _arch_key(net) -> Optional[str]:
    """Architecture identity beyond shapes: the config JSON minus the seed
    (same-shaped nets can still differ in activation/layer type — reusing
    the old executables for those would silently serve the wrong math;
    the seed is irrelevant to the traced forward, so seed-only differences
    keep the free-swap fast path)."""
    conf = getattr(net, "conf", None)
    if conf is None or not hasattr(conf, "to_json"):
        return None
    import json
    try:
        d = json.loads(conf.to_json())
        d.pop("seed", None)
        if isinstance(d.get("config"), dict):    # serde wraps the conf body
            d["config"].pop("seed", None)
        return json.dumps(d, sort_keys=True)
    except Exception:       # pragma: no cover - exotic conf: shape-only match
        return None


def default_forward(net) -> Callable:
    """Pure forward for MultiLayerNetwork-style nets: (params, state, x) ->
    output activations, inference mode."""
    def fwd(params, state, x):
        return net._output_pure(params, state, x, train=False)
    return fwd


class ProgramSet:
    """One model version's warmed executables + the params they close over.

    Immutable after ``warm()`` — the engine swaps whole ProgramSets
    atomically, and an in-flight batch keeps serving on the set it
    snapshotted at dispatch time.
    """

    def __init__(self, net, *, feature_shape: Tuple[int, ...],
                 ladder: BucketLadder, dtype="float32", mesh=None,
                 data_axis: str = "data",
                 forward_fn: Optional[Callable] = None,
                 trace_hook: Optional[Callable[[], None]] = None,
                 cost_path: Optional[str] = None):
        self.net = net
        self.cost_path = cost_path      # e.g. "serving.<model>" — enables
        # per-bucket cost-index registration at warm() (telemetry/perf.py)
        self.feature_shape = tuple(int(d) for d in feature_shape)
        self.ladder = ladder
        self.dtype = jnp.dtype(dtype)
        self.mesh = mesh
        self.data_axis = data_axis
        self._custom_fwd = forward_fn
        self._fwd = forward_fn or default_forward(net)
        self._trace_hook = trace_hook
        self._compiled: Dict[int, Any] = {}
        self._x_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            ladder.validate_for_mesh(mesh, data_axis)
            self._x_sharding = NamedSharding(mesh, P(data_axis))
            rep = NamedSharding(mesh, P())
            self.params = jax.device_put(net.params, rep)
            self.state = jax.device_put(net.state, rep)
        else:
            self.params = jax.tree.map(jnp.asarray, net.params)
            self.state = jax.tree.map(jnp.asarray, net.state)
        self.signature = (_tree_signature(self.params),
                          _tree_signature(self.state),
                          _arch_key(net),
                          self.feature_shape, str(self.dtype),
                          self.ladder.rungs, id(mesh))

    # ---------------------------------------------------------------- warm-up
    def warm(self) -> "ProgramSet":
        """Compile every rung. Called once at server start / before a swap
        that changed shapes — NEVER on the request path."""
        def traced(params, state, x):
            if self._trace_hook is not None:
                self._trace_hook()   # trace-time side effect: counts traces
            return self._fwd(params, state, x)

        for b in self.ladder:
            x_spec = jax.ShapeDtypeStruct((b,) + self.feature_shape,
                                          self.dtype)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                rep = NamedSharding(self.mesh, P())
                jitted = jax.jit(traced,
                                 in_shardings=(jax.tree.map(lambda _: rep,
                                                            self.params),
                                               jax.tree.map(lambda _: rep,
                                                            self.state),
                                               self._x_sharding))
            else:
                jitted = jax.jit(traced)
            self._compiled[b] = jitted.lower(
                self.params, self.state, x_spec).compile()
            self._register_cost(b)
            # touch the executable once so first real traffic doesn't pay
            # one-time dispatch setup either
            pad = np.zeros((b,) + self.feature_shape, self.dtype)
            np.asarray(self.run(pad))
        return self

    def _register_cost(self, b: int) -> None:
        """Cost-model accounting (telemetry/perf.py): register the AOT
        executable's cost analysis keyed by bucket, paired with the
        per-bucket dispatch-wall histogram the batcher observes — the
        perf fold turns the two into live ``perf.serving...`` MFU/
        roofline gauges. Never raises into warm-up."""
        if self.cost_path is None:
            return
        try:
            from ..telemetry import get_registry
            from ..telemetry.perf import accounting_enabled, get_cost_index
            if not (accounting_enabled() and get_registry().enabled):
                return
            get_cost_index().register(
                f"{self.cost_path}.bucket{b}", program=self._compiled[b],
                items_per_step=float(b),
                timing_metric=f"{self.cost_path}.bucket{b}.dispatch_ms")
        except Exception:       # pragma: no cover - defensive
            pass

    @property
    def warmed(self) -> bool:
        return set(self._compiled) == set(self.ladder.rungs)

    # ---------------------------------------------------------------- serving
    def run(self, padded: np.ndarray) -> np.ndarray:
        """Execute the pre-compiled program for ``padded.shape[0]`` rows.
        Host-side work is numpy-only (no jnp ops → nothing to compile)."""
        b = padded.shape[0]
        compiled = self._compiled.get(b)
        if compiled is None:
            from .errors import ServingError
            raise ServingError(
                f"no warmed program for bucket {b} (warmed: "
                f"{sorted(self._compiled)}) — call warm()/warm_up() before "
                "serving")
        x = padded
        if self._x_sharding is not None:
            x = jax.device_put(padded, self._x_sharding)
        return np.asarray(compiled(self.params, self.state, x))

    def with_params_from(self, net) -> "ProgramSet":
        """Hot-swap fast path: same architecture (equal signatures) →
        new ProgramSet sharing THIS set's executables, new params/state.
        Raises ValueError when shapes differ (caller warms a fresh set)."""
        new = ProgramSet(net, feature_shape=self.feature_shape,
                         ladder=self.ladder, dtype=self.dtype,
                         mesh=self.mesh, data_axis=self.data_axis,
                         forward_fn=self._custom_fwd,
                         trace_hook=self._trace_hook,
                         cost_path=self.cost_path)
        if new.signature != self.signature:
            raise ValueError("parameter/state shapes changed; full warm-up "
                             "required")
        new._compiled = self._compiled   # shared: programs are shape-keyed
        return new
