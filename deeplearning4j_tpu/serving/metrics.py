"""Serving observability: per-model latency/queue/occupancy/rejection
counters + a process-wide XLA compile counter.

The compile counter rides ``jax.monitoring`` (every backend compile emits a
``/jax/core/compile/backend_compile_duration`` event) — it counts REAL XLA
compilations anywhere in the process, so the zero-recompile-after-warm-up
guarantee is asserted against the runtime itself, not against bookkeeping
the engine could forget to do. Snapshots plug into the existing stats
machinery via ``publish()`` (ui/storage.py StatsStorage contract — the same
route StatsListener uses)."""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_compile_count = 0
_counter_installed = False
_install_lock = threading.Lock()


def _install_compile_counter() -> None:
    global _counter_installed
    with _install_lock:
        if _counter_installed:
            return
        import jax.monitoring

        def _on_duration(name, secs, **kw):
            global _compile_count
            if name == _BACKEND_COMPILE_EVENT:
                _compile_count += 1

        # jax 0.4.x has register but no unregister for a single listener;
        # one increment-only listener installed once per process is inert.
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _counter_installed = True


def xla_compile_count() -> int:
    """Process-wide XLA backend-compile count. Take a snapshot after
    warm-up; any later increase means something recompiled."""
    _install_compile_counter()
    return _compile_count


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class ServingMetrics:
    """Per-model counters. Latency percentiles come from a bounded ring of
    the most recent ``window`` observations (enough for stable p99 at
    serving rates without unbounded memory)."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._lat_ms = deque(maxlen=window)
        self._qwait_ms = deque(maxlen=window)
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.batch_rows = 0
        self.padded_rows = 0
        self.per_bucket: Dict[int, int] = {}
        self.rejected: Dict[str, int] = {"full": 0, "draining": 0,
                                         "deadline": 0, "error": 0}
        self.swaps = 0
        self._t0 = time.monotonic()

    # ------------------------------------------------------------- recording
    def record_request(self, latency_ms: float, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows
            self._lat_ms.append(latency_ms)

    def record_queue_wait(self, queue_wait_ms: float) -> None:
        with self._lock:
            self._qwait_ms.append(queue_wait_ms)

    def record_batch(self, bucket: int, rows: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += rows
            self.padded_rows += bucket - rows
            self.per_bucket[bucket] = self.per_bucket.get(bucket, 0) + 1

    def record_rejection(self, kind: str) -> None:
        with self._lock:
            self.rejected[kind] = self.rejected.get(kind, 0) + 1

    def record_swap(self) -> None:
        with self._lock:
            self.swaps += 1

    # ------------------------------------------------------------- reporting
    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._lat_ms)
            qw = sorted(self._qwait_ms)
            dispatched = self.batch_rows + self.padded_rows
            occupancy = self.batch_rows / dispatched if dispatched else 0.0
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "latency_ms": {"p50": round(_percentile(lat, 0.50), 3),
                               "p99": round(_percentile(lat, 0.99), 3)},
                "queue_wait_ms": {"p50": round(_percentile(qw, 0.50), 3),
                                  "p99": round(_percentile(qw, 0.99), 3)},
                "batch_occupancy": round(occupancy, 4),
                "padding_waste": round(1.0 - occupancy, 4) if dispatched else 0.0,
                "per_bucket": dict(self.per_bucket),
                "rejected": dict(self.rejected),
                "hot_swaps": self.swaps,
                "uptime_s": round(time.monotonic() - self._t0, 1),
            }

    def publish(self, storage, session_id: str = "serving",
                worker_id: str = "default") -> dict:
        """Push a snapshot into a StatsStorage backend (ui/storage.py) — the
        serving analogue of StatsListener's training reports, so dashboards
        and the remote router see serving metrics through the same SPI."""
        snap = self.snapshot()
        storage.put_update(session_id, worker_id, snap)
        return snap
